"""Replica fleet: N frozen appliers behind a least-outstanding router.

One `PipelineService` batcher thread draining onto one `FrozenApplier`
(PR 5) saturates exactly one device; the "millions of users" direction
(ROADMAP item 1) needs every local device serving and a live model-swap
story.  This module is that layer:

- **Replica** — one :class:`~keystone_tpu.workflow.pipeline.FrozenApplier`
  pinned to one device.  Multi-replica pools clone the fitted pipeline
  per replica (pickle round-trip) and re-place every fitted device array
  with an explicit ``jax.device_put`` onto the replica's device, so each
  flush's computation lands where its parameters live (committed inputs
  pin XLA placement).  Each replica owns a worker thread with a private
  flush queue — while replica 0 computes, the batcher is already
  dispatching the next flush to replica 1 — and a per-replica
  :class:`~keystone_tpu.utils.guard.CircuitBreaker` (key
  ``<service>.replica.<i>``) charged by flush outcomes.
- **ReplicaPool** — the router.  ``dispatch`` picks the replica with the
  fewest outstanding flushes whose breaker admits work (a tripped
  replica is routed *around* until its half-open probe); when NO
  replica can serve — every slot quarantined/dead, every routable
  breaker open — it FAILS FAST with :class:`FleetUnavailable` (503 +
  derived ``Retry-After`` at HTTP, non-200 ``/healthz``) instead of
  force-routing into the dead pool; the supervisor's first successful
  restart (or a breaker's half-open probe) re-admits traffic.
- **ReplicaSupervisor** — the self-healing loop: dead workers (thread
  exited — e.g. an injected ``serve.worker`` crash) and wedged workers
  (flush held past the heartbeat budget) are restarted in place —
  re-clone + re-place from the pool's source, re-prime, rejoin the
  router with queued work transferred — and a slot that keeps dying is
  quarantined (``serve.replica_restarts`` / ``serve.quarantined``
  metrics, ``replica.restart`` ledger + recorder ops spans).
- **Blue/green swap** — ``stage()`` builds a full staged generation of
  replicas for a new model version on the same devices (the caller
  primes their padding-bucket programs while the old generation keeps
  serving); ``commit()`` swaps the routing list under the router lock —
  the swap pause IS that lock-held window, microseconds — and retires
  the old generation: each old worker drains its already-queued flushes
  before exiting, so queued requests never drop and in-flight requests
  resolve from the version that admitted them.

Observability: per-replica series share the label key ``replica``
(``serve.replica_flushes{replica=i}`` counter,
``serve.replica_outstanding{replica=i}`` / queue-share gauges) — one
metric name per quantity, fan-out via labels, which is the convention
``tools/lint.py`` now enforces.  Fault site ``serve.replica`` fires on
every live flush's replica apply (chaos: fail/stall one flush, trip a
breaker, exercise failover).

The single-replica default (``replicas=1``, no devices) wraps the given
pipeline's applier directly — no clone, no placement — so the PR-5
service behavior, program counts, and byte-identity pins are exactly
unchanged.

**Backends (ISSUE 15).**  ``backend="thread"`` (default) is everything
above.  ``backend="process"`` keeps this module's entire control plane
— the router, flow control, swap, supervision — and swaps each slot's
COMPUTE for a worker process (``serve/procfleet.py``): ``_build_one``
spawns a :class:`~keystone_tpu.serve.procfleet.ProcessReplica` from a
staged deploy-payload file (one per generation; workers load + prime
from it), generations move the payload at ``commit()``, and
``add_replica``/``remove_replica``/``set_window`` give the autoscaler
its levers.  A replica's worker-thread queue/claim semantics are
IDENTICAL in both backends — the parent thread blocks in the wire
protocol's ``recv`` (GIL released) while the child computes.
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from keystone_tpu.faults import fault_point
from keystone_tpu.obs import ledger, metrics
from keystone_tpu.utils import guard

logger = logging.getLogger(__name__)

#: replica breakers default to a short reset so a swapped-in healthy
#: model is probed within seconds, not the 30 s stage-retry default
DEFAULT_REPLICA_BREAKER_RESET = 5.0

#: how long a replica worker may go between heartbeats with a flush in
#: hand before the supervisor declares it wedged.  Generous by default:
#: priming at construction keeps in-band compiles rare, but a legit
#: apply longer than this budget WILL be treated as a wedge — size it
#: above the slowest honest flush.
DEFAULT_HEARTBEAT_SECONDS = 30.0


class FleetUnavailable(RuntimeError):
    """Every replica is quarantined, dead, or breaker-open: the fleet
    cannot serve right now.  Deliberately NOT an ``OSError`` — retrying
    into a dead pool is futile; recovery is the supervisor's restart or
    a breaker's half-open probe, both time-based.  The HTTP front end
    maps this to 503 with a ``Retry-After`` derived from
    :meth:`ReplicaPool.retry_after_unavailable`."""

    def __init__(self, message: str, retry_after_seconds: float = 1.0):
        super().__init__(message)
        self.retry_after_seconds = float(retry_after_seconds)


def _place_on_device(obj, device, _seen=None, _depth=0):
    """Recursively ``jax.device_put`` every device array reachable from
    ``obj`` onto ``device``; containers/attributes are updated in place
    where possible (the mirror of ``executor.block_on_arrays``'s walk —
    same depth cap, same "has block_until_ready" leaf test).  Returns
    the — possibly replaced — object.  ``_seen`` maps ``id(original)``
    to the placed result so an array referenced from two sites gets ONE
    placed copy at both — a set-based guard would re-place the first
    reference and leave the alias on the default device, and XLA
    rejects the resulting mixed placement on every flush."""
    import jax

    if _depth > 8 or obj is None or isinstance(obj, (str, bytes, int, float, bool)):
        return obj
    if _seen is None:
        _seen = {}
    if id(obj) in _seen:
        return _seen[id(obj)]
    if hasattr(obj, "block_until_ready"):
        placed = jax.device_put(obj, device)
        _seen[id(obj)] = placed
        return placed
    _seen[id(obj)] = obj  # containers: in-place update, cycle-safe
    if isinstance(obj, dict):
        for k in list(obj):
            obj[k] = _place_on_device(obj[k], device, _seen, _depth + 1)
        return obj
    if isinstance(obj, list):
        for i in range(len(obj)):
            obj[i] = _place_on_device(obj[i], device, _seen, _depth + 1)
        return obj
    if isinstance(obj, tuple):
        new = type(obj)(
            _place_on_device(v, device, _seen, _depth + 1) for v in obj
        )
        _seen[id(obj)] = new  # aliases of the tuple get the rebuilt one
        return new
    if hasattr(obj, "__dict__") and not isinstance(obj, type):
        for k, v in list(vars(obj).items()):
            nv = _place_on_device(v, device, _seen, _depth + 1)
            if nv is not v:
                setattr(obj, k, nv)
        return obj
    return obj


def _clone_and_place(pipeline, device):
    """An independent copy of a fitted pipeline with its fitted state
    committed to ``device`` (None = leave placement alone).  The clone
    is a pickle round-trip — the same serialization contract
    ``FittedPipeline.save``/``load`` already pin — so replicas share no
    transformer instances and therefore no per-instance jit caches:
    each replica compiles (and keeps hot) its own bucket programs
    against its own device.  Multi-tenant appliers expose ``graphs()``
    (one graph per tenant); plain pipelines/appliers hold one
    ``graph``."""
    clone = pickle.loads(pickle.dumps(pipeline))
    if device is not None:
        graphs_fn = getattr(clone, "graphs", None)
        graphs = graphs_fn() if callable(graphs_fn) else [clone.graph]
        seen: dict = {}
        for g in graphs:
            for op in g.operators.values():
                t = getattr(op, "transformer", None)
                if t is not None:
                    # ONE _seen map across tenant graphs: a featurizer
                    # instance shared by two tenants' graphs must get
                    # one placed copy at both sites
                    _place_on_device(t, device, _seen=seen)
    return clone


def _as_applier(pipeline):
    from keystone_tpu.workflow.pipeline import FrozenApplier

    # serve_applier marks duck-typed appliers (the multi-tenant
    # MultiTenantApplier) that already implement the frozen-apply
    # contract and must not be re-wrapped
    if isinstance(pipeline, FrozenApplier) or getattr(
        pipeline, "serve_applier", False
    ):
        return pipeline
    return FrozenApplier(pipeline)


_SENTINEL = object()


class Replica:
    """One frozen applier pinned to one device, plus its flush worker,
    queue, breaker, and counters.  Constructed by :class:`ReplicaPool`."""

    def __init__(
        self,
        index: int,
        applier,
        device=None,
        version: str = "v0",
        breaker: Optional[guard.CircuitBreaker] = None,
        pool_name: str = "serve",
        heartbeat_timeout: float = DEFAULT_HEARTBEAT_SECONDS,
    ):
        self.index = int(index)
        self.applier = applier
        self.device = device
        self.version = version
        self.pool_name = pool_name
        self.breaker = breaker or guard.CircuitBreaker(
            f"{pool_name}.replica.{index}",
            reset_timeout=DEFAULT_REPLICA_BREAKER_RESET,
        )
        #: dispatched-but-unfinished flushes (queued + in flight);
        #: guarded by the owning pool's lock — the router reads it
        self.outstanding = 0
        self.flushes = 0
        self.errors = 0
        #: supervision state: the worker beats once per loop iteration
        #: (and on enqueue, so a just-woken idle worker is never stale);
        #: ``inflight`` is the flush the worker currently holds —
        #: inflight + expired heartbeat = wedged.  ``dead`` marks a
        #: crashed worker (its thread exited without retirement);
        #: ``quarantined`` takes the replica out of routing entirely
        #: until a swap installs a fresh generation.
        self.heartbeat = guard.Heartbeat(heartbeat_timeout)
        self.inflight = None
        self.dead = False
        self.dead_error: Optional[str] = None
        self.quarantined = False
        #: how many times this SLOT has been restarted (carried onto
        #: replacements by the supervisor, so /statusz shows history)
        self.restarts = 0
        #: pool-installed callback for a crash-handler flush that can no
        #: longer be requeued here (the slot was drained/retired in the
        #: race window): the pool re-dispatches it onto a survivor so
        #: its riders never strand.  None = requeue-in-place only (the
        #: pre-process-fleet behavior; the threaded crash handler always
        #: wins the race because is_dead() needs the thread EXITED).
        self.on_stranded: Optional[Callable] = None
        self._q: list = []
        self._cond = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._retired = False

    def is_dead(self) -> bool:
        """A worker that exited WITHOUT being retired: either the crash
        handler flagged it, or the thread is gone (killed by an
        uncontained error)."""
        if self.dead:
            return True
        w = self._worker
        return (
            w is not None
            and w.ident is not None
            and not w.is_alive()
            and not self._retired
        )

    def routable(self) -> bool:
        """May the router consider this replica at all (breaker state
        aside)?  Quarantined, dead, and retired replicas are not
        eligible — their queues are not being drained."""
        return not (self.quarantined or self.dead or self._retired)

    # ------------------------------------------------------------ apply
    def apply(self, ds, deadline=None, prime: bool = False, **kw):
        """Run the frozen graph over one padded batch on THIS replica.
        Live flushes pass through the ``serve.replica`` fault site;
        priming warm-ups (``prime=True``) do not — chaos plans target
        traffic, not warm-up.  Extra keywords pass through to the
        applier (the multi-tenant path threads per-flush ``segments``
        and ``tenants`` this way)."""
        if not prime:
            fault_point("serve.replica", replica=self.index)
        return self.applier(ds, deadline=deadline, **kw)

    # ----------------------------------------------------------- worker
    def start(self, runner: Callable, obs_context=None) -> None:
        """Spawn the flush worker: pops queued items and hands them to
        ``runner(replica, batch)`` until the retire sentinel.
        ``obs_context``: a ``ledger.capture_context`` token restored at
        worker start, so the runner's ledger spans (``serve.batch`` and
        the executor stages under it) parent where the service was
        constructed instead of floating rootless on this thread."""

        def loop():
            ledger.restore_context(obs_context)
            try:
                while True:
                    with self._cond:
                        while not self._q:
                            self._cond.wait()
                        item = self._q.pop(0)
                    if item is _SENTINEL:
                        return
                    self.inflight = item
                    self.heartbeat.beat()
                    try:
                        # the worker-level fault site: a ``raise`` here is a
                        # WORKER CRASH (the thread dies; the in-hand flush is
                        # requeued at the front so the supervisor's
                        # replacement serves it — zero futures lost), and a
                        # ``hang`` wedges the worker (inflight set, heartbeat
                        # going stale) for the supervisor to detect.
                        fault_point("serve.worker", replica=self.index)
                        runner(self, item)
                    except BaseException as e:
                        # anything escaping here is a worker crash whose
                        # flush is safely re-runnable: either it was never
                        # claimed (the injected serve.worker fault, a
                        # pre-claim bug — the runner fails its own riders
                        # for ordinary post-claim escapes), or the runner
                        # un-claimed it before re-raising (a WorkerCrashed
                        # process death).  Front-requeue so the
                        # supervisor's replacement pops it next — UNLESS
                        # the slot was already drained/retired (a
                        # process-death sweep can win that race): then
                        # hand it to the pool's stranded re-dispatch so
                        # its riders never hang in a dead queue.  The
                        # thread exits; the supervisor detects the death
                        # via is_dead().
                        with self._cond:
                            if not self._retired or self.on_stranded is None:
                                # requeue in place: the normal path (a
                                # live slot — the replacement pops it),
                                # and the no-callback fallback for a
                                # retired slot (join() collects it for
                                # the caller to fail typed — never
                                # dropped on the floor)
                                self._q.insert(0, item)
                                item = None
                        self.inflight = None
                        self.dead_error = f"{type(e).__name__}: {e}"
                        self.dead = True
                        logger.error(
                            "replica %d worker crashed: %s",
                            self.index,
                            self.dead_error,
                        )
                        if item is not None and self.on_stranded is not None:
                            self.on_stranded(item)
                        return
                    finally:
                        self.inflight = None
                        self.heartbeat.beat()
            finally:
                self._on_worker_exit()

        self._worker = threading.Thread(
            target=loop,
            daemon=True,
            name=f"{self.pool_name}-replica{self.index}",
        )
        self._worker.start()

    def _on_worker_exit(self) -> None:
        """Worker-thread exit hook (sentinel drain or crash) — no-op
        for thread replicas; process replicas reap their child here."""

    def enqueue(self, batch) -> None:
        with self._cond:
            self._q.append(batch)
            # beat on enqueue: an idle worker's last beat may be long
            # ago — without this, work arriving after an idle stretch
            # reads as "outstanding + stale heartbeat" for the instant
            # before the worker wakes, a false wedge
            self.heartbeat.beat()
            self._cond.notify()

    def drain_queue(self) -> List:
        """Atomically take every queued (non-sentinel) flush, retire the
        worker (the sentinel makes a merely-wedged worker exit when it
        unsticks), and return the flushes for the caller to transfer or
        fail.  The supervisor's restart/quarantine path."""
        with self._cond:
            left = [b for b in self._q if b is not _SENTINEL]
            self._q.clear()
            self._retired = True
            self._q.append(_SENTINEL)
            self._cond.notify()
        return left

    def retire(self) -> None:
        """Queue the stop sentinel BEHIND any already-dispatched flushes
        — the worker drains them first, so a swap never drops work."""
        with self._cond:
            if not self._retired:
                self._retired = True
                self._q.append(_SENTINEL)
                self._cond.notify()

    def join(self, timeout: float) -> List:
        """Wait for the worker to exit; returns any batches left in the
        queue so the caller can fail their futures — a wedged worker's
        abandoned flushes, or flushes enqueued after retirement (the
        worker exits at the sentinel and never sees what lands behind
        it)."""
        if self._worker is not None:
            self._worker.join(timeout)
        with self._cond:
            left = [b for b in self._q if b is not _SENTINEL]
            self._q.clear()
        return left

    def status(self) -> dict:
        return {
            "replica": self.index,
            "device": str(self.device) if self.device is not None else None,
            "version": self.version,
            "breaker": self.breaker.state(),
            "outstanding": self.outstanding,
            "flushes": self.flushes,
            "errors": self.errors,
            "dead": self.is_dead(),
            "quarantined": self.quarantined,
            "restarts": self.restarts,
            "artifact_buckets": getattr(
                self.applier, "installed_buckets", lambda: 0
            )(),
        }


class ReplicaPool:
    """N replicas + the least-outstanding router + blue/green swap.

    ``pipeline``: a fitted pipeline (or ``FrozenApplier``).  With
    ``replicas=1`` and no explicit devices the pool wraps the given
    applier directly (the PR-5 single-device behavior, bit-for-bit);
    with more, each replica gets an independent clone of the fitted
    state ``jax.device_put`` onto its device (``devices=None`` cycles
    ``jax.local_devices()``)."""

    def __init__(
        self,
        pipeline,
        replicas: int = 1,
        devices: Optional[Sequence] = None,
        version: str = "v0",
        name: str = "serve",
        dispatch_window: int = 2,
        heartbeat_s: float = DEFAULT_HEARTBEAT_SECONDS,
        artifacts: Optional[dict] = None,
        backend: str = "thread",
        worker_opts: Optional[dict] = None,
        telemetry=None,
    ):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if dispatch_window < 1:
            raise ValueError(
                f"dispatch_window must be >= 1, got {dispatch_window}"
            )
        if backend not in ("thread", "process", "net"):
            raise ValueError(
                f"backend must be 'thread', 'process' or 'net', "
                f"got {backend!r}"
            )
        if backend in ("process", "net") and devices is not None:
            raise ValueError(
                f"backend={backend!r} owns device placement in the "
                f"workers; devices= applies to the thread backend only"
            )
        self.name = name
        #: replica backend: "thread" (the PR-8..14 in-process fleet,
        #: byte-for-byte), "process" (serve/procfleet.py — one worker
        #: process per replica over the shared-memory wire protocol) or
        #: "net" (serve/net.py — lease-fenced remote workers over TCP)
        self.backend = backend
        #: fleet-telemetry sink (serve/telemetry.FleetTelemetry, or
        #: None): every worker handle this pool ever constructs —
        #: initial build, staged generation, supervisor heal, scale-up
        #: — is attached to it, so shipped spans/metrics survive any
        #: replica churn.  Set BEFORE _build runs.
        self.telemetry = telemetry
        #: process-backend knobs (buckets/item_shape/dtype prime the
        #: worker at spawn; ready_timeout bounds spawn→ready)
        self._worker_opts = dict(worker_opts or {})
        self._lock = threading.Lock()
        #: the fitted pipeline (or applier) the CURRENT generation was
        #: built from — the supervisor re-clones replacement replicas
        #: from it, so an in-place restart serves the same version the
        #: crashed worker did.  stage()/commit() move it with the
        #: generation.
        self._source = pipeline
        self._staged_source = None
        #: the AOT artifact bundle for the current generation: every
        #: replica built from _source (initial build AND the
        #: supervisor's heal replacements) installs these pre-lowered
        #: bucket programs instead of re-tracing.  Moves with the
        #: generation at stage()/commit(), like _source.
        self._artifacts = artifacts
        self._staged_artifacts = None
        self._staged_artifacts_set = False
        #: deserialized AOT programs shared across replica builds AND
        #: supervisor heals, keyed (bundle signature, entry, device):
        #: the pre-lowered executable survives its worker's death, so a
        #: heal re-installs in microseconds instead of re-deserializing
        #: — compile time must not become recovery time.  Exported
        #: programs are immutable pure functions: sharing across
        #: generations is safe (unlike per-transformer jit caches,
        #: which is why replicas clone).
        self._artifact_programs: dict = {}
        self._heartbeat_s = float(heartbeat_s)
        #: sticky hint set when dispatch finds the whole fleet
        #: unavailable, cleared by the next availability recheck or a
        #: restart/commit — lets submit-side admission refuse fast
        #: (one attribute read) without polling breakers per request
        self._known_unavailable = False
        #: flow control between the batcher and the replica queues:
        #: ``dispatch`` blocks while EVERY replica already holds
        #: ``dispatch_window`` outstanding flushes (one computing + one
        #: queued behind it, by default).  Without this bound the
        #: batcher drains the admission queue into the replicas' private
        #: queues at line rate, the admission queue never fills, and
        #: overload bypasses ``Overloaded`` backpressure entirely —
        #: excess work queues invisibly and completes past its deadline
        #: instead of being rejected at submit.
        self._window = int(dispatch_window)
        self._cond = threading.Condition(self._lock)
        self._draining = False
        self._runner: Optional[Callable] = None
        self._obs_ctx = None
        self._on_stranded: Optional[Callable] = None
        self.version = version
        #: process backend: the staged deploy-payload files workers
        #: load (one per generation; swept with the pool)
        self._payload_dir: Optional[str] = None
        self._payload_seq = 0
        self._payload_path: Optional[str] = None
        self._staged_payload_path: Optional[str] = None
        #: net backend: the router's accept side + the machine registry
        #: scale-ups and heals spawn capacity from
        self._listener = None
        self._hostmap = None
        if backend in ("process", "net"):
            import tempfile

            self._payload_dir = tempfile.mkdtemp(prefix=f"ksw-{name}-")
            self._payload_path = self._stage_payload(pipeline, artifacts)
        if backend == "net":
            from keystone_tpu.serve import net as netmod
            from keystone_tpu.utils import hostmap as hostmap_mod

            opts = self._worker_opts
            self._listener = netmod.WorkerListener(
                host=opts.get("listen_host", "127.0.0.1"),
                port=int(opts.get("listen_port", 0)),
            )
            hosts = opts.get("hosts") or ["local"]
            self._hostmap = (
                hosts
                if isinstance(hosts, hostmap_mod.HostMap)
                else hostmap_mod.HostMap(hosts)
            )
        try:
            self.replicas: List[Replica] = self._build(
                pipeline, int(replicas), devices, version
            )
        except BaseException:
            # a failed build leaves no pool handle to close(): sweep the
            # staged payload dir here (spawned workers were already
            # reaped by _build_process_many's error path)
            if self._payload_dir is not None:
                import shutil

                shutil.rmtree(self._payload_dir, ignore_errors=True)
                self._payload_dir = None
            if self._listener is not None:
                self._listener.close()
            if self._hostmap is not None:
                self._hostmap.close()
            raise

    # ------------------------------------------------------------ build
    def _stage_payload(self, source, artifacts) -> str:
        """Write one generation's worker deploy payload (process
        backend): workers of the generation — initial, staged,
        scale-up, supervisor heals — all load this one file."""
        from keystone_tpu.serve.procfleet import stage_payload

        self._payload_seq += 1
        return stage_payload(
            self._payload_dir, self._payload_seq, source, artifacts
        )

    def _build_process_one(
        self, index: int, version: str, payload_path: Optional[str] = None
    ) -> Replica:
        """Spawn one worker process and wrap it in a routing slot.
        The worker loads + primes from the staged payload; the ready
        handshake bounds the wait."""
        from keystone_tpu.serve import procfleet

        opts = self._worker_opts
        t0 = time.monotonic()
        handle = procfleet.WorkerHandle(
            self.name,
            index,
            payload_path or self._payload_path,
            buckets=opts.get("buckets"),
            item_shape=opts.get("item_shape"),
            dtype=opts.get("dtype"),
            ready_timeout=opts.get(
                "ready_timeout", procfleet.DEFAULT_READY_TIMEOUT_S
            ),
            max_slab_bytes=opts.get(
                "max_slab_bytes", procfleet.wire.DEFAULT_MAX_SLAB_BYTES
            ),
        )
        metrics.observe(
            "serve.worker_spawn_seconds", time.monotonic() - t0
        )
        handle.attach_telemetry(self.telemetry)
        installed = int(handle.ready_info.get("artifact_buckets", 0))
        if installed:
            metrics.inc("serve.artifact_hits", installed)
        elif self._artifacts or self._staged_artifacts:
            metrics.inc("serve.artifact_fallbacks")
        return procfleet.ProcessReplica(
            index,
            handle,
            version=version,
            pool_name=self.name,
            heartbeat_timeout=self._heartbeat_s,
        )

    def _build_net_one(
        self,
        index: int,
        version: str,
        payload_path: Optional[str] = None,
        spawn_grace_s: Optional[float] = None,
        allow_overflow: bool = False,
    ) -> Replica:
        """Claim (or spawn) one REMOTE worker and deploy the staged
        generation onto it.  A pending registration — a fenced worker
        rejoining after a healed partition, or one an operator started
        by hand — is adopted within ``spawn_grace_s`` before the host
        map is asked for fresh capacity, so a heal prefers the worker
        that already holds this generation's built applier.
        ``allow_overflow``: exempt a spawn from the host map's slot
        budget — set on swap builds, whose workers coexist with the
        old generation's only until commit."""
        from keystone_tpu.serve import net as netmod
        from keystone_tpu.serve import procfleet

        opts = self._worker_opts
        path = payload_path or self._payload_path
        with open(path, "rb") as f:
            payload_bytes = f.read()
        grace = (
            float(opts.get("spawn_grace_s", 2.0))
            if spawn_grace_s is None
            else float(spawn_grace_s)
        )
        ready_timeout = float(
            opts.get("ready_timeout", procfleet.DEFAULT_READY_TIMEOUT_S)
        )
        t0 = time.monotonic()
        pending = self._listener.next_pending(timeout=grace)
        if pending is None:
            self._hostmap.spawn(
                self._listener.address, allow_overflow=allow_overflow
            )
            pending = self._listener.next_pending(timeout=ready_timeout)
            if pending is None:
                raise procfleet.WorkerSpawnError(
                    f"{self.name}: spawned worker for slot {index} never "
                    f"registered within {ready_timeout:.0f}s"
                )
        handle = netmod.deploy_worker(
            self.name,
            index,
            pending,
            payload_bytes,
            buckets=opts.get("buckets"),
            item_shape=opts.get("item_shape"),
            dtype=opts.get("dtype"),
            lease_s=float(opts.get("lease_s", netmod.DEFAULT_LEASE_S)),
            ready_timeout=ready_timeout,
            max_frame_bytes=int(
                opts.get(
                    "max_frame_bytes", procfleet.wire.DEFAULT_MAX_FRAME_BYTES
                )
            ),
        )
        metrics.observe("serve.worker_spawn_seconds", time.monotonic() - t0)
        handle.attach_telemetry(self.telemetry)
        installed = int(handle.ready_info.get("artifact_buckets", 0))
        if installed:
            metrics.inc("serve.artifact_hits", installed)
        elif self._artifacts or self._staged_artifacts:
            metrics.inc("serve.artifact_fallbacks")
        return netmod.NetReplica(
            index,
            handle,
            version=version,
            pool_name=self.name,
            heartbeat_timeout=self._heartbeat_s,
        )

    def _devices_for(self, n: int, devices) -> list:
        if self.backend in ("process", "net"):
            # workers own their devices; the router holds no placement
            return [None] * n
        if devices is not None:
            devices = list(devices)
            if not devices:
                raise ValueError("devices must be non-empty when given")
            return [devices[i % len(devices)] for i in range(n)]
        if n == 1:
            return [None]  # single replica: no placement, no clone
        import jax

        local = jax.local_devices()
        return [local[i % len(local)] for i in range(n)]

    def _build_one(
        self, source, index: int, device, version, n: int,
        force_clone: bool = False, artifacts=_SENTINEL,
        payload_path: Optional[str] = None,
    ) -> Replica:
        """One replica for slot ``index``: the direct-wrap fast path for
        a 1-replica deviceless pool, the clone+place path otherwise —
        shared by initial build, staged generations, and the
        supervisor's in-place restarts (which pass ``force_clone``: the
        replaced worker may still be EXECUTING inside the old applier,
        and two threads must never share transformer instances / jit
        caches).  ``artifacts`` (default: the pool's current bundle):
        AOT bucket programs installed into the fresh applier — a failed
        install NEVER fails the build; the replica compiles instead.
        Process backend: spawn a worker from ``payload_path`` (default:
        the live generation's staged payload) — cloning/placement/
        artifact install all happen inside the worker."""
        if self.backend == "process":
            return self._build_process_one(
                index, version, payload_path=payload_path
            )
        if self.backend == "net":
            return self._build_net_one(
                index, version, payload_path=payload_path
            )
        if device is None and n == 1 and not force_clone:
            applier = _as_applier(source)
        else:
            applier = _as_applier(_clone_and_place(source, device))
        if artifacts is _SENTINEL:
            artifacts = self._artifacts
        if artifacts:
            self._install_artifacts(applier, device, artifacts, source)
        return Replica(
            index,
            applier,
            device=device,
            version=version,
            pool_name=self.name,
            heartbeat_timeout=self._heartbeat_s,
        )

    @staticmethod
    def _source_signature(source) -> str:
        """The pipeline hash install verification compares against —
        computed from the pool's UNPLACED source (and cached on it), so
        N replicas and every heal share one weight read instead of
        re-hashing each clone."""
        from keystone_tpu.utils.hashing import pipeline_fingerprint
        from keystone_tpu.workflow.pipeline import FrozenApplier

        if isinstance(source, FrozenApplier):
            return source.fingerprint()
        return pipeline_fingerprint(source)

    def _install_artifacts(self, applier, device, artifacts, source) -> int:
        """Install AOT bucket programs into one fresh applier —
        artifact→compile degradation happens HERE: a corrupt/skewed
        bundle (or an injected ``serve.artifact_load`` fault) is
        counted and logged, and the replica serves via the compile
        ladder."""
        try:
            fault_point("serve.artifact_load")
            n = applier.install_artifacts(
                artifacts,
                device=device,
                signature=self._source_signature(source),
                program_cache=self._artifact_programs,
            )
        except Exception as e:
            metrics.inc("serve.artifact_fallbacks")
            logger.warning(
                "pool %r: artifact install failed (%s: %s); replica "
                "will compile",
                self.name,
                type(e).__name__,
                e,
            )
            return 0
        if n:
            metrics.inc("serve.artifact_hits", n)
        return n

    def _build_process_many(
        self, n: int, version: str, payload_path: Optional[str],
        swap: bool = False,
    ) -> List[Replica]:
        """Spawn a whole generation's workers CONCURRENTLY: each pays a
        fresh interpreter + runtime import + prime, and paying them
        serially would make construction and swap wall-clock ~n× one
        cold start.  On any spawn failure the already-ready workers are
        reaped before the error propagates — no half-born generation.
        The net backend rides the same fan-out with a zero adopt grace:
        an initial generation claims every already-registered volunteer
        first, then spawns the shortfall from the host map.  ``swap``:
        this generation REPLACES one that still occupies its host-map
        slots until commit, so its spawns carry the map's transient
        overflow allowance instead of failing on a budget sized to the
        steady-state fleet."""
        if self.backend == "net":
            def build(i: int) -> Replica:
                return self._build_net_one(
                    i, version, payload_path, spawn_grace_s=0.0,
                    allow_overflow=swap,
                )
        else:
            def build(i: int) -> Replica:
                return self._build_process_one(i, version, payload_path)

        if n == 1:
            return [build(0)]
        from concurrent.futures import ThreadPoolExecutor

        results: List[Optional[Replica]] = [None] * n
        errors: List[BaseException] = []

        def one(i: int) -> None:
            try:
                results[i] = build(i)
            except BaseException as e:
                errors.append(e)

        with ThreadPoolExecutor(max_workers=n) as ex:
            list(ex.map(one, range(n)))
        if errors:
            for r in results:
                if r is not None:
                    r.handle.shutdown()
            raise errors[0]
        return [r for r in results if r is not None]

    def _build(self, pipeline, n: int, devices, version) -> List[Replica]:
        if self.backend in ("process", "net"):
            return self._build_process_many(n, version, self._payload_path)
        devs = self._devices_for(n, devices)
        return [
            self._build_one(pipeline, i, dev, version, n)
            for i, dev in enumerate(devs)
        ]

    @property
    def size(self) -> int:
        return len(self.replicas)

    @property
    def has_artifacts(self) -> bool:
        """Was an AOT artifact bundle configured for the live
        generation?  (Install may still have fallen through per
        replica — the per-replica ``artifact_buckets`` status and the
        ``serve.artifact_*`` counters tell that story.)"""
        return self._artifacts is not None

    # ----------------------------------------------------------- router
    def start(
        self, runner: Callable, obs_context=None, on_stranded=None
    ) -> None:
        """Start every replica worker; ``runner(replica, batch)`` is the
        service's flush body (shed + pad + apply + resolve futures).
        ``obs_context`` (a ``ledger.capture_context`` token) is restored
        in every worker — including staged generations built later — so
        span parenting survives the replica threads.  ``on_stranded``:
        the service's re-dispatch for a crash-handler flush whose slot
        was drained in the race window (process backend)."""
        self._runner = runner
        self._obs_ctx = obs_context
        self._on_stranded = on_stranded
        for r in self.replicas:
            self._start_replica(r)

    def _start_replica(self, r: Replica) -> None:
        r.on_stranded = self._on_stranded
        r.start(self._runner, self._obs_ctx)

    def dispatch(self, batch) -> Replica:
        """Route one batch: least outstanding work first among
        ROUTABLE replicas (not quarantined/dead/retired), skipping
        replicas whose breaker refuses (``allow()`` on the chosen
        replica doubles as the half-open probe admission).

        When NO replica can serve — all quarantined/dead, or every
        routable breaker refusing — raises :class:`FleetUnavailable`
        instead of force-routing into the dead pool: the batcher fails
        the batch fast (503 at HTTP, with a derived ``Retry-After``),
        ``/healthz`` turns non-200, and traffic is re-admitted by the
        supervisor's first successful restart or a breaker's half-open
        probe (a fresh replacement carries a CLOSED breaker).

        Blocks while every routable replica is at the dispatch window —
        the backpressure that makes submit-side admission control real."""
        with self._cond:
            while True:
                if self._draining:
                    # shutdown: park the batch in SOME queue so close()
                    # collects it as abandoned and fails its futures —
                    # eligibility no longer matters
                    order = sorted(
                        self.replicas, key=lambda r: (r.outstanding, r.index)
                    )
                    if not order:
                        raise FleetUnavailable("replica pool is empty")
                    chosen = order[0]
                    break
                routable = [r for r in self.replicas if r.routable()]
                if not routable:
                    self._known_unavailable = True
                    raise FleetUnavailable(
                        f"fleet {self.name!r}: every replica is "
                        "quarantined or dead; awaiting supervisor restart",
                        retry_after_seconds=self._retry_after_for(routable),
                    )
                if min(r.outstanding for r in routable) >= self._window:
                    # timed: a commit/complete notify can land between
                    # the predicate and the wait on another generation
                    self._cond.wait(0.05)
                    continue
                order = sorted(routable, key=lambda r: (r.outstanding, r.index))
                chosen = None
                for r in order:
                    if r.breaker.allow():
                        chosen = r
                        break
                if chosen is None:
                    self._known_unavailable = True
                    eta = self._retry_after_for(routable)
                    raise FleetUnavailable(
                        f"fleet {self.name!r}: every replica breaker is "
                        f"open; next half-open probe in {eta:.1f}s",
                        retry_after_seconds=eta,
                    )
                break
            self._known_unavailable = False
            try:
                batch.primary = chosen.index
            except AttributeError:
                pass  # raw batches (tests) need no hedge bookkeeping
            chosen.outstanding += 1
            metrics.set_gauge(
                "serve.replica_outstanding",
                chosen.outstanding,
                replica=chosen.index,
            )
            # enqueue UNDER the router lock: commit() retires the old
            # generation only after taking this lock, so a batch routed
            # to an old replica is queued ahead of the retire sentinel
            # and the draining worker still serves it.  Enqueued outside
            # the lock, a concurrent swap could slot the sentinel first
            # and the batch's futures would hang forever (swap-retired
            # replicas are never join()ed).
            chosen.enqueue(batch)
        return chosen

    def hedge_dispatch(
        self,
        batch,
        exclude_index: Optional[int] = None,
        respect_window: bool = True,
    ):
        """Best-effort second dispatch of an already-routed batch onto a
        DIFFERENT replica (the hedging path): least-outstanding routable
        replica other than ``exclude_index`` with window headroom and an
        admitting breaker.  Never blocks and never raises — returns the
        chosen replica, or None when no second replica can take it (the
        hedge is simply skipped).  ``respect_window=False`` is the
        supervisor's redistribution mode: stranded work from a healed/
        quarantined slot lands on a survivor even when the survivors
        are momentarily at the dispatch window — extra queueing beats
        failing admitted requests a living fleet could serve."""
        with self._cond:
            if self._draining:
                return None
            cands = sorted(
                (
                    r
                    for r in self.replicas
                    if r.index != exclude_index
                    and r.routable()
                    and (not respect_window or r.outstanding < self._window)
                ),
                key=lambda r: (r.outstanding, r.index),
            )
            chosen = None
            for r in cands:
                if r.breaker.allow():
                    chosen = r
                    break
            if chosen is None:
                return None
            chosen.outstanding += 1
            metrics.set_gauge(
                "serve.replica_outstanding",
                chosen.outstanding,
                replica=chosen.index,
            )
            chosen.enqueue(batch)
        return chosen

    def dispatch_staged(self, batch, staged) -> Optional[Replica]:
        """Best-effort dispatch onto a STAGED generation (the canary
        split — serve/rollout.py): least-outstanding routable replica
        among ``staged`` with window headroom and an admitting breaker.
        Never blocks and never raises — returns None when no staged
        replica can take the batch (the caller serves it on the live
        generation instead; a canary must degrade to live traffic, not
        stall the batcher).  No ``serve.replica_outstanding`` gauge
        write: staged indices shadow live ones, and a staged enqueue
        overwriting the live replica's gauge would corrupt the series
        mid-canary (``complete`` skips the gauge for non-pool replicas
        for the same reason)."""
        with self._cond:
            if self._draining:
                return None
            cands = sorted(
                (
                    r
                    for r in staged
                    if r.routable() and r.outstanding < self._window
                ),
                key=lambda r: (r.outstanding, r.index),
            )
            chosen = None
            for r in cands:
                if r.breaker.allow():
                    chosen = r
                    break
            if chosen is None:
                return None
            try:
                batch.primary = chosen.index
            except AttributeError:
                pass
            chosen.outstanding += 1
            # enqueue UNDER the router lock: the same sentinel-ordering
            # discipline as dispatch() — abandon_staged retires under
            # this lock's shadow, so a canary flush is queued ahead of
            # the retire sentinel and the draining worker serves it
            chosen.enqueue(batch)
        return chosen

    def abandon_staged(self, staged, timeout: float = 30.0) -> list:
        """Retire a staged generation WITHOUT committing it (a canary
        rollback): clear the staged source/artifacts/payload captured
        by :meth:`stage`, retire every staged replica (the sentinel
        queues BEHIND already-routed canary flushes, which the worker
        drains and serves first), then join each worker and collect
        what it could not serve.  Returns the leftover flushes — the
        caller re-dispatches them onto the live generation (the
        scale-down discipline), so a rollback loses zero futures."""
        with self._cond:
            self._staged_source = None
            self._staged_artifacts = None
            self._staged_artifacts_set = False
            path, self._staged_payload_path = self._staged_payload_path, None
            for r in staged:
                # retire under the router lock: a concurrent
                # dispatch_staged enqueue cannot slot a flush behind
                # the sentinel (its futures would hang — swap-retired
                # replicas are never joined; abandoned ones are, below)
                r.retire()
        leftovers: list = []
        for r in staged:
            leftovers.extend(r.join(timeout))
        if path:
            try:
                import os

                os.unlink(path)
            except OSError:
                pass
        return leftovers

    # ------------------------------------------------------ availability
    def _compute_available(self) -> bool:
        with self._lock:
            replicas = list(self.replicas)
        # breaker.state() (not allow()): read-only resolution, so an
        # availability poll can never consume a half-open probe slot
        return any(
            r.routable() and r.breaker.state() != guard.OPEN for r in replicas
        )

    @property
    def max_slab_bytes(self) -> int:
        """The dispatch slab cap this fleet's workers were built with —
        the ingress sizes ITS admission pool to the same bound so a
        payload it accepts is never refused downstream.  Thread/device
        fleets (no slab wire at all) report the wire default."""
        cap = self._worker_opts.get("max_slab_bytes")
        if cap is not None:
            return int(cap)
        from keystone_tpu.serve import wire

        return int(wire.DEFAULT_MAX_SLAB_BYTES)

    def available(self) -> bool:
        """Can the fleet accept traffic right now?  One attribute read
        on the happy path (the per-submit admission check); the full
        breaker scan runs only while the router has flagged the fleet
        down (and clears the flag as soon as a breaker's half-open
        window or a restart re-admits)."""
        if not self._known_unavailable:
            return True
        if self._compute_available():
            self._known_unavailable = False
            return True
        return False

    def available_now(self) -> bool:
        """The FULL availability scan, flag refreshed from the result —
        for low-rate health surfaces (``/healthz``, ``/statusz``) that
        must see an all-dead fleet even before any dispatch tried (and
        whose verdict then primes the cheap admission check)."""
        ok = self._compute_available()
        self._known_unavailable = not ok
        return ok

    @staticmethod
    def _retry_after_for(replicas: List[Replica]) -> float:
        """The soonest half-open probe among these replicas' breakers,
        else 1 s (the supervisor restart path has no fixed ETA).  Takes
        no pool lock — callable from inside dispatch."""
        etas = [
            e
            for e in (r.breaker.seconds_until_probe() for r in replicas)
            if e > 0.0
        ]
        return min(etas) if etas else 1.0

    def retry_after_unavailable(self) -> float:
        """Seconds until the fleet could plausibly serve again — what an
        unavailable 503's ``Retry-After`` should carry."""
        with self._lock:
            replicas = [r for r in self.replicas if r.routable()]
        return self._retry_after_for(replicas)

    def complete(self, replica: Replica, ok: Optional[bool]) -> None:
        """Account one finished flush: outstanding/queue-share updates
        plus the breaker charge.  ``ok=True`` records a success (closes
        a half-open breaker), ``ok=False`` a failure (accumulates toward
        open), ``ok=None`` is NEUTRAL — nothing ran on the device
        (shed/cancelled-only flush), so it must neither pass a half-open
        probe nor reset the consecutive-failure streak: a sick replica
        shedding 100% of its riders would otherwise keep its breaker
        closed exactly when failover matters most."""
        with self._cond:
            replica.outstanding = max(0, replica.outstanding - 1)
            self._cond.notify_all()
            replica.flushes += 1
            if ok is False:
                replica.errors += 1
            # gauge writes only for replicas still IN the routing list:
            # a swapped-out/healed slot's late-finishing worker would
            # otherwise clobber its replacement's series for the same
            # index with a stale count
            live = replica in self.replicas
            if live:
                metrics.set_gauge(
                    "serve.replica_outstanding",
                    replica.outstanding,
                    replica=replica.index,
                )
            metrics.inc("serve.replica_flushes", replica=replica.index)
            if ok is False:
                metrics.inc("serve.replica_errors", replica=replica.index)
            if live:
                total = sum(r.flushes for r in self.replicas) or 1
                for r in self.replicas:
                    metrics.set_gauge(
                        "serve.replica_queue_share",
                        r.flushes / total,
                        replica=r.index,
                    )
        if ok is True:
            replica.breaker.record_success()
        elif ok is False:
            replica.breaker.record_failure()

    # ------------------------------------------------------------- swap
    def stage(
        self, pipeline, version: str, artifacts: Optional[dict] = None
    ) -> List[Replica]:
        """Build (and start) a full staged generation for ``version`` on
        the same devices as the current one.  Staged replicas accept
        priming applies but receive no routed traffic until
        :meth:`commit` — the old generation keeps serving.
        ``artifacts``: the new version's AOT bundle — staged appliers
        install it (so the caller's prime loads instead of compiling),
        and :meth:`commit` makes it the pool's bundle for later heals."""
        devices = [r.device for r in self.replicas]
        n = len(devices)
        if self.backend in ("process", "net"):
            # a fresh generation of workers off a fresh payload,
            # spawned concurrently: the old workers keep serving their
            # (already-loaded) payload throughout
            path = self._stage_payload(pipeline, artifacts)
            staged = self._build_process_many(n, version, path, swap=True)
            self._staged_payload_path = path
        elif n == 1 and devices[0] is None:
            # staged single-replica generations still clone: the OLD
            # generation keeps serving the caller's applier while the
            # staged one primes, so they must not share jit caches
            applier = _as_applier(_clone_and_place(pipeline, None))
            if artifacts:
                self._install_artifacts(applier, None, artifacts, pipeline)
            staged = [
                Replica(
                    0,
                    applier,
                    device=None,
                    version=version,
                    pool_name=self.name,
                    heartbeat_timeout=self._heartbeat_s,
                )
            ]
        else:
            staged = [
                self._build_one(
                    pipeline, i, dev, version, n, artifacts=artifacts
                )
                for i, dev in enumerate(devices)
            ]
        self._staged_source = pipeline
        self._staged_artifacts = artifacts
        self._staged_artifacts_set = True
        if self._runner is not None:
            for r in staged:
                self._start_replica(r)
        return staged

    def commit(self, staged: List[Replica], version: str) -> float:
        """Atomically install a staged generation; returns the swap
        pause in seconds — the router-lock-held window during which no
        flush could be dispatched.  Old workers retire AFTER the lock is
        released: they drain their queued flushes, then exit."""
        t0 = time.perf_counter()
        with self._cond:
            refused = self._draining
            if not refused:
                old, self.replicas = self.replicas, staged
                self.version = version
                if self._staged_source is not None:
                    # the supervisor's restart source moves with the
                    # generation: replacements serve what the fleet does
                    self._source = self._staged_source
                    self._staged_source = None
                if self._staged_artifacts_set:
                    # the artifact bundle moves with the generation too
                    # (None is meaningful: the new version may have no
                    # artifacts, and heals must not install the OLD
                    # version's programs into new-version replacements)
                    new_sig = (
                        (self._staged_artifacts or {})
                        .get("manifest", {})
                        .get("signature")
                    )
                    # prune the retired version's deserialized programs
                    # (keyed by bundle signature, so the staged
                    # generation's entries survive the prune)
                    self._artifact_programs = {
                        k: v
                        for k, v in self._artifact_programs.items()
                        if k[0] == new_sig
                    }
                    self._artifacts = self._staged_artifacts
                    self._staged_artifacts = None
                    self._staged_artifacts_set = False
                if self._staged_payload_path is not None:
                    # the worker payload moves with the generation:
                    # future heals/scale-ups spawn from the new file.
                    # The old file is unlinked — its workers loaded it
                    # long ago.
                    old_payload = self._payload_path
                    self._payload_path = self._staged_payload_path
                    self._staged_payload_path = None
                    if old_payload and old_payload != self._payload_path:
                        try:
                            import os

                            os.unlink(old_payload)
                        except OSError:
                            pass
                # a fresh generation is healthy by construction: clear
                # the unavailability hint so admission re-opens
                self._known_unavailable = False
                pause = time.perf_counter() - t0
                # the fresh generation has zero outstanding work: wake a
                # batcher blocked on the old generation's window
                self._cond.notify_all()
        if refused:
            # the pool is closing: installing a fresh generation now
            # would leak its worker threads (close() has already
            # snapshotted the replicas it will retire).  Retire the
            # staged workers instead and refuse the swap.
            for r in staged:
                r.retire()
            raise RuntimeError(
                f"replica pool {self.name!r} is closing; swap commit refused"
            )
        for r in staged:
            # a swap is the operator's quarantine reset: the fresh
            # generation's slots start clean
            metrics.set_gauge("serve.quarantined", 0.0, replica=r.index)
        for r in old:
            r.retire()
        return pause

    # ---------------------------------------------------------- healing
    def build_replacement(self, old: Replica) -> Replica:
        """A fresh replica for ``old``'s slot: re-cloned and re-placed
        from the pool's current source, worker started, NOT yet routed
        (the caller primes it, then :meth:`adopt_replacement` installs
        it).  The replacement carries the slot's restart count and a
        fresh CLOSED breaker — a successful restart re-admits traffic."""
        with self._lock:
            n = len(self.replicas)
            source, version = self._source, self.version
            artifacts = self._artifacts
            payload = self._payload_path
        fresh = self._build_one(
            source, old.index, old.device, version, n, force_clone=True,
            artifacts=artifacts, payload_path=payload,
        )
        fresh.restarts = old.restarts + 1
        if self._runner is not None:
            self._start_replica(fresh)
        return fresh

    def adopt_replacement(self, old: Replica, fresh: Replica):
        """Swap ``fresh`` into ``old``'s routing slot under the router
        lock, transferring old's queued flushes (its in-hand crash
        requeue included) so no admitted work is dropped.  Returns None
        on success.  When the slot is gone (a blue/green swap or a
        close() raced the restart) the replacement is retired and the
        drained flushes are RETURNED to the caller — re-enqueueing them
        into ``old`` would strand them forever: a swap-retired replica
        is never joined, and close() may already be past its join."""
        with self._cond:
            # drain UNDER the router lock: a wedged replica is still
            # routable() until this very swap, and dispatch/hedge both
            # select-and-enqueue while holding this lock — drained
            # outside it, a concurrent dispatch could enqueue a batch
            # into old AFTER the drain (behind the sentinel, in a
            # replica about to vanish from the list) and its riders
            # would hang forever.  Replica._cond nests inside the pool
            # lock here exactly as in dispatch's chosen.enqueue().
            moved = old.drain_queue()
            if self._draining:
                # close() is tearing the pool down: installing the
                # replacement now would leak its worker past close()'s
                # snapshot
                adopted = False
                i = -1
            else:
                try:
                    i = self.replicas.index(old)
                except ValueError:
                    adopted = False
                else:
                    adopted = True
            if adopted:
                self.replicas[i] = fresh
                for item in moved:
                    fresh.enqueue(item)
                fresh.outstanding = len(moved)
                metrics.set_gauge(
                    "serve.replica_outstanding",
                    fresh.outstanding,
                    replica=fresh.index,
                )
                metrics.set_gauge(
                    "serve.quarantined", 0.0, replica=fresh.index
                )
                self._known_unavailable = False
                self._cond.notify_all()
        if not adopted:
            fresh.retire()
            return moved
        old.retire()
        return None

    def quarantine_replica(self, replica: Replica) -> List:
        """Mark a replica quarantined (out of routing until a swap
        installs a fresh generation), drain its queue, and return the
        stranded flushes for the caller to re-dispatch or fail."""
        with self._cond:
            replica.quarantined = True
            if not any(r.routable() for r in self.replicas):
                # the LAST routable replica just left: admission must
                # refuse immediately, not on the next failed dispatch
                self._known_unavailable = True
            self._cond.notify_all()
        metrics.set_gauge("serve.quarantined", 1.0, replica=replica.index)
        return replica.drain_queue()

    # ---------------------------------------------------------- scaling
    @property
    def window(self) -> int:
        return self._window

    @property
    def host_capacity(self) -> Optional[int]:
        """The host map's total worker-slot budget (net backend), or
        ``None`` when unbounded / not a net fleet — the autoscaler
        clamps scale-up targets to this."""
        if self._hostmap is None:
            return None
        return self._hostmap.capacity()

    @property
    def listen_address(self) -> Optional[str]:
        """``host:port`` of the worker listener (net backend) — what an
        operator points a hand-started ``keystone worker`` at."""
        if self._listener is None:
            return None
        return self._listener.address

    def set_window(self, n: int) -> int:
        """Retune the dispatch window live (the autoscaler's second
        lever): raising it deepens per-replica queueing before the
        batcher blocks; lowering it tightens backpressure.  Returns the
        clamped value.  Waiters are woken — a batcher blocked at the
        old window re-evaluates immediately."""
        n = max(1, int(n))
        with self._cond:
            self._window = n
            self._cond.notify_all()
        metrics.set_gauge("serve.dispatch_window", float(n))
        return n

    def next_index(self) -> int:
        with self._lock:
            taken = {r.index for r in self.replicas}
        i = 0
        while i in taken:
            i += 1
        return i

    def add_replica(self, primer: Optional[Callable] = None) -> Replica:
        """Grow the fleet by one: build (spawn, for the process
        backend) → ``primer(replica)`` (the service's bucket prime) →
        admit under the router lock.  The new slot takes the lowest
        free index and a fresh CLOSED breaker.  Build/prime happen
        OUTSIDE the router lock — the live fleet keeps serving."""
        with self._lock:
            if self._draining:
                raise RuntimeError(
                    f"pool {self.name!r} is closing; scale-up refused"
                )
            n = len(self.replicas)
            source, version = self._source, self.version
            artifacts = self._artifacts
            payload = self._payload_path
        index = self.next_index()
        device = None
        if self.backend == "thread" and any(
            r.device is not None for r in self.replicas
        ):
            import jax

            local = jax.local_devices()
            device = local[index % len(local)]
        fresh = self._build_one(
            source, index, device, version, n + 1, force_clone=True,
            artifacts=artifacts, payload_path=payload,
        )
        if self._runner is not None:
            self._start_replica(fresh)
        if primer is not None:
            try:
                primer(fresh)
            except BaseException:
                fresh.retire()
                raise
        with self._cond:
            if self._draining:
                admitted = False
            else:
                self.replicas.append(fresh)
                self._known_unavailable = False
                admitted = True
                self._cond.notify_all()
        if not admitted:
            fresh.retire()
            raise RuntimeError(
                f"pool {self.name!r} closed during scale-up"
            )
        metrics.set_gauge("serve.workers", float(self.size))
        return fresh

    def remove_replica(self, timeout: float = 30.0) -> Optional[List]:
        """Shrink the fleet by one — gracefully: the HIGHEST-index
        routable replica leaves the routing list under the router lock
        (no new work lands on it), then drains its already-queued
        flushes and exits; the child process (process backend) is
        reaped by the worker-exit hook.  Returns flushes left behind by
        a worker that would not drain within ``timeout`` (the caller
        re-dispatches or fails them), or None when the fleet is already
        at one replica (the floor — a pool never scales to zero)."""
        with self._cond:
            cands = [r for r in self.replicas if r.routable()]
            if len(cands) <= 1 or len(self.replicas) <= 1:
                return None
            victim = max(cands, key=lambda r: r.index)
            self.replicas.remove(victim)
            self._cond.notify_all()
        victim.retire()
        left = victim.join(max(0.1, float(timeout)))
        if victim._worker is not None and victim._worker.is_alive():
            # the victim would not drain within the timeout (a wedged
            # apply): it left the routing list at retire, the
            # supervisor skips retired slots, and a thread backend has
            # no child to kill — surface the in-hand flush so the
            # caller resolves its riders instead of stranding them
            # forever.  (Process backend: join already killed the
            # child, so this path is thread-only.)
            stuck = victim.inflight
            if stuck is not None:
                left.append(stuck)
        for gauge in ("serve.replica_outstanding", "serve.replica_queue_share"):
            try:
                metrics.REGISTRY.remove_gauge(gauge, replica=victim.index)
            except Exception:
                pass
        metrics.set_gauge("serve.workers", float(self.size))
        return left

    # ------------------------------------------------------------ close
    def begin_drain(self) -> None:
        """Release a ``dispatch`` blocked at the dispatch window: with
        draining set it dispatches regardless, so the batch lands in a
        replica queue where :meth:`close` can collect and hand it back
        instead of the batcher holding it in-hand forever.  The service
        calls this BEFORE joining its batcher thread — otherwise a
        batcher blocked on a wedged fleet burns the whole join timeout
        and its in-hand batch's futures never resolve."""
        with self._lock:
            self._draining = True
            self._cond.notify_all()

    def close(self, timeout: float = 30.0) -> List:
        """Retire and join every replica; returns batches abandoned by
        wedged workers (the service fails their futures).  Process
        backend: each replica's join reaps its child (bye → join →
        terminate → kill), and the staged payload files are swept."""
        self.begin_drain()
        with self._lock:
            replicas = list(self.replicas)
        abandoned: List = []
        for r in replicas:
            r.retire()
        deadline = time.monotonic() + timeout
        for r in replicas:
            abandoned.extend(r.join(max(0.1, deadline - time.monotonic())))
        if self._payload_dir is not None:
            import shutil

            shutil.rmtree(self._payload_dir, ignore_errors=True)
            self._payload_dir = None
        if self._listener is not None:
            self._listener.close()
        if self._hostmap is not None:
            # spawned worker processes are reaped here; hand-started
            # workers see the listener close and exit on their own
            # when their reconnect budget runs dry
            self._hostmap.close()
        return abandoned

    def statuses(self) -> List[dict]:
        with self._lock:
            replicas = list(self.replicas)
        return [r.status() for r in replicas]


class ReplicaSupervisor:
    """The self-healing loop: detect dead or wedged replica workers and
    restart them in place, quarantining a slot that keeps dying.

    Detection, once per ``interval`` seconds:

    - **dead** — the worker thread exited without being retired (an
      injected ``serve.worker`` crash, or any error that escaped the
      runner's own failure delivery).  The crash handler requeued the
      in-hand flush, so a restart loses nothing.
    - **wedged** — the thread is alive but has held one flush past the
      replica's heartbeat budget (``guard.Heartbeat``): a hung apply,
      an injected ``hang``.  The thread cannot be killed; the wedged
      replica is swapped out of routing, its QUEUED flushes transfer to
      the replacement, and its in-hand flush's riders are failed (typed
      :class:`FleetUnavailable`) so their callers unblock — if the hang
      ever finishes, late delivery is tolerated and discarded.

    Healing: re-clone + re-place a replacement from the pool's current
    source (:meth:`ReplicaPool.build_replacement`), prime its padding
    buckets via the service, then swap it into the routing slot under
    the router lock (queued work transfers; the replacement's fresh
    CLOSED breaker re-admits traffic).  ``restart_limit`` restarts
    within ``restart_window`` seconds quarantine the slot instead —
    the fleet keeps serving on the survivors, and a blue/green swap
    resets quarantine.

    Every restart/quarantine is visible: ``serve.replica_restarts`` /
    ``serve.quarantined{replica=i}`` metrics, a ``replica.restart``
    ledger span, and a flight-recorder ops span (``/tracez``,
    ``/statusz``)."""

    def __init__(
        self,
        service,
        interval: float = 0.5,
        restart_limit: int = 3,
        restart_window: float = 60.0,
    ):
        self.service = service
        self.interval = max(0.05, float(interval))
        self.restart_limit = max(1, int(restart_limit))
        self.restart_window = float(restart_window)
        self.restarts_total = 0
        self.quarantined_total = 0
        self.last_restart: Optional[dict] = None
        self._history: Dict[int, deque] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop,
            daemon=True,
            name=f"{service.name}-supervisor",
        )

    def start(self) -> "ReplicaSupervisor":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def status(self) -> dict:
        return {
            "interval_seconds": self.interval,
            "restart_limit": self.restart_limit,
            "restart_window_seconds": self.restart_window,
            "restarts": self.restarts_total,
            "quarantined": self.quarantined_total,
            "last_restart": self.last_restart,
        }

    # ------------------------------------------------------------ sweep
    def _loop(self) -> None:
        ledger.restore_context(self.service._obs_ctx)
        while not self._stop.wait(self.interval):
            try:
                self.check_now()
            except Exception:  # the healer must never die of a heal
                logger.exception("replica supervisor sweep failed")

    def check_now(self) -> int:
        """One detection sweep (the loop body; callable from tests).
        Returns how many replicas were healed or quarantined."""
        pool = self.service._pool
        with pool._lock:
            replicas = list(pool.replicas)
        healed = 0
        for r in replicas:
            if r.quarantined or r._retired:
                continue
            dead = r.is_dead()
            wedged = (
                not dead
                and r.inflight is not None
                and r.heartbeat.expired()
            )
            if not (dead or wedged):
                continue
            self._heal(r, "dead" if dead else "wedged")
            healed += 1
        return healed

    # ------------------------------------------------------------- heal
    def _budget_exhausted(self, index: int) -> bool:
        hist = self._history.setdefault(index, deque())
        now = time.monotonic()
        while hist and now - hist[0] > self.restart_window:
            hist.popleft()
        return len(hist) >= self.restart_limit

    def _heal(self, replica: Replica, reason: str) -> None:
        svc = self.service
        pool = svc._pool
        if self._budget_exhausted(replica.index):
            self._quarantine(replica, reason)
            return
        self._history[replica.index].append(time.monotonic())
        # a wedged worker's in-hand flush: grab it BEFORE the swap so
        # its riders can be failed (their callers are blocked on it)
        stuck = replica.inflight if reason == "wedged" else None
        t0 = time.monotonic()
        with ledger.span(
            "replica.restart", replica=replica.index, reason=reason
        ):
            fresh = pool.build_replacement(replica)
            try:
                svc.prime_replacement(fresh)
            except BaseException as e:
                # a replacement that cannot prime must not join the
                # router; leave the slot as-is — the budget entry above
                # converges repeated failures onto quarantine
                fresh.retire()
                metrics.inc(
                    "serve.replica_restart_failures", replica=replica.index
                )
                logger.error(
                    "replica %d restart failed to prime: %s: %s",
                    replica.index,
                    type(e).__name__,
                    e,
                )
                return
            leftover = pool.adopt_replacement(replica, fresh)
        if leftover is not None:
            # a swap/close raced the restart: the slot is gone, but the
            # drained flushes are admitted work — redistribute them to
            # the (new-generation) survivors rather than stranding them.
            # The wedged in-hand flush is NOT in that queue (it was
            # popped) and no future sweep revisits the vanished slot:
            # abandon it here too, or its riders hang forever.
            self._redistribute(leftover, replica, reason)
            if stuck is not None:
                self._abandon(stuck, replica, reason)
            return
        took = time.monotonic() - t0
        self.restarts_total += 1
        metrics.inc("serve.replica_restarts", replica=replica.index)
        self.last_restart = {
            "replica": replica.index,
            "reason": reason,
            "seconds": round(took, 3),
            "restarts_in_window": len(self._history[replica.index]),
            "error": replica.dead_error,
        }
        rec = getattr(svc, "recorder", None)
        if rec is not None:
            rec.ops(
                "replica.restart",
                replica=replica.index,
                reason=reason,
                seconds=round(took, 3),
                restarts=len(self._history[replica.index]),
                error=replica.dead_error,
            )
        logger.warning(
            "restarted %s replica %d in %.2fs (%d restart(s) in window)",
            reason,
            replica.index,
            took,
            len(self._history[replica.index]),
        )
        if stuck is not None:
            self._abandon(stuck, replica, reason)

    def _quarantine(self, replica: Replica, reason: str) -> None:
        svc = self.service
        stranded = svc._pool.quarantine_replica(replica)
        self.quarantined_total += 1
        ledger.event(
            "replica.quarantine",
            replica=replica.index,
            reason=reason,
            restarts=len(self._history.get(replica.index, ())),
        )
        rec = getattr(svc, "recorder", None)
        if rec is not None:
            rec.ops(
                "replica.quarantine",
                replica=replica.index,
                reason=reason,
                restarts=len(self._history.get(replica.index, ())),
            )
        logger.error(
            "quarantined replica %d after %d restarts within %.0fs (%s)",
            replica.index,
            len(self._history.get(replica.index, ())),
            self.restart_window,
            reason,
        )
        self._redistribute(stranded, replica, "quarantined")
        stuck = replica.inflight
        if stuck is not None:
            self._abandon(stuck, replica, reason)

    def _redistribute(self, flushes: List, replica: Replica, why: str) -> None:
        """Re-dispatch flushes stranded on a healed/quarantined/raced
        slot onto the survivors — the service's single shared
        stranded-work policy (``_handle_stranded_flush``): skip claimed
        copies, window-ignoring hedge dispatch, typed failure (aborted
        first) only when no routable survivor exists."""
        for flush in flushes:
            self.service._handle_stranded_flush(
                flush, why=f"replica {replica.index} {why}"
            )

    def _abandon(self, flush, replica: Replica, reason: str) -> None:
        """Fail a wedged worker's in-hand flush so its callers unblock.
        ``abort()`` stops an unclaimed flush from ever running; a
        CLAIMED one may still finish inside the wedged thread — late
        delivery into already-failed futures is tolerated/discarded."""
        aborted = getattr(flush, "abort", lambda: False)()
        self.service.fail_flush(
            flush,
            FleetUnavailable(
                f"replica {replica.index} {reason}; flush abandoned "
                f"({'never ran' if aborted else 'outcome unknown'})"
            ),
        )
