"""Fleet telemetry: worker-side span capture + metrics-delta shipping,
and the router-side sink that stitches both into the existing ops
surface (FlightRecorder, metrics registry, ``/statusz``).

PRs 15–17 moved the actual compute out of the router process — into
spawned workers (``serve/procfleet.py``), across hosts over TCP
(``serve/net.py``), and behind a binary ingress — but the PR-3/PR-9
observability stack stayed router-local, so a request's critical path
went dark the moment it crossed a wire.  This module closes that gap
without any new connection or clock assumption:

- **Worker side** (:class:`WorkerTelemetry`): a bounded ring of
  completed spans (``worker.load`` / ``worker.build`` /
  ``worker.prime`` / ``worker.attach`` / ``worker.apply``) plus
  periodic *deltas* of the worker-local metrics registry.  Both
  piggyback on frames the transport already sends (replies, the ready
  frame, heartbeats) — there is no telemetry channel to partition
  separately from the data it describes.  Everything is
  **dropped-not-queued**: the span ring overwrites its oldest entry,
  metric deltas wait for the next ship, and a worker that never gets to
  ship simply loses telemetry, never memory.

- **Clock alignment** (:class:`ClockSync`): workers stamp each exchange
  with their own monotonic clock (``t_rx`` at request receipt, ``t_tx``
  at reply send); the router pairs those with its own send/receive
  stamps — the classic NTP four-timestamp sample.  ``offset`` estimates
  ``worker_clock - router_clock``; the minimum-delay sample wins (it
  bounds the error by the one-way wire time), with a slow decay so a
  drifting clock re-syncs.  Stitched span times are additionally
  clamped into the router's ``[t_send, t_recv]`` observation window, so
  ordering holds and durations are never negative no matter how wrong
  the skew estimate is.

- **Router side** (:class:`FleetTelemetry`): one sink shared by every
  worker handle of a pool.  Each reply exchange updates the worker's
  clock sync, folds shipped metric deltas into the router registry
  under ``worker=``/``host=`` labels (``tools/lint.py`` pins that
  fan-out rides *labels*, never interpolated metric names), feeds the
  ``serve.fleet.*`` series, and — when the flush carried trace context
  — merges wire accounting + worker spans into the FlightRecorder's
  batch record, where ``GET /requestz/<id>`` joins them into the
  request's cross-process causal chain.

Version tolerance is structural: every field added to a frame is an
optional body key.  An old worker ignores ``trace``; an old router
ignores ``telemetry``; an absent field means "old peer", never an
error.  This module is numpy-free and imports only ``obs.metrics``
(stdlib-only), so worker processes pay nothing extra at import.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from keystone_tpu.obs import metrics

logger = logging.getLogger(__name__)

# fine-grained bounds for the fleet series: worker applies are
# milliseconds-scale (the serve latency grid), wire round trips are
# often sub-millisecond on a LAN (the ingress grid)
metrics.register_buckets(
    "serve.fleet.apply_seconds", metrics.LATENCY_MS_BUCKETS
)
metrics.register_buckets(
    "serve.fleet.wire_rtt_seconds", metrics.INGRESS_TIME_BUCKETS
)

#: ceiling on completed spans held worker-side between ships.  Overflow
#: drops the OLDEST span (dropped-not-queued): a worker the router never
#: drains again loses telemetry, not memory.
MAX_PENDING_SPANS = 64

#: ceiling on metric-delta entries per ship; series beyond the cap stay
#: pending (their baseline does not advance) and ride the next ship.
MAX_DELTA_ENTRIES = 128

#: floor between metric-delta exports on one channel — replies arriving
#: faster than this carry spans + clock stamps only, keeping the
#: telemetry tax on a hot flush path to a dict copy, not a registry walk
DELTA_MIN_INTERVAL_S = 0.5

#: trace context ships at most this many rider request ids (a 1024-row
#: ingress batch must not quadruple its control frame)
MAX_TRACE_REQUEST_IDS = 16


# ------------------------------------------------------------- worker side


class WorkerTelemetry:
    """Worker-process half: bounded span capture + registry deltas.

    One instance per worker serve loop (or per net session).  All
    methods are thread-safe (the net worker's beat thread ships metric
    deltas while the serve loop records spans)."""

    def __init__(
        self,
        registry: Optional[metrics.MetricsRegistry] = None,
        max_spans: int = MAX_PENDING_SPANS,
        max_entries: int = MAX_DELTA_ENTRIES,
        min_metrics_interval_s: float = DELTA_MIN_INTERVAL_S,
    ):
        self._reg = registry if registry is not None else metrics.REGISTRY
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=max(1, int(max_spans)))
        self._max_entries = max(1, int(max_entries))
        self._min_interval = max(0.0, float(min_metrics_interval_s))
        #: absolute values already shipped, per series key — counters
        #: and histograms export the difference against this
        self._shipped_counters: Dict = {}
        self._shipped_hists: Dict = {}
        self._shipped_gauges: Dict = {}
        self._last_metrics_ship = -float("inf")

    # ------------------------------------------------------------- spans
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Record one completed span (worker monotonic clock) around a
        block.  The span lands in the ring even when the block raises —
        a failing apply is exactly the span worth shipping."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add_span(name, t0, time.monotonic(), **attrs)

    def add_span(self, name: str, t0: float, t1: float, **attrs) -> None:
        sp = {"name": str(name), "t0": float(t0), "t1": float(t1)}
        if attrs:
            sp["attrs"] = attrs
        with self._lock:
            self._spans.append(sp)

    # ----------------------------------------------------------- shipping
    def ship(self, t_rx: Optional[float] = None) -> dict:
        """The ``telemetry`` body of one reply frame: worker clock
        stamps, every pending span (drained), and — when the throttle
        window elapsed — registry deltas."""
        blob: dict = {"t_tx": time.monotonic()}
        if t_rx is not None:
            blob["t_rx"] = float(t_rx)
        with self._lock:
            if self._spans:
                blob["spans"] = list(self._spans)
                self._spans.clear()
        entries = self.metrics_entries()
        if entries:
            blob["metrics"] = entries
        return blob

    def metrics_entries(
        self, min_interval_s: Optional[float] = None
    ) -> Optional[list]:
        """Registry deltas since the last ship, or None inside the
        throttle window / when nothing changed.  Baselines advance only
        for entries actually returned, so a capped export ships the
        remainder next round instead of losing it."""
        interval = (
            self._min_interval if min_interval_s is None else float(min_interval_s)
        )
        now = time.monotonic()
        with self._lock:
            if now - self._last_metrics_ship < interval:
                return None
            counters, gauges, hists = self._reg.export_raw()
            entries: List[list] = []
            for k, v in counters.items():
                if len(entries) >= self._max_entries:
                    break
                delta = v - self._shipped_counters.get(k, 0.0)
                if delta <= 0.0:
                    continue
                name, labels = k
                entries.append(["c", name, [list(p) for p in labels], delta])
                self._shipped_counters[k] = v
            for k, v in gauges.items():
                if len(entries) >= self._max_entries:
                    break
                if self._shipped_gauges.get(k) == v:
                    continue
                name, labels = k
                entries.append(["g", name, [list(p) for p in labels], v])
                self._shipped_gauges[k] = v
            for k, h in hists.items():
                if len(entries) >= self._max_entries:
                    break
                bounds, buckets, count, total, mn, mx = h
                prev = self._shipped_hists.get(k)
                if prev is not None and prev[2] == count:
                    continue
                if prev is not None and tuple(prev[0]) == tuple(bounds):
                    d_buckets = [b - p for b, p in zip(buckets, prev[1])]
                    d_count = count - prev[2]
                    d_sum = total - prev[3]
                else:
                    d_buckets, d_count, d_sum = list(buckets), count, total
                name, labels = k
                entries.append(
                    [
                        "h",
                        name,
                        [list(p) for p in labels],
                        {
                            "bounds": list(bounds),
                            "buckets": d_buckets,
                            "count": d_count,
                            "sum": d_sum,
                            "min": None if mn is None else mn,
                            "max": None if mx is None else mx,
                        },
                    ]
                )
                self._shipped_hists[k] = (bounds, list(buckets), count, total)
            if entries:
                self._last_metrics_ship = now
            return entries or None


# ---------------------------------------------------------- clock alignment


class ClockSync:
    """NTP-style monotonic-clock alignment for one worker, from the
    four timestamps of a request/reply exchange:

    - router sends at ``t_send``, receives at ``t_recv`` (router clock)
    - worker receives at ``t_rx``, replies at ``t_tx`` (worker clock)

    ``delay = (t_recv - t_send) - (t_tx - t_rx)`` is the wire round
    trip with the worker's compute subtracted; ``offset = ((t_rx -
    t_send) + (t_tx - t_recv)) / 2`` estimates ``worker_clock -
    router_clock`` with error bounded by ``delay / 2``.  The
    minimum-delay sample is kept (its bound is tightest); the kept
    delay decays slightly per rejected sample so a drifting clock
    re-converges instead of trusting one ancient lucky sample forever.
    A negative measured delay (a retransmit answered by the reply to an
    earlier send) is rejected outright."""

    __slots__ = ("offset", "best_delay", "last_delay", "samples")

    #: per-rejected-sample growth of the kept delay bound: ~70 rejected
    #: exchanges double the bound, after which a typical sample wins
    _DECAY = 1.01

    def __init__(self):
        self.offset: Optional[float] = None
        self.best_delay: Optional[float] = None
        self.last_delay: Optional[float] = None
        self.samples = 0

    def observe(
        self, t_send: float, t_recv: float, t_rx: float, t_tx: float
    ) -> Optional[float]:
        """Fold one exchange in; returns the measured wire delay (for
        the RTT series), or None for an unusable sample."""
        delay = (t_recv - t_send) - (t_tx - t_rx)
        if delay < 0.0 or t_recv < t_send:
            return None
        self.samples += 1
        self.last_delay = delay
        if self.best_delay is None or delay <= self.best_delay:
            self.best_delay = delay
            self.offset = ((t_rx - t_send) + (t_tx - t_recv)) / 2.0
        else:
            self.best_delay *= self._DECAY
        return delay

    def to_router(self, t_worker: float) -> Optional[float]:
        """A worker-clock instant on the router's clock (None before
        the first accepted sample)."""
        if self.offset is None:
            return None
        return t_worker - self.offset


def clamp_span(
    sync: ClockSync,
    t0_worker: float,
    t1_worker: float,
    t_send: float,
    t_recv: float,
):
    """Align one worker span into the router clock, clamped into the
    router's ``[t_send, t_recv]`` observation window.  The clamp is the
    skew-tolerance guarantee: whatever the offset estimate got wrong,
    the span stays inside the interval the router *observed* containing
    it, stays ordered, and never has negative duration."""
    lo, hi = float(t_send), max(float(t_send), float(t_recv))
    r0 = sync.to_router(t0_worker)
    r1 = sync.to_router(t1_worker)
    if r0 is None or r1 is None:
        # no sync yet: preserve the span's own duration, anchored at
        # the window start (duration itself needs no clock alignment)
        dur = max(0.0, float(t1_worker) - float(t0_worker))
        return lo, min(hi, lo + dur)
    r0 = min(max(r0, lo), hi)
    r1 = min(max(r1, lo), hi)
    if r1 < r0:
        r1 = r0
    return r0, r1


# ------------------------------------------------------------- router side


class FleetTelemetry:
    """The router-side sink one :class:`~keystone_tpu.serve.fleet.
    ReplicaPool` shares across all its worker handles (initial build,
    scale-ups, supervisor heals — every handle built by the pool is
    attached to the same sink, so telemetry survives replacement).

    Never raises into the serving path: a malformed shipment is logged
    at debug and dropped — telemetry must not be able to fail a flush
    that the data path served fine."""

    def __init__(self, registry=None, recorder=None):
        self._reg = registry if registry is not None else metrics.REGISTRY
        #: the service's FlightRecorder; assigned after construction
        #: (the pool is built before the recorder exists) and None when
        #: tracing is off — metric aggregation works either way
        self.recorder = recorder
        self._lock = threading.Lock()
        self._clocks: Dict[str, ClockSync] = {}
        self._hosts: Dict[str, str] = {}

    # ------------------------------------------------------------ intake
    def _sync_for(self, worker: str, host: str) -> ClockSync:
        with self._lock:
            sync = self._clocks.get(worker)
            if sync is None:
                sync = self._clocks[worker] = ClockSync()
            self._hosts[worker] = host
            return sync

    def on_exchange(
        self,
        worker: str,
        host: Optional[str],
        t_send: float,
        t_recv: float,
        shipped,
        trace: Optional[dict] = None,
    ) -> None:
        """One request/reply exchange's worth of shipped telemetry.
        ``shipped`` is the reply's ``telemetry`` body (None from an old
        worker — tolerated, nothing to aggregate); ``trace`` is the
        context the apply frame carried, when the flush was traced."""
        if not isinstance(shipped, dict):
            return
        try:
            self._ingest(worker, host, t_send, t_recv, shipped, trace)
        except Exception:  # telemetry must never fail the data path
            logger.debug(
                "dropping malformed telemetry from %s", worker, exc_info=True
            )

    def on_beat(self, worker: str, host: Optional[str], shipped) -> None:
        """Heartbeat-piggybacked shipment: metric deltas only (a beat
        is one-way — no RTT sample, no trace to stitch)."""
        if not isinstance(shipped, dict):
            return
        try:
            worker, host = str(worker), str(host or "local")
            self._sync_for(worker, host)
            entries = shipped.get("metrics")
            if entries:
                self._reg.merge_entries(entries, worker=worker, host=host)
        except Exception:
            logger.debug(
                "dropping malformed beat telemetry from %s",
                worker,
                exc_info=True,
            )

    def _ingest(self, worker, host, t_send, t_recv, shipped, trace) -> None:
        worker, host = str(worker), str(host or "local")
        sync = self._sync_for(worker, host)
        delay = None
        t_rx, t_tx = shipped.get("t_rx"), shipped.get("t_tx")
        if isinstance(t_rx, (int, float)) and isinstance(t_tx, (int, float)):
            delay = sync.observe(
                float(t_send), float(t_recv), float(t_rx), float(t_tx)
            )
            if delay is not None:
                self._reg.observe(
                    "serve.fleet.wire_rtt_seconds",
                    delay,
                    worker=worker,
                    host=host,
                )
        spans = shipped.get("spans")
        good_spans: List[dict] = []
        if isinstance(spans, list):
            for sp in spans[: MAX_PENDING_SPANS]:
                if not isinstance(sp, dict):
                    continue
                try:
                    t0, t1 = float(sp["t0"]), float(sp["t1"])
                except (KeyError, TypeError, ValueError):
                    continue
                name = str(sp.get("name") or "worker.span")
                good_spans.append(
                    {"name": name, "t0": t0, "t1": t1, "attrs": sp.get("attrs")}
                )
                if name == "worker.apply":
                    self._reg.observe(
                        "serve.fleet.apply_seconds",
                        max(0.0, t1 - t0),
                        worker=worker,
                        host=host,
                    )
        entries = shipped.get("metrics")
        if entries:
            self._reg.merge_entries(entries, worker=worker, host=host)
        rec = self.recorder
        bid = trace.get("batch") if isinstance(trace, dict) else None
        if rec is None or bid is None:
            return
        # stitch into the flush's batch record: /requestz joins batch
        # records onto every rider's trace, so one update per exchange
        # keeps the per-request cost flat in batch size (the PR-9
        # batch-span discipline, now crossing the process boundary)
        aligned = []
        for sp in good_spans:
            r0, r1 = clamp_span(sync, sp["t0"], sp["t1"], t_send, t_recv)
            entry = {
                "name": sp["name"],
                "t_off": round(r0 - t_send, 6),
                "seconds": round(r1 - r0, 6),
            }
            if sp.get("attrs"):
                entry["attrs"] = sp["attrs"]
            aligned.append(entry)
        wire_acct = {"rtt_s": None if delay is None else round(delay, 6)}
        rx_r = None if not isinstance(t_rx, (int, float)) else sync.to_router(float(t_rx))
        tx_r = None if not isinstance(t_tx, (int, float)) else sync.to_router(float(t_tx))
        if rx_r is not None:
            rx_r = min(max(rx_r, t_send), t_recv)
            wire_acct["send_s"] = round(max(0.0, rx_r - t_send), 6)
        if tx_r is not None:
            tx_r = min(max(tx_r, t_send), t_recv)
            wire_acct["recv_s"] = round(max(0.0, t_recv - tx_r), 6)
        update = {"worker": worker, "host": host, "wire": wire_acct}
        if aligned:
            update["worker_spans"] = aligned
        rec.batch_update(str(bid), **update)

    # -------------------------------------------------------------- read
    def known_workers(self) -> List[str]:
        with self._lock:
            return sorted(self._clocks)

    def fleet_status(self) -> dict:
        """The ``/statusz`` ``fleet`` block: per-worker apply/wire
        percentiles (from the merged registry series), clock sync
        state, and the transport's retransmit/late-discard counters."""

        def _ms(v):
            return None if v is None else round(1000.0 * v, 3)

        workers = {}
        with self._lock:
            clocks = dict(self._clocks)
            hosts = dict(self._hosts)
        for worker, sync in sorted(clocks.items()):
            host = hosts.get(worker, "local")
            entry: dict = {
                "host": host,
                "clock_offset_s": (
                    None if sync.offset is None else round(sync.offset, 6)
                ),
                "clock_samples": sync.samples,
            }
            apply_h = self._reg.histogram_summary(
                "serve.fleet.apply_seconds", worker=worker, host=host
            )
            if apply_h is not None:
                entry["apply_ms"] = {
                    "count": apply_h["count"],
                    "p50": _ms(apply_h.get("p50")),
                    "p99": _ms(apply_h.get("p99")),
                }
            rtt_h = self._reg.histogram_summary(
                "serve.fleet.wire_rtt_seconds", worker=worker, host=host
            )
            if rtt_h is not None:
                entry["wire_rtt_ms"] = {
                    "count": rtt_h["count"],
                    "p50": _ms(rtt_h.get("p50")),
                    "p99": _ms(rtt_h.get("p99")),
                }
            retrans = self._reg.counter_value(
                "serve.net.retransmits", worker=worker
            )
            late = self._reg.counter_value(
                "serve.net.late_discards", worker=worker
            )
            if retrans:
                entry["retransmits"] = retrans
            if late:
                entry["late_discards"] = late
            workers[worker] = entry
        return {"workers": workers}
