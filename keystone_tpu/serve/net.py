"""Cross-host replicas: the worker wire contract over TCP, lease-fenced.

PR 15 promoted replica compute into worker *processes* but the
transport — shared-memory slabs + a ``multiprocessing`` pipe — dies at
the host boundary, and the reference system is a *cluster* framework:
every KeystoneML result assumes distributed execution.  This module
carries the same :class:`~keystone_tpu.serve.procfleet.WorkerHandle`
contract over a socket (wire v2: length-prefixed CRC-checked frames,
payload bytes inline — ``serve/wire.py``), which drops the fleet into a
genuinely hostile failure domain: partitions, half-open connections,
reordered retries, split-brain after heal.  The robustness machinery
here IS the feature:

- **Heartbeat lease.**  Both sides beat every ``lease_s / 4``; each
  treats ``lease_s`` of inbound silence as the other's death.  The
  ROUTER marks the worker dead (an in-flight apply raises
  :class:`~keystone_tpu.serve.procfleet.WorkerCrashed`, the service
  un-claims the flush, front-requeues it, and the supervisor heals onto
  a survivor — byte-for-byte the PR-15 crash path).  The WORKER
  **self-fences**: when its own lease lapses mid-compute it DISCARDS
  the finished result, closes the socket, and reconnects for a fresh
  lease — so a healed partition cannot double-serve a flush the router
  already re-dispatched.
- **Idempotent dispatch, at-least-once delivery.**  Every apply
  carries a flush id.  While a reply is pending the router RESENDS the
  apply frame every ``lease_s / 2`` (``serve.net.retransmits``):
  a partition can eat one frame and heal inside the lease window, and
  without retransmission a lost apply on an otherwise-beating link
  would wait forever — beats prove the peer is alive, not that the
  frame arrived.  The worker answers a repeated id from its last-reply
  cache without recomputing, and the router discards any result whose
  id is not the one in flight (``serve.net.late_discards`` — the PR-10
  hedge-loser discipline: late work is a no-op, never a double
  delivery).  Together: at-least-once dispatch, exactly-once effect.
- **Typed infra errors.**  Connection failures ride the ``OSError``
  family (:class:`WorkerCrashed` / :class:`FaultInjected` /
  ``ConnectionError``), so breakers, bisection's infra short-circuit,
  and hedging all behave unchanged off-box.
- **Fault sites.**  ``serve.net.connect`` / ``serve.net.send`` /
  ``serve.net.recv`` fire per connection attempt / frame, with ctx
  ``link=<worker name>`` and ``role=router|worker``.  The ``drop``
  action (alias ``partition``) silently discards the frame — a severed
  link is *silence*, detected only by lease expiry, exactly like a real
  partition.  ``corrupt`` flips bytes so the peer's CRC check condemns
  the connection.

Topology: the router owns a :class:`WorkerListener`; workers dial IN
(``keystone worker --connect HOST:PORT``) and announce themselves with
a ``hello`` frame.  The router deploys a generation by streaming the
staged payload bytes inline (the worker caches built appliers by
payload digest, so a fenced worker's rejoin skips the rebuild), then
serves the strict one-in-flight apply protocol of PR 15.  Spawning
local capacity — and, via a host map, remote capacity — lives in
``keystone_tpu.utils.hostmap``.

Local fleets never touch this module: ``workers=N`` without ``hosts=``
stays on the shared-memory path, and the ``serve.net.*`` sites are
structurally inert (nothing calls them) when no remote peer is
configured.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import socket
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

import numpy as np

from keystone_tpu import faults
from keystone_tpu.serve import wire
from keystone_tpu.serve.procfleet import (
    RemoteApplier,
    WorkerCrashed,
    WorkerSpawnError,
    WorkerHandle,
)

logger = logging.getLogger(__name__)

#: default lease: either side reads this much inbound silence as the
#: other's death.  Beats go out at lease/4, so a healthy link delivers
#: ~4 proofs of life per lease window — one lost beat never fences.
DEFAULT_LEASE_S = 5.0

#: floor on the beat interval (a tiny test lease must not busy-spin)
MIN_BEAT_INTERVAL_S = 0.05

#: ceiling on connect→hello for an accepted connection; a client that
#: dials and says nothing is not a worker
HELLO_TIMEOUT_S = 10.0

#: worker-side dial attempts before giving up on the router
DEFAULT_CONNECT_ATTEMPTS = 30


def parse_address(address: str) -> Tuple[str, int]:
    """``HOST:PORT`` → ``(host, port)``; the one place the CLI grammar
    is interpreted."""
    host, _, port = str(address).rpartition(":")
    if not host or not port:
        raise ValueError(f"address must be HOST:PORT, got {address!r}")
    return host, int(port)


def payload_digest(payload_bytes: bytes) -> str:
    """Content address of a staged generation's payload — the worker's
    applier-reuse key (a fenced worker rejoining the SAME generation
    skips the rebuild + re-prime entirely)."""
    return hashlib.blake2b(payload_bytes, digest_size=16).hexdigest()


def _beat_interval(lease_s: float) -> float:
    return max(MIN_BEAT_INTERVAL_S, float(lease_s) / 4.0)


def _corrupt_frame(data: bytes) -> bytes:
    """The ``corrupt`` wire action: flip a byte inside the CRC-covered
    region so the receiver rejects the frame as damaged in flight."""
    buf = bytearray(data)
    buf[-1] ^= 0xFF
    return bytes(buf)


# ---------------------------------------------------------------- listener


class WorkerListener:
    """The router's accept side: workers dial in, say ``hello``, and
    wait in a pending queue until a deploy claims them.  Handshakes run
    off-thread so one slow or foreign client never stalls accepts."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        backlog: int = 16,
        hello_timeout: float = HELLO_TIMEOUT_S,
    ):
        self._hello_timeout = float(hello_timeout)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(int(backlog))
        self.host, self.port = self._sock.getsockname()[:2]
        self._cond = threading.Condition()
        self._pending: Deque[Tuple[socket.socket, dict]] = deque()
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="net-accept"
        )
        self._accept_thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return  # listener closed
            threading.Thread(
                target=self._handshake,
                args=(conn, addr),
                daemon=True,
                name="net-hello",
            ).start()

    def _handshake(self, conn: socket.socket, addr) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # the socket timeout is the SEND budget only — reads wait
            # via select and never touch it (wire.SEND_TIMEOUT_S)
            conn.settimeout(wire.SEND_TIMEOUT_S)
            hello, _ = wire.recv_stream_frame(conn, timeout=self._hello_timeout)
            if hello.get("op") != "hello" or hello.get("protocol") != wire.SOCKET_VERSION:
                raise wire.WireError(
                    f"bad hello from {addr}: {hello.get('op')!r}"
                )
            # a partition severs the rejoin path too: a dropped hello
            # means this connection never registers
            act = faults.fault_point(
                "serve.net.recv",
                role="router",
                link=hello.get("name"),
                op="hello",
            )
            if act is not None:
                raise wire.WireError(f"hello {act}ped by fault plan")
        except (TimeoutError, EOFError, OSError, wire.WireError, ValueError) as e:
            logger.warning("worker handshake from %s failed: %s", addr, e)
            try:
                conn.close()
            except OSError:
                pass
            return
        from keystone_tpu.obs import metrics

        metrics.inc("serve.net.registrations")
        with self._cond:
            if self._closed:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self._pending.append((conn, hello))
            self._cond.notify_all()
        logger.info(
            "worker %s (pid %s) registered from %s",
            hello.get("name"),
            hello.get("pid"),
            addr,
        )

    def next_pending(
        self, timeout: Optional[float] = None
    ) -> Optional[Tuple[socket.socket, dict]]:
        """Pop one handshaked connection, waiting up to ``timeout``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._pending:
                if self._closed:
                    return None
                remain = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remain is not None and remain <= 0:
                    return None
                self._cond.wait(remain if remain is not None else 1.0)
            return self._pending.popleft()

    def pending_count(self) -> int:
        with self._cond:
            return len(self._pending)

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            pending, self._pending = list(self._pending), deque()
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass
        for conn, _ in pending:
            try:
                conn.close()
            except OSError:
                pass
        self._accept_thread.join(2.0)


# ------------------------------------------------------------ router side


class NetWorkerHandle:
    """Owns one REMOTE worker's connection: deploy handshake, the
    strict one-in-flight request slot, the reader thread (beats,
    results, late-result discards), the outbound beat thread, and the
    lease clock.  Duck-type-compatible with
    :class:`~keystone_tpu.serve.procfleet.WorkerHandle` everywhere the
    fleet touches it (``apply`` / ``alive`` / ``heartbeat_age`` /
    ``kill`` / ``shutdown`` / ``ready_info`` / ``artifact_keys``), so
    :class:`~keystone_tpu.serve.procfleet.RemoteApplier` and the
    service's remote fast path work unchanged."""

    def __init__(
        self,
        name: str,
        index: int,
        sock: socket.socket,
        hello: dict,
        payload_bytes: bytes,
        buckets=None,
        item_shape=None,
        dtype: Optional[str] = None,
        lease_s: float = DEFAULT_LEASE_S,
        ready_timeout: float = 300.0,
        max_frame_bytes: int = wire.DEFAULT_MAX_FRAME_BYTES,
    ):
        self.name = f"{name}-net{index}"
        self.index = int(index)
        self.lease_s = float(lease_s)
        self.max_frame_bytes = int(max_frame_bytes)
        self.hello = dict(hello)
        #: fleet-telemetry sink (``serve/telemetry.py``), attached by
        #: the pool via :meth:`attach_telemetry`; None = telemetry off
        self.telemetry = None
        self._sock = sock
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()  # strict one-in-flight
        self._resp_cond = threading.Condition()
        self._pending_fid: Optional[str] = None
        self._reply: Optional[Tuple[dict, bytes]] = None
        self._bye_ack = threading.Event()
        self._seq = 0
        self._closed = False
        self._dead: Optional[str] = None
        self._last_rx = time.monotonic()
        spec = {
            "name": self.name,
            "index": self.index,
            "buckets": None if buckets is None else [int(b) for b in buckets],
            "item_shape": (
                None
                if item_shape is None
                else list(int(d) for d in item_shape)
            ),
            "dtype": dtype,
            "lease_s": self.lease_s,
            "max_frame_bytes": self.max_frame_bytes,
            "digest": payload_digest(payload_bytes),
        }
        t0 = time.monotonic()
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(wire.SEND_TIMEOUT_S)
            self._raw_send({"op": "deploy", "spec": spec}, payload_bytes)
            ready, _ = wire.recv_stream_frame(
                sock, timeout=ready_timeout, max_frame_bytes=self.max_frame_bytes
            )
        except (TimeoutError, EOFError, OSError, wire.WireError) as e:
            self.kill()
            raise WorkerSpawnError(
                f"{self.name}: no ready frame within {ready_timeout:.0f}s "
                f"({type(e).__name__}: {e})"
            ) from e
        if ready.get("op") == "fatal":
            self.kill()
            raise WorkerSpawnError(
                f"{self.name}: worker failed to start "
                f"({ready.get('etype')}: {ready.get('emsg')})"
            )
        if ready.get("op") != "ready":
            self.kill()
            raise WorkerSpawnError(
                f"{self.name}: unexpected first frame {ready.get('op')!r}"
            )
        self.ready_info = ready
        self.spawn_seconds = time.monotonic() - t0
        #: the deploy→ready exchange's telemetry (remote build/prime
        #: spans + first clock sample), flushed when the pool attaches
        #: its sink
        self._pending_ready = (t0, time.monotonic(), ready.get("telemetry"))
        self.artifact_keys = {
            (tuple(shape), str(dt))
            for shape, dt in ready.get("artifact_keys", ())
        }
        self._last_rx = time.monotonic()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name=f"{self.name}-rx"
        )
        self._reader.start()
        self._beater = threading.Thread(
            target=self._beat_loop, daemon=True, name=f"{self.name}-beat"
        )
        self._beater.start()

    # --------------------------------------------------------- telemetry
    def attach_telemetry(self, sink) -> None:
        """Wire this handle to the pool's fleet-telemetry sink and
        flush the deploy→ready exchange's shipment.  Safe with
        ``sink=None``."""
        self.telemetry = sink
        pending, self._pending_ready = getattr(
            self, "_pending_ready", None
        ), None
        if sink is None or pending is None:
            return
        t_send, t_recv, shipped = pending
        sink.on_exchange(self.name, self.peer_host, t_send, t_recv, shipped)

    def _ship_reply_telemetry(self, reply, t_send, t_recv, trace) -> None:
        sink = self.telemetry
        if sink is None or not isinstance(reply, dict):
            return
        shipped = reply.get("telemetry")
        if shipped is not None:
            sink.on_exchange(
                self.name, self.peer_host, t_send, t_recv, shipped, trace=trace
            )

    # ---------------------------------------------------------- liveness
    @property
    def pid(self) -> Optional[int]:
        return self.hello.get("pid")

    @property
    def peer_host(self) -> Optional[str]:
        return self.hello.get("host")

    def alive(self) -> bool:
        """Alive = channel open AND the lease is fresh.  An expired
        lease IS death: the supervisor heals on it exactly as it would
        a SIGKILLed local worker, whether or not TCP still pretends the
        connection is up (half-open connections lie; leases don't)."""
        if self._closed or self._dead is not None:
            return False
        return (time.monotonic() - self._last_rx) <= self.lease_s

    def heartbeat_age(self) -> Optional[float]:
        return max(0.0, time.monotonic() - self._last_rx)

    def lease_expired(self) -> bool:
        return (time.monotonic() - self._last_rx) > self.lease_s

    # ------------------------------------------------------------- frames
    def _raw_send(self, msg: dict, payload: bytes = b"") -> None:
        data = wire.pack_stream_frame(msg, payload)
        with self._send_lock:
            self._sock.sendall(data)

    def _send(self, msg: dict, payload: bytes = b"") -> None:
        """One outbound frame through the ``serve.net.send`` site: a
        ``drop`` verdict silently discards it (partition semantics), a
        ``corrupt`` verdict damages it so the worker's CRC check
        condemns the link."""
        act = faults.fault_point(
            "serve.net.send", role="router", link=self.name, op=msg.get("op")
        )
        if act == "drop":
            return
        data = wire.pack_stream_frame(msg, payload)
        if act == "corrupt":
            data = _corrupt_frame(data)
        with self._send_lock:
            self._sock.sendall(data)

    def _mark_dead(self, reason: str) -> None:
        with self._resp_cond:
            if self._dead is None:
                self._dead = reason
            self._resp_cond.notify_all()

    def _read_loop(self) -> None:
        """The ONLY socket reader: beats refresh the lease, results
        fill the one pending slot, anything else is discarded loudly.
        The thread outlives a lease-expiry ``WorkerCrashed`` on purpose
        — that is the window where a fenced worker's late result must
        be OBSERVED and discarded, not left unread."""
        from keystone_tpu.obs import metrics

        while not self._closed:
            try:
                msg, payload = wire.recv_stream_frame(
                    self._sock,
                    timeout=0.25,
                    max_frame_bytes=self.max_frame_bytes,
                )
            except TimeoutError:
                continue
            except (EOFError, OSError, wire.WireError) as e:
                if not self._closed:
                    self._mark_dead(f"{type(e).__name__}: {e}")
                return
            act = faults.fault_point(
                "serve.net.recv",
                role="router",
                link=self.name,
                op=msg.get("op"),
            )
            if act == "drop":
                continue  # the frame never arrived
            if act == "corrupt":
                # damaged arrival: the channel is condemned, exactly as
                # if the CRC check had caught it
                self._mark_dead("injected frame corruption on recv")
                return
            self._last_rx = time.monotonic()
            op = msg.get("op")
            if op == "beat":
                shipped = msg.get("telemetry")
                sink = self.telemetry
                if shipped is not None and sink is not None:
                    # worker metrics deltas piggyback on the beats the
                    # worker already sends — no extra frames, and an
                    # old worker (no telemetry key) is simply silent
                    sink.on_beat(self.name, self.peer_host, shipped)
                continue
            if op == "bye_ack":
                self._bye_ack.set()
                continue
            if op in ("result", "error"):
                with self._resp_cond:
                    if (
                        self._pending_fid is not None
                        and msg.get("fid") == self._pending_fid
                    ):
                        self._reply = (msg, payload)
                        self._resp_cond.notify_all()
                        continue
                # a result nobody is waiting for: the flush was already
                # re-dispatched after this worker's lease expired — the
                # fenced loser's work is a discarded no-op
                metrics.inc("serve.net.late_discards", worker=self.name)
                logger.warning(
                    "%s: discarding late %s for flush %s (lease was "
                    "forfeited; the flush re-served elsewhere)",
                    self.name,
                    op,
                    msg.get("fid"),
                )
                continue
            logger.warning("%s: ignoring unexpected frame %r", self.name, op)

    def _beat_loop(self) -> None:
        interval = _beat_interval(self.lease_s)
        while not self._closed and self._dead is None:
            try:
                self._send({"op": "beat"})
            except OSError as e:
                if not self._closed:
                    self._mark_dead(f"beat send failed: {e}")
                return
            time.sleep(interval)

    # ----------------------------------------------------------- request
    def apply(
        self,
        arr: np.ndarray,
        n: int,
        deadline_s: Optional[float] = None,
        trace: Optional[dict] = None,
    ) -> np.ndarray:
        """One remote apply: frame the padded batch inline, wait for the
        matching flush id.  Raises the relayed typed error, or
        :class:`WorkerCrashed` when the channel died or the lease
        expired mid-request — the un-claim/front-requeue/heal path.

        ``trace``: optional trace context carried as a frame body key —
        absent when the recorder is off, ignored by an old worker."""
        meta, payload = wire.array_payload(arr)
        if len(payload) > self.max_frame_bytes:
            raise wire.PayloadTooLarge(
                f"payload of {len(payload)} bytes exceeds the frame cap "
                f"({self.max_frame_bytes}); refused at dispatch"
            )
        with self._lock:
            if self._closed or self._dead is not None:
                raise WorkerCrashed(
                    f"{self.name}: channel is down ({self._dead or 'closed'})"
                )
            self._seq += 1
            fid = f"{self.name}-f{self._seq}"
            with self._resp_cond:
                self._pending_fid = fid
                self._reply = None
            try:
                frame = {
                    "op": "apply",
                    "fid": fid,
                    "n": int(n),
                    "deadline_s": deadline_s,
                    "meta": meta,
                }
                if trace is not None:
                    frame["trace"] = trace
                t_send = time.monotonic()
                try:
                    self._send(frame, payload)
                except OSError as e:
                    self._mark_dead(f"send failed: {e}")
                    raise WorkerCrashed(
                        f"{self.name}: apply send failed ({e})"
                    ) from e
                reply, rpayload = self._wait_reply(fid, frame, payload)
                # the clock-sync sample pairs this side's FIRST send
                # with the reply arrival; a reply to a retransmit only
                # inflates the measured delay, and an inflated sample
                # loses the min-delay race instead of skewing the offset
                self._ship_reply_telemetry(
                    reply, t_send, time.monotonic(), trace
                )
            finally:
                with self._resp_cond:
                    self._pending_fid = None
                    self._reply = None
        if reply.get("op") == "error":
            raise WorkerHandle._map_error(reply)
        try:
            return wire.payload_array(reply["meta"], rpayload)
        except (KeyError, wire.WireError) as e:
            self._mark_dead(f"malformed result: {e}")
            raise WorkerCrashed(
                f"{self.name}: malformed result frame ({e})"
            ) from e

    def _wait_reply(
        self,
        fid: str,
        frame: Optional[dict] = None,
        payload: bytes = b"",
    ) -> Tuple[dict, bytes]:
        """Block until the matching reply, the channel's death, or
        lease expiry.  No wall-clock cap beyond the lease: a worker
        that is computing keeps beating, and a beating worker holds its
        lease — the deadline belongs to the worker's own guard.

        While waiting, the request frame is RETRANSMITTED every
        ``lease_s / 2``: beats prove the peer is alive, not that this
        frame arrived, and a partition can eat exactly one frame and
        heal inside the lease window — without retransmission that
        lost apply would wait forever behind a healthy heartbeat.  The
        worker's last-reply cache makes a duplicate arrival a cached
        resend, never a recompute."""
        from keystone_tpu.obs import metrics

        interval = max(MIN_BEAT_INTERVAL_S, self.lease_s / 2.0)
        next_tx = time.monotonic() + interval
        while True:
            with self._resp_cond:
                if self._reply is not None:
                    return self._reply
                if self._closed or self._dead is not None:
                    raise WorkerCrashed(
                        f"{self.name} died mid-request "
                        f"({self._dead or 'closed'})"
                    )
                if self.lease_expired():
                    # the pending slot clears in apply's finally, so a
                    # result that limps in later is a LATE result and
                    # the reader discards it
                    raise WorkerCrashed(
                        f"{self.name}: lease expired mid-request "
                        f"({self.lease_s:.2f}s of silence) — flush {fid} "
                        f"forfeited for re-dispatch"
                    )
                self._resp_cond.wait(0.05)
                if self._reply is not None:
                    return self._reply
            if frame is not None and time.monotonic() >= next_tx:
                next_tx = time.monotonic() + interval
                metrics.inc("serve.net.retransmits", worker=self.name)
                try:
                    self._send(frame, payload)
                except OSError as e:
                    self._mark_dead(f"retransmit failed: {e}")

    # ---------------------------------------------------------- shutdown
    def kill(self) -> None:
        """Sever the channel (wedge/quarantine path).  A waiter
        unblocks with :class:`WorkerCrashed`; the worker side sees EOF
        (or fences on silence) and dials back for a fresh lease."""
        self._closed = True
        self._mark_dead(self._dead or "killed")
        try:
            self._sock.close()
        except OSError:
            pass

    def shutdown(self, timeout: float = 3.0) -> None:
        """Graceful-then-forceful: ``bye`` (worker exits its serve
        loop cleanly), short ack wait, then sever."""
        if not self._closed and self._dead is None:
            try:
                self._send({"op": "bye"})
                self._bye_ack.wait(max(0.2, timeout / 2.0))
            except OSError:
                pass
        self.kill()

    def stats(self) -> dict:
        return {
            "pid": self.pid,
            "host": self.peer_host,
            "alive": self.alive(),
            "heartbeat_age_s": round(self.heartbeat_age(), 3),
            "lease_s": self.lease_s,
            "spawn_seconds": round(self.spawn_seconds, 3),
        }


def deploy_worker(
    pool_name: str,
    index: int,
    pending: Tuple[socket.socket, dict],
    payload_bytes: bytes,
    buckets=None,
    item_shape=None,
    dtype: Optional[str] = None,
    lease_s: float = DEFAULT_LEASE_S,
    ready_timeout: float = 300.0,
    max_frame_bytes: int = wire.DEFAULT_MAX_FRAME_BYTES,
) -> NetWorkerHandle:
    """Claim one handshaked connection and deploy a generation onto it.
    On failure the connection is closed and :class:`WorkerSpawnError`
    raised — no half-born workers."""
    sock, hello = pending
    return NetWorkerHandle(
        pool_name,
        index,
        sock,
        hello,
        payload_bytes,
        buckets=buckets,
        item_shape=item_shape,
        dtype=dtype,
        lease_s=lease_s,
        ready_timeout=ready_timeout,
        max_frame_bytes=max_frame_bytes,
    )


# ------------------------------------------------------------ worker side


class ConnectRetriesExhausted(ConnectionError):
    """The worker's bounded backoff+jitter dial ladder ran out without
    reaching the router.  ``ConnectionError`` (OSError family) — the
    process exits nonzero and whatever spawned it decides."""


def _connect(
    host: str,
    port: int,
    name: str,
    attempts: int = DEFAULT_CONNECT_ATTEMPTS,
    base_delay: float = 0.2,
    max_delay: float = 5.0,
    seed: Optional[int] = None,
) -> socket.socket:
    """Dial the router with bounded exponential backoff + jitter
    (``durable.backoff_delays`` — the repo's one retry cadence) and
    send ``hello``.  Each attempt passes the ``serve.net.connect``
    fault site; an injected failure is retried like any refused dial."""
    from keystone_tpu.utils import durable

    delays = list(
        durable.backoff_delays(
            max(0, int(attempts) - 1),
            base_delay=base_delay,
            max_delay=max_delay,
            seed=seed,
        )
    )
    last: Optional[BaseException] = None
    for i in range(max(1, int(attempts))):
        sock = None
        try:
            act = faults.fault_point(
                "serve.net.connect", role="worker", link=name, host=host
            )
            if act is not None:
                # a drop/partition verdict at the connect site IS a
                # failed dial — silence, retried by the ladder like any
                # refused connection (a plan never silently does nothing)
                raise ConnectionRefusedError(
                    f"fault plan injected {act!r} at serve.net.connect"
                )
            sock = socket.create_connection((host, port), timeout=10.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # swap the dial timeout for the steady-state SEND budget;
            # reads wait via select and never touch the socket timeout
            sock.settimeout(wire.SEND_TIMEOUT_S)
            wire.send_stream_frame(
                sock,
                {
                    "op": "hello",
                    "name": name,
                    "pid": os.getpid(),
                    "host": socket.gethostname(),
                    "protocol": wire.SOCKET_VERSION,
                },
            )
            return sock
        except OSError as e:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            last = e
            if i < len(delays):
                logger.info(
                    "connect to %s:%s failed (%s); retry in %.2fs",
                    host,
                    port,
                    e,
                    delays[i],
                )
                time.sleep(delays[i])
    raise ConnectRetriesExhausted(
        f"could not reach router at {host}:{port} after {attempts} "
        f"attempts ({type(last).__name__}: {last})"
    )


def _drain_ready(
    sock, max_frame_bytes: int, wname: str
) -> Tuple[List[Tuple[dict, bytes]], bool, bool]:
    """Drain frames already queued in the kernel buffer (beats that
    landed during a long compute).  Returns ``((msg, payload) tuples in
    order, any frame arrived, channel dead)``.  Payload bytes are kept
    with their frame — a stashed apply is USUALLY a retransmit answered
    from the last-reply cache, but nothing guarantees that, and
    replaying it with an empty payload would turn a recomputable apply
    into a confusing meta/byte-count error.  This runs BEFORE the
    self-fence check so a healthy worker whose compute outlasted one
    lease window is refreshed by the beats that were waiting for it —
    only true silence fences."""
    stashed: List[Tuple[dict, bytes]] = []
    got_any = False
    while True:
        try:
            msg, payload = wire.recv_stream_frame(
                sock, timeout=0.01, max_frame_bytes=max_frame_bytes
            )
        except TimeoutError:
            return stashed, got_any, False
        except (EOFError, OSError, wire.WireError):
            return stashed, got_any, True
        act = faults.fault_point(
            "serve.net.recv", role="worker", link=wname, op=msg.get("op")
        )
        if act == "drop":
            continue  # never arrived; does not refresh the lease
        got_any = True
        if msg.get("op") != "beat":
            stashed.append((msg, payload))


def _worker_session(sock: socket.socket, name: str, cache: dict) -> str:
    """One lease's worth of service: wait for deploy, build (or reuse)
    the applier, answer applies until ``bye`` / EOF / self-fence.
    Returns the exit reason; anything but ``"bye"`` means the caller
    should dial back for a fresh lease."""
    from keystone_tpu.serve.telemetry import WorkerTelemetry
    from keystone_tpu.serve.worker import build_from_payload, classify_error
    from keystone_tpu.utils import durable, guard
    from keystone_tpu.workflow.dataset import Dataset

    # ---- wait for the router to claim this connection with a deploy
    while True:
        try:
            msg, payload = wire.recv_stream_frame(sock, timeout=1.0)
            break
        except TimeoutError:
            continue
        except (EOFError, OSError, wire.WireError):
            return "eof"
    if msg.get("op") != "deploy":
        logger.warning("%s: expected deploy, got %r", name, msg.get("op"))
        return "torn"
    spec = msg.get("spec") or {}
    lease_s = float(spec.get("lease_s") or DEFAULT_LEASE_S)
    max_frame_bytes = int(
        spec.get("max_frame_bytes") or wire.DEFAULT_MAX_FRAME_BYTES
    )
    wname = spec.get("name") or name

    send_lock = threading.Lock()
    stop = threading.Event()

    def wsend(reply: dict, rpayload: bytes = b"") -> None:
        act = faults.fault_point(
            "serve.net.send", role="worker", link=wname, op=reply.get("op")
        )
        if act == "drop":
            return
        data = wire.pack_stream_frame(reply, rpayload)
        if act == "corrupt":
            data = _corrupt_frame(data)
        with send_lock:
            sock.sendall(data)

    # ---- build the applier (or reuse a cached one: same digest ⇒ the
    # exact generation this process already built and primed)
    digest = spec.get("digest")
    t0 = time.monotonic()
    #: span capture + metrics-delta shipping, piggybacked on the frames
    #: this session already answers (ready, beat, result, error); an
    #: old router ignores the optional ``telemetry`` body key
    tel = WorkerTelemetry()
    cached = cache.get(digest) if digest else None
    try:
        if cached is not None:
            applier, installed, primed = cached[0], cached[1], 0
            logger.info("%s: reusing built applier for %s", name, digest)
        else:
            with tel.span("worker.load"):
                deploy_payload = pickle.loads(payload)
            applier, installed, primed = durable.with_retries(
                lambda: build_from_payload(deploy_payload, spec, tel=tel),
                description=f"{wname} build",
            )
            if digest:
                cache.clear()  # one generation per worker process
                cache[digest] = (applier, installed)
    except BaseException as e:
        try:
            wsend(
                {
                    "op": "fatal",
                    "etype": type(e).__name__,
                    "emsg": str(e)[:800],
                }
            )
        except OSError:
            pass
        return "fatal"
    try:
        wsend(
            {
                "op": "ready",
                "pid": os.getpid(),
                "primed": primed,
                "reused": cached is not None,
                "artifact_buckets": installed,
                "artifact_keys": _ready_artifact_keys(applier),
                "startup_seconds": round(time.monotonic() - t0, 3),
                "telemetry": tel.ship(t_rx=t0),
            }
        )
    except OSError:
        return "eof"

    def beat_loop() -> None:
        interval = _beat_interval(lease_s)
        while not stop.wait(interval):
            beat: dict = {"op": "beat"}
            # metrics deltas ride the beats the lease already requires
            # — no extra frames, bounded entries, and a quiet registry
            # ships nothing at all
            entries = tel.metrics_entries(min_interval_s=1.0)
            if entries:
                beat["telemetry"] = {"metrics": entries}
            try:
                wsend(beat)
            except OSError:
                return

    threading.Thread(target=beat_loop, daemon=True, name="net-beat").start()

    last_rx = time.monotonic()
    last_reply: Optional[Tuple[str, dict, bytes]] = None
    stashed: Deque[Tuple[dict, bytes]] = deque()
    try:
        while True:
            if stashed:
                msg, payload = stashed.popleft()
            else:
                try:
                    msg, payload = wire.recv_stream_frame(
                        sock,
                        timeout=min(0.25, _beat_interval(lease_s)),
                        max_frame_bytes=max_frame_bytes,
                    )
                except TimeoutError:
                    if time.monotonic() - last_rx > lease_s:
                        logger.warning(
                            "%s: lease lapsed (%.2fs silent); self-fencing",
                            wname,
                            lease_s,
                        )
                        return "fenced"
                    continue
                except EOFError:
                    return "eof"
                except (OSError, wire.WireError):
                    return "torn"
                act = faults.fault_point(
                    "serve.net.recv",
                    role="worker",
                    link=wname,
                    op=msg.get("op"),
                )
                if act == "drop":
                    continue  # never arrived; last_rx stays stale
                if act == "corrupt":
                    return "torn"
                last_rx = time.monotonic()
            op = msg.get("op")
            if op == "beat":
                continue
            if op == "bye":
                try:
                    wsend({"op": "bye_ack"})
                except OSError:
                    pass
                return "bye"
            if op != "apply":
                logger.warning("%s: ignoring frame %r", wname, op)
                continue
            fid = msg.get("fid")
            if last_reply is not None and last_reply[0] == fid:
                # idempotent retransmit: same flush id ⇒ the SAME
                # answer, no recompute (dispatch is at-least-once; the
                # reply cache makes it exactly-once in effect)
                try:
                    wsend(last_reply[1], last_reply[2])
                except OSError:
                    return "eof"
                continue
            t_apply = time.monotonic()
            try:
                with tel.span("worker.attach"):
                    arr = wire.payload_array(msg["meta"], payload)
                n = int(msg.get("n", arr.shape[0]))
                deadline_s = msg.get("deadline_s")
                deadline = (
                    None
                    if deadline_s is None
                    else guard.Deadline.after(float(deadline_s))
                )
                with tel.span("worker.apply", n=n):
                    out = applier(Dataset(arr, n=n), deadline=deadline)
                result = np.asarray(out.array)
                rmeta, rpayload = wire.array_payload(result)
                reply = {
                    "op": "result",
                    "fid": fid,
                    "meta": rmeta,
                    "seconds": round(time.monotonic() - t_apply, 6),
                    "telemetry": tel.ship(t_rx=t_apply),
                }
            except BaseException as e:
                reply, rpayload = {
                    "op": "error",
                    "fid": fid,
                    "kind": classify_error(e),
                    "etype": type(e).__name__,
                    "emsg": str(e)[:800],
                    "seconds": round(time.monotonic() - t_apply, 6),
                    "telemetry": tel.ship(t_rx=t_apply),
                }, b""
            # beats queued behind a long compute refresh the lease
            # BEFORE the fence verdict — only true silence fences
            more, got_any, dead = _drain_ready(sock, max_frame_bytes, wname)
            if got_any:
                last_rx = time.monotonic()
            stashed.extend(more)
            if dead:
                return "eof"
            if time.monotonic() - last_rx > lease_s:
                # SELF-FENCE: the router stopped vouching for us while
                # we computed — it has (or will have) re-dispatched
                # this flush.  Our finished result is DISCARDED, not
                # sent: a healed partition must not double-serve.
                logger.warning(
                    "%s: lease lapsed during flush %s; discarding result "
                    "and fencing",
                    wname,
                    fid,
                )
                return "fenced"
            last_reply = (fid, reply, rpayload)
            try:
                wsend(reply, rpayload)
            except OSError:
                return "eof"
    finally:
        stop.set()
        try:
            sock.close()
        except OSError:
            pass


def _ready_artifact_keys(applier) -> list:
    from keystone_tpu.serve.worker import _artifact_keys

    return _artifact_keys(applier)


def run_worker(
    address: str,
    name: Optional[str] = None,
    connect_attempts: int = DEFAULT_CONNECT_ATTEMPTS,
    max_sessions: Optional[int] = None,
    backoff_seed: Optional[int] = None,
) -> int:
    """The ``keystone worker --connect HOST:PORT`` loop: dial, hello,
    serve one lease, and — unless the router said ``bye`` — dial back
    for a fresh one.  A fenced or partitioned worker REJOINS through
    the same front door as a brand-new one: there is no special resume
    handshake to get wrong, and the applier cache makes the rejoin
    cheap (same payload digest ⇒ no rebuild, no re-prime)."""
    host, port = parse_address(address)
    wname = name or f"{socket.gethostname()}-{os.getpid()}"
    cache: dict = {}
    sessions = 0
    while True:
        try:
            sock = _connect(
                host,
                port,
                wname,
                attempts=connect_attempts,
                seed=backoff_seed,
            )
        except ConnectRetriesExhausted as e:
            if sessions:
                # the router served us once and is now unreachable:
                # it is gone, not late — exit clean so spawned workers
                # don't linger as orphans
                logger.info("router gone (%s); worker %s exiting", e, wname)
                return 0
            logger.error("%s", e)
            return 1
        reason = _worker_session(sock, wname, cache)
        sessions += 1
        logger.info(
            "worker %s session %d ended: %s", wname, sessions, reason
        )
        if reason in ("bye", "fatal"):
            return 0 if reason == "bye" else 1
        if max_sessions is not None and sessions >= max_sessions:
            return 0


from keystone_tpu.serve.fleet import Replica  # noqa: E402


class NetReplica(Replica):
    """A routing slot whose compute lives across a socket.  All
    queue/claim/breaker semantics are inherited; the lifecycle edges
    mirror :class:`~keystone_tpu.serve.procfleet.ProcessReplica` with
    "child process" replaced by "leased channel"."""

    def __init__(
        self,
        index: int,
        handle: NetWorkerHandle,
        version: str = "v0",
        pool_name: str = "serve",
        heartbeat_timeout: float = 30.0,
    ):
        super().__init__(
            index,
            RemoteApplier(handle),
            device=None,
            version=version,
            pool_name=pool_name,
            heartbeat_timeout=heartbeat_timeout,
        )
        self.handle = handle
        self._shutdown_once = threading.Lock()
        self._shut = False

    # ------------------------------------------------------------ health
    def is_dead(self) -> bool:
        """Dead = the parent worker thread crashed (base), OR the lease
        expired / channel severed while the slot is live — an idle
        worker lost to a partition must be healed without waiting for
        the next dispatch to find the silence."""
        if super().is_dead():
            return True
        return (
            not (self._retired or self.quarantined)
            and not self.handle.alive()
        )

    # --------------------------------------------------------- lifecycle
    def _on_worker_exit(self) -> None:
        self._shutdown_handle()

    def _shutdown_handle(self) -> None:
        with self._shutdown_once:
            if self._shut:
                return
            self._shut = True
        self.handle.shutdown()

    def drain_queue(self):
        """Supervisor decommission: a channel still holding a flush is
        severed so the blocked parent thread unblocks
        (:class:`WorkerCrashed`) and the far side fences/rejoins."""
        left = super().drain_queue()
        if self.inflight is not None and self.handle.alive():
            logger.warning(
                "severing wedged net worker %s (pid %s)",
                self.handle.name,
                self.handle.pid,
            )
            self.handle.kill()
        return left

    def join(self, timeout: float):
        left = super().join(timeout)
        w = self._worker
        if w is not None and w.is_alive():
            self.handle.kill()
            w.join(2.0)
        self._shutdown_handle()
        return left

    def status(self) -> dict:
        out = super().status()
        out["backend"] = "net"
        out.update(
            {
                "link": self.handle.name,
                "pid": self.handle.pid,
                "peer_host": self.handle.peer_host,
                "worker_alive": self.handle.alive(),
                "worker_heartbeat_age_s": round(
                    self.handle.heartbeat_age(), 3
                ),
                "lease_s": self.handle.lease_s,
            }
        )
        out["artifact_buckets"] = self.applier.installed_buckets()
        return out
