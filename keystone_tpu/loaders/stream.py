"""Out-of-core streaming input helpers.

The reference streams data through Spark partitions (RDD iterators,
executor-side decode — SURVEY.md §2.5, §3.4); the TPU equivalent feeds
the chip from host shards with decode/transform on host threads
overlapping device compute (the role grain plays in TPU stacks;
implemented here directly since grain isn't in this image).

The user-facing out-of-core type is
:class:`keystone_tpu.workflow.dataset.StreamDataset`; this module holds
the host-side building blocks loaders use to construct one:

- :func:`batched` — re-iterable batch source over an in-memory array;
- :func:`prefetched` — wrap any re-iterable batch source so host work
  (decode, transforms) runs on a background thread one batch ahead of
  the consumer;
- :func:`resilient` — wrap any re-iterable batch source with bounded
  per-batch retry (exponential backoff) plus a ``max_bad_batches`` drop
  quota, so flaky storage degrades instead of killing the fit.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Iterator, Optional

import numpy as np

from keystone_tpu.faults import fault_point
from keystone_tpu.obs import metrics

logger = logging.getLogger(__name__)


def _deadline_exceeded_type():
    """Lazy accessor for guard.DeadlineExceeded (resilient() must stay
    usable before utils.guard — and its obs imports — are loaded when no
    timeout is configured)."""
    from keystone_tpu.utils.guard import DeadlineExceeded

    return DeadlineExceeded


def batched(array: np.ndarray, batch_size: int) -> Callable[[], Iterator[np.ndarray]]:
    """Re-iterable batch source over an in-memory array.  Carries the
    ``stream.batch`` fault site so chaos plans can flake any pipeline
    built on in-memory batching (the demo/test source every --stream
    app can fall back to)."""

    def gen():
        for i in range(0, len(array), batch_size):
            t0 = time.perf_counter()
            fault_point("stream.batch", index=i // batch_size)
            batch = array[i : i + batch_size]
            metrics.observe(
                "stream.batch_seconds", time.perf_counter() - t0,
                source="batched",
            )
            yield batch

    return gen


def resilient(
    source,
    retries: int = 2,
    max_bad_batches: int = 0,
    base_delay: float = 0.05,
    max_delay: float = 1.0,
    sleep: Callable[[float], None] = time.sleep,
    timeout: Optional[float] = None,
) -> Callable[[], Iterator]:
    """Re-iterable batch source that survives transient per-batch
    failures (the Spark-task-retry analogue for input streams).

    A failed fetch is retried up to ``retries`` times with exponential
    backoff; each retry re-creates the underlying iterator (``source``
    must be re-iterable, this module's standing contract) and replays to
    the failed position.  A batch that still fails with its retries
    exhausted is DROPPED against the ``max_bad_batches`` quota — the
    reference tolerated lost partitions the same way, by bounded data
    loss rather than job death — and once the quota is spent the last
    error propagates.  ``max_bad_batches=0`` (default) means retry-only:
    transient flakiness is absorbed, deterministic failure still fails
    the fit.

    ``timeout`` (seconds, per batch fetch): a watchdog around each
    ``next()`` — a source that silently HANGS (stuck NFS read, wedged
    decoder) raises ``utils.guard.DeadlineExceeded``, an ``OSError``,
    so it is retried and then counted against ``max_bad_batches``
    exactly like a raising batch, instead of blocking the iterator
    forever.  The fetch runs on a watchdog worker thread only when a
    timeout is configured (default None: same-thread, zero overhead);
    after a timeout the suspect iterator is abandoned and a fresh one
    replays, per the retry contract above.  Costs to know about: each
    guarded fetch spawns one short-lived thread (~tens of µs — noise
    against ms-scale batch decode, but don't configure timeouts on
    microsecond-batch sources), and each ABANDONED fetch parks a daemon
    thread in ``next()`` until the source wakes — bounded by
    ``retries + max_bad_batches`` per stream, never unbounded.

    A source that ends BEFORE the replay position raises rather than
    silently truncating the stream.  One ambiguity is undetectable from
    the iterator protocol alone: a plain generator dies at the batch
    that raised, so a DROPPED batch on a generator source ends the
    stream at the drop point (observationally identical to a source
    whose final batch was bad) — it is logged loudly, and exact-n
    consumers (``FeatureBlockStore.from_batches``) still fail on the row
    shortfall.  A nonzero drop quota therefore wants batch-resumable
    iterators (e.g. file-per-batch readers), where fetches after a
    failed batch keep working.

    Note: dropped batches shrink the delivered row count, so only
    consumers that tolerate ragged totals (df sweeps, statistics) should
    run with a nonzero quota; exact-n consumers (FeatureBlockStore
    spills) keep the default.
    """
    if not callable(source) and iter(source) is source:
        raise ValueError(
            "resilient() needs a re-iterable source: pass a callable "
            "returning a fresh iterator (or a list of batches), not a "
            "one-shot generator/iterator"
        )

    def gen():
        delivered = 0  # batches yielded to the consumer
        dropped = set()  # absolute indices written off against the quota
        attempt = 0  # failures of the batch at `attempt_idx`
        attempt_idx = -1  # the budget is PER BATCH, not pooled
        swallowed_last = False  # previous fetch was a dropped batch failing
        stall = 0  # consecutive restarts with zero progress
        progress_mark = None  # (delivered, len(dropped)) at last restart
        last_err = None  # the exception that ended the previous cycle
        while True:
            # a restart cycle that neither delivered nor dropped anything
            # AND ended in a fetch timeout is spinning (e.g. a dropped
            # batch that HANGS on every replay — it cannot be skipped,
            # only re-executed): fail loudly after a bounded number of
            # such cycles instead of paying one timeout per cycle
            # forever.  Raise-y transient failures are exempt — their
            # budget is PER BATCH (the module's documented contract),
            # and alternating failures across different replay batches
            # must not pool into one abort.
            mark = (delivered, len(dropped))
            barren = progress_mark is not None and mark == progress_mark
            if not barren:
                stall = 0
            elif timeout is not None and isinstance(
                last_err, _deadline_exceeded_type()
            ):
                stall += 1
                if stall > retries:
                    raise last_err
            progress_mark = mark
            src = source() if callable(source) else iter(source)
            pos = 0  # absolute index of the next fetch from this iterator
            restart = False
            while not restart:
                # everything before `target` was already handled: either
                # delivered to the consumer (replayed silently) or
                # dropped (its failure swallowed)
                target = delivered + len(dropped)
                idx = pos
                t_fetch = time.perf_counter()
                try:
                    if timeout is None:
                        batch = next(src)
                    else:
                        from keystone_tpu.utils import guard

                        batch = guard.run_with_deadline(
                            lambda: next(src),
                            guard.Deadline.after(timeout),
                            site="stream.batch",
                            index=idx,
                        )
                    metrics.observe(
                        "stream.batch_seconds",
                        time.perf_counter() - t_fetch,
                        source="resilient",
                    )
                    pos += 1
                    swallowed_last = False
                except StopIteration:
                    if idx < target:
                        raise RuntimeError(
                            f"stream source ended at batch {idx} while "
                            f"replaying to batch {target}: the source "
                            "shrank (or a non-resumable iterator died on "
                            "a dropped batch) — refusing to silently "
                            "truncate the stream"
                        )
                    if swallowed_last:
                        # undetectable generator-death-vs-final-bad-batch
                        # ambiguity (see docstring): be loud about it
                        logger.warning(
                            "stream ended immediately after dropped batch "
                            "%d; if the source is a plain generator its "
                            "remaining batches are unreachable (use a "
                            "batch-resumable iterator with "
                            "max_bad_batches)",
                            idx - 1,
                        )
                    return
                except Exception as e:
                    pos += 1
                    last_err = e
                    # a timed-out fetch may leave the abandoned watchdog
                    # worker still INSIDE next(src) — pulling more from
                    # that iterator would blow up ("generator already
                    # executing") and charge the error to the next
                    # healthy batch.  The drop/swallow paths WANT to
                    # continue the same iterator (that is how a
                    # batch-resumable source skips past a bad batch), so
                    # give the worker a short grace to vacate — cancel-
                    # aware work exits promptly — and only fall back to
                    # a fresh-iterator replay when it is truly stuck.
                    occupied = False
                    if timeout is not None and isinstance(
                        e, _deadline_exceeded_type()
                    ):
                        w = getattr(e, "worker", None)
                        if w is not None:
                            w.join(min(1.0, timeout))
                        occupied = w is None or w.is_alive()
                    if idx in dropped:
                        swallowed_last = True
                        if occupied:
                            restart = True
                        continue  # a written-off batch failing again
                    swallowed_last = False
                    if idx != attempt_idx:
                        attempt_idx, attempt = idx, 0
                    attempt += 1
                    if attempt <= retries:
                        metrics.inc("stream.retries")
                        delay = min(
                            max_delay, base_delay * (2.0 ** (attempt - 1))
                        )
                        logger.warning(
                            "stream batch %d failed (%s); retry %d/%d "
                            "in %.2fs",
                            idx,
                            e,
                            attempt,
                            retries,
                            delay,
                        )
                        sleep(delay)
                        # the iterator is suspect after an exception:
                        # restart fresh and replay rather than pull more
                        restart = True
                        continue
                    if idx >= target and len(dropped) < max_bad_batches:
                        dropped.add(idx)
                        metrics.inc("stream.bad_batches")
                        attempt_idx, attempt = -1, 0
                        # if the source is a dead generator, the next
                        # fetch is StopIteration — flag it so the
                        # truncation warning above fires
                        swallowed_last = True
                        logger.warning(
                            "stream batch %d failed %d times; dropping "
                            "it (%d/%d bad-batch quota used)",
                            idx,
                            retries + 1,
                            len(dropped),
                            max_bad_batches,
                        )
                        if occupied:
                            restart = True  # see timeout note above
                        continue
                    # out of quota — or an already-DELIVERED batch failed
                    # its replay (dropping it would desync the consumer)
                    raise
                else:
                    if idx == attempt_idx:
                        # the batch that was failing came through
                        attempt_idx, attempt = -1, 0
                    if idx < target:
                        continue  # replaying an already-delivered batch
                    yield batch
                    delivered += 1

    return gen


def prefetched(
    source,
    transform: Optional[Callable] = None,
    prefetch: int = 2,
) -> Callable[[], Iterator]:
    """Re-iterable source whose host work runs on a producer thread.

    ``source``: an iterable of host batches, or a callable returning a
    fresh iterator (required for re-iteration).  Each batch is passed
    through ``transform`` on the worker thread, then handed to the
    consumer through a bounded queue (``prefetch`` deep) so decode
    overlaps device compute.  Worker exceptions re-raise in the
    consumer.
    """
    depth = max(1, int(prefetch))

    def gen():
        q: "queue.Queue" = queue.Queue(maxsize=depth)
        sentinel = object()
        stop = threading.Event()
        err: list = []

        def put(item) -> bool:
            # bounded put that gives up when the consumer abandoned the
            # generator — otherwise the thread would park forever on a
            # full queue, pinning decoded host batches
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                src = source() if callable(source) else iter(source)
                for batch in src:
                    if transform is not None:
                        batch = transform(batch)
                    if stop.is_set() or not put(batch):
                        return
            except BaseException as e:  # surface worker errors to consumer
                err.append(e)
            finally:
                put(sentinel)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()

    return gen


def stream_labeled(labeled, batch_size: int, prefetch: int = 0):
    """Wrap an in-memory LabeledData's features as a StreamDataset (the
    demo/test path apps use for --stream without real files): memory
    still holds the source array, but the streaming fit paths engage."""
    from keystone_tpu.loaders.labeled import LabeledData
    from keystone_tpu.workflow.dataset import StreamDataset

    return LabeledData(
        StreamDataset(
            batched(labeled.data.numpy(), batch_size),
            n=labeled.data.n,
            prefetch=prefetch,
        ),
        labeled.labels,
    )


def require_stream_test_path(config) -> None:
    """Apps with --stream must be given an explicit test set: evaluating
    on the training source would eagerly load the data streaming exists
    to avoid."""
    if config.stream and config.train_path and not config.test_path:
        raise ValueError(
            "--stream needs --test-path: evaluating on the training "
            "source would eagerly load the data streaming exists to avoid"
        )


def resolve_train_source(config, load, stream, synthetic):
    """The 4-way train-source selection shared by the --stream apps:
    real+stream, real, synthetic-as-stream (demo path), synthetic."""
    if config.stream and config.train_path:
        return stream(config.train_path, batch_size=config.stream_batch_size)
    if config.train_path:
        return load(config.train_path)
    if config.stream:
        return stream_labeled(synthetic(), config.stream_batch_size)
    return synthetic()


def add_stream_args(parser, default_batch_size: int, noun: str) -> None:
    """The --stream/--stream-batch-size argparse block the apps share."""
    parser.add_argument(
        "--stream",
        "--out-of-core",
        action="store_true",
        dest="stream",
        help=f"re-read {noun} from disk per sweep (requires --test-path); "
        "fits run out-of-core",
    )
    parser.add_argument(
        "--stream-batch-size", type=int, default=default_batch_size
    )
