"""Out-of-core streaming input helpers.

The reference streams data through Spark partitions (RDD iterators,
executor-side decode — SURVEY.md §2.5, §3.4); the TPU equivalent feeds
the chip from host shards with decode/transform on host threads
overlapping device compute (the role grain plays in TPU stacks;
implemented here directly since grain isn't in this image).

The user-facing out-of-core type is
:class:`keystone_tpu.workflow.dataset.StreamDataset`; this module holds
the host-side building blocks loaders use to construct one:

- :func:`batched` — re-iterable batch source over an in-memory array;
- :func:`prefetched` — wrap any re-iterable batch source so host work
  (decode, transforms) runs on a background thread one batch ahead of
  the consumer.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional

import numpy as np


def batched(array: np.ndarray, batch_size: int) -> Callable[[], Iterator[np.ndarray]]:
    """Re-iterable batch source over an in-memory array."""

    def gen():
        for i in range(0, len(array), batch_size):
            yield array[i : i + batch_size]

    return gen


def prefetched(
    source,
    transform: Optional[Callable] = None,
    prefetch: int = 2,
) -> Callable[[], Iterator]:
    """Re-iterable source whose host work runs on a producer thread.

    ``source``: an iterable of host batches, or a callable returning a
    fresh iterator (required for re-iteration).  Each batch is passed
    through ``transform`` on the worker thread, then handed to the
    consumer through a bounded queue (``prefetch`` deep) so decode
    overlaps device compute.  Worker exceptions re-raise in the
    consumer.
    """
    depth = max(1, int(prefetch))

    def gen():
        q: "queue.Queue" = queue.Queue(maxsize=depth)
        sentinel = object()
        stop = threading.Event()
        err: list = []

        def put(item) -> bool:
            # bounded put that gives up when the consumer abandoned the
            # generator — otherwise the thread would park forever on a
            # full queue, pinning decoded host batches
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                src = source() if callable(source) else iter(source)
                for batch in src:
                    if transform is not None:
                        batch = transform(batch)
                    if stop.is_set() or not put(batch):
                        return
            except BaseException as e:  # surface worker errors to consumer
                err.append(e)
            finally:
                put(sentinel)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()

    return gen


def stream_labeled(labeled, batch_size: int, prefetch: int = 0):
    """Wrap an in-memory LabeledData's features as a StreamDataset (the
    demo/test path apps use for --stream without real files): memory
    still holds the source array, but the streaming fit paths engage."""
    from keystone_tpu.loaders.labeled import LabeledData
    from keystone_tpu.workflow.dataset import StreamDataset

    return LabeledData(
        StreamDataset(
            batched(labeled.data.numpy(), batch_size),
            n=labeled.data.n,
            prefetch=prefetch,
        ),
        labeled.labels,
    )


def require_stream_test_path(config) -> None:
    """Apps with --stream must be given an explicit test set: evaluating
    on the training source would eagerly load the data streaming exists
    to avoid."""
    if config.stream and config.train_path and not config.test_path:
        raise ValueError(
            "--stream needs --test-path: evaluating on the training "
            "source would eagerly load the data streaming exists to avoid"
        )


def resolve_train_source(config, load, stream, synthetic):
    """The 4-way train-source selection shared by the --stream apps:
    real+stream, real, synthetic-as-stream (demo path), synthetic."""
    if config.stream and config.train_path:
        return stream(config.train_path, batch_size=config.stream_batch_size)
    if config.train_path:
        return load(config.train_path)
    if config.stream:
        return stream_labeled(synthetic(), config.stream_batch_size)
    return synthetic()


def add_stream_args(parser, default_batch_size: int, noun: str) -> None:
    """The --stream/--stream-batch-size argparse block the apps share."""
    parser.add_argument(
        "--stream",
        "--out-of-core",
        action="store_true",
        dest="stream",
        help=f"re-read {noun} from disk per sweep (requires --test-path); "
        "fits run out-of-core",
    )
    parser.add_argument(
        "--stream-batch-size", type=int, default=default_batch_size
    )
