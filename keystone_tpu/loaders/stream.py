"""Out-of-core streaming input pipeline.

The reference streams data through Spark partitions (RDD iterators,
executor-side decode — SURVEY.md §2.5, §3.4); the TPU equivalent feeds the
chip from host shards with decode/transform on host threads overlapping
device compute (the role grain plays in TPU stacks; implemented here
directly since grain isn't in this image — double-buffered producer
threads + ``jax.device_put`` onto the mesh's 'data' sharding).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from keystone_tpu.parallel import mesh as _mesh


class ShardedBatchStream:
    """Iterate device-resident batches from a host record source.

    source: an iterable of numpy batches (or a callable returning such an
    iterator, so the stream is re-iterable).  Each batch is host-processed
    by ``transform`` on a worker thread, then device_put with the batch
    axis sharded over 'data'.
    """

    def __init__(
        self,
        source,
        transform: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        prefetch: int = 2,
    ):
        self._source = source
        self._transform = transform
        self._prefetch = max(1, int(prefetch))

    def _iterator(self) -> Iterator[np.ndarray]:
        src = self._source() if callable(self._source) else iter(self._source)
        return iter(src)

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self._prefetch)
        sentinel = object()
        err: list = []

        def produce():
            try:
                for batch in self._iterator():
                    if self._transform is not None:
                        batch = self._transform(batch)
                    q.put(batch)
            except BaseException as e:  # surface worker errors to consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                if err:
                    raise err[0]
                return
            yield _mesh.shard_batch(item)


def batched(array: np.ndarray, batch_size: int) -> Callable[[], Iterator[np.ndarray]]:
    """Re-iterable batch source over an in-memory array."""

    def gen():
        for i in range(0, len(array), batch_size):
            yield array[i : i + batch_size]

    return gen
