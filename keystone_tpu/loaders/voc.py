"""PASCAL VOC 2007 loader (reference loaders/VOCLoader.scala): JPEG images
+ multilabel annotations (20 classes; an image carries every class whose
XML annotation names it)."""

from __future__ import annotations

import os
import tarfile
import xml.etree.ElementTree as ET
from typing import List, Optional, Tuple

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset

VOC_CLASSES = [
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
]
NUM_CLASSES = len(VOC_CLASSES)


class VOCLoader:
    @staticmethod
    def load(
        images_dir: str,
        annotations_dir: str,
        size: Tuple[int, int] = (256, 256),
        limit: Optional[int] = None,
    ) -> LabeledData:
        from keystone_tpu.loaders.imagenet import _decode_jpeg

        cls_index = {c: i for i, c in enumerate(VOC_CLASSES)}
        images, labels = [], []
        for fname in sorted(os.listdir(annotations_dir)):
            if not fname.endswith(".xml"):
                continue
            stem = os.path.splitext(fname)[0]
            jpg = os.path.join(images_dir, stem + ".jpg")
            if not os.path.exists(jpg):
                continue
            tree = ET.parse(os.path.join(annotations_dir, fname))
            multilabel = np.zeros((NUM_CLASSES,), np.float32)
            for obj in tree.findall(".//object/name"):
                idx = cls_index.get(obj.text)
                if idx is not None:
                    multilabel[idx] = 1.0
            with open(jpg, "rb") as f:
                images.append(_decode_jpeg(f.read(), size))
            labels.append(multilabel)
            if limit is not None and len(images) >= limit:
                break
        x = np.stack(images) if images else np.zeros((0, *size, 3), np.uint8)
        y = np.stack(labels) if labels else np.zeros((0, NUM_CLASSES), np.float32)
        name = (
            f"voc:{os.path.abspath(images_dir)}:{os.path.abspath(annotations_dir)}"
            f":{size[0]}x{size[1]}:lim{limit}"
        )
        return LabeledData(
            Dataset(x, name=name), Dataset(y, name=name + "-labels")
        )

    @staticmethod
    def synthetic(
        n: int = 48, size: Tuple[int, int] = (64, 64), seed: int = 0
    ) -> LabeledData:
        from keystone_tpu.loaders.imagenet import ImageNetLoader

        base = ImageNetLoader.synthetic(n=n, num_classes=NUM_CLASSES, size=size, seed=seed)
        single = base.labels.numpy()
        multi = np.zeros((n, NUM_CLASSES), np.float32)
        multi[np.arange(n), single] = 1.0
        # occasionally add a second label, as VOC images are multilabel
        rng = np.random.default_rng(seed + 1)
        extra = rng.integers(0, NUM_CLASSES, size=n)
        mask = rng.random(n) < 0.3
        multi[np.arange(n)[mask], extra[mask]] = 1.0
        return LabeledData(
            base.data,
            Dataset(multi, name=f"voc-synth-multilabels-n{n}-s{seed}"),
        )
