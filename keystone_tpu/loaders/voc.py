"""PASCAL VOC 2007 loader (reference loaders/VOCLoader.scala): JPEG images
+ multilabel annotations (20 classes; an image carries every class whose
XML annotation names it)."""

from __future__ import annotations

import os
import tarfile
import xml.etree.ElementTree as ET
from typing import List, Optional, Sequence, Tuple

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset

VOC_CLASSES = [
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
]
NUM_CLASSES = len(VOC_CLASSES)


class VOCLoader:
    @staticmethod
    def index(
        images_dir: str, annotations_dir: str
    ) -> Tuple[List[str], List[np.ndarray]]:
        """The cheap XML pass: (jpg paths, multilabels) in sorted
        annotation order.  Callers doing a train/test index split pass
        the result back to :meth:`load`/:meth:`stream` via ``index=`` so
        the directory is parsed exactly once."""
        return _index(images_dir, annotations_dir)

    @staticmethod
    def load(
        images_dir: str,
        annotations_dir: str,
        size: Tuple[int, int] = (256, 256),
        limit: Optional[int] = None,
        indices: Optional[Sequence[int]] = None,
        index=None,
    ) -> LabeledData:
        paths, labels = index if index is not None else _index(
            images_dir, annotations_dir
        )
        if indices is not None:
            paths = [paths[i] for i in indices]
            labels = [labels[i] for i in indices]
        if limit is not None:
            paths, labels = paths[:limit], labels[:limit]
        x = (
            _decode_paths(paths, size)
            if paths
            else np.zeros((0, *size, 3), np.uint8)
        )
        y = np.stack(labels) if labels else np.zeros((0, NUM_CLASSES), np.float32)
        # the subset is part of the dataset IDENTITY: names feed CSE and
        # saved-state keys, and two subsets of one directory must never
        # alias (stream() carries the same tag)
        name = (
            f"voc:{os.path.abspath(images_dir)}:{os.path.abspath(annotations_dir)}"
            f":{size[0]}x{size[1]}:lim{limit}{_idx_tag(indices, len(paths))}"
        )
        return LabeledData(
            Dataset(x, name=name), Dataset(y, name=name + "-labels")
        )

    @staticmethod
    def stream(
        images_dir: str,
        annotations_dir: str,
        size: Tuple[int, int] = (256, 256),
        batch_size: int = 64,
        prefetch: int = 2,
        indices: Optional[Sequence[int]] = None,
        index=None,
    ) -> LabeledData:
        """Out-of-core loader: one cheap XML pass fixes the file list and
        multilabels; JPEGs re-decode from disk in ``batch_size`` chunks
        per sweep on a prefetch thread.  ``indices`` selects a subset of
        the sorted annotation order (the app's train/test split streams
        the train rows while the eager test load takes the complement);
        ``index`` reuses a precomputed :meth:`index` result."""
        from keystone_tpu.workflow.dataset import StreamDataset

        paths, labels = index if index is not None else _index(
            images_dir, annotations_dir
        )
        if indices is not None:
            paths = [paths[i] for i in indices]
            labels = [labels[i] for i in indices]
        n = len(paths)

        def batches():
            for i in range(0, n, batch_size):
                yield _decode_paths(paths[i : i + batch_size], size)

        name = (
            f"voc-stream:{os.path.abspath(images_dir)}"
            f":{os.path.abspath(annotations_dir)}:{size[0]}x{size[1]}"
            f":b{batch_size}{_idx_tag(indices, n)}"
        )
        y = (
            np.stack(labels)
            if labels
            else np.zeros((0, NUM_CLASSES), np.float32)
        )
        return LabeledData(
            StreamDataset(batches, n, name=name, prefetch=prefetch),
            Dataset(y, name=name + "-labels"),
        )

    @staticmethod
    def synthetic(
        n: int = 48, size: Tuple[int, int] = (64, 64), seed: int = 0
    ) -> LabeledData:
        from keystone_tpu.loaders.imagenet import ImageNetLoader

        base = ImageNetLoader.synthetic(n=n, num_classes=NUM_CLASSES, size=size, seed=seed)
        multi = _synthetic_multilabels(base.labels.numpy(), n, seed)
        return LabeledData(
            base.data,
            Dataset(multi, name=f"voc-synth-multilabels-n{n}-s{seed}"),
        )

    @staticmethod
    def synthetic_stream(
        n: int = 48,
        size: Tuple[int, int] = (64, 64),
        seed: int = 0,
        batch_size: int = 32,
        prefetch: int = 2,
    ) -> LabeledData:
        """Streaming variant of :meth:`synthetic` — pixel- and
        label-identical to it for the same (n, size, seed); images
        materialize ``batch_size`` at a time (the stream==in-memory
        parity convention every loader follows)."""
        from keystone_tpu.loaders.imagenet import ImageNetLoader

        base = ImageNetLoader.synthetic_stream(
            n=n,
            num_classes=NUM_CLASSES,
            size=size,
            seed=seed,
            batch_size=batch_size,
            prefetch=prefetch,
        )
        multi = _synthetic_multilabels(base.labels.numpy(), n, seed)
        return LabeledData(
            base.data,
            Dataset(multi, name=f"voc-synth-stream-multilabels-n{n}-s{seed}"),
        )


def _idx_tag(indices, n: int) -> str:
    """Subset identity tag for Dataset names.  ``hash`` on an int tuple
    is deterministic across processes (no PYTHONHASHSEED effect)."""
    if indices is None:
        return ""
    return f":idx{n}-{hash(tuple(int(i) for i in indices)) & 0xFFFFFFFF:08x}"


def _synthetic_multilabels(single: np.ndarray, n: int, seed: int) -> np.ndarray:
    """Multilabels from per-image class ids, shared by synthetic() and
    synthetic_stream() so the two are label-identical."""
    multi = np.zeros((n, NUM_CLASSES), np.float32)
    multi[np.arange(n), single] = 1.0
    # occasionally add a second label, as VOC images are multilabel
    rng = np.random.default_rng(seed + 1)
    extra = rng.integers(0, NUM_CLASSES, size=n)
    mask = rng.random(n) < 0.3
    multi[np.arange(n)[mask], extra[mask]] = 1.0
    return multi


def _index(
    images_dir: str, annotations_dir: str
) -> Tuple[List[str], List[np.ndarray]]:
    """One XML pass shared by load() and stream(): (jpg paths,
    multilabels), in sorted-annotation order."""
    cls_index = {c: i for i, c in enumerate(VOC_CLASSES)}
    paths: List[str] = []
    labels: List[np.ndarray] = []
    for fname in sorted(os.listdir(annotations_dir)):
        if not fname.endswith(".xml"):
            continue
        stem = os.path.splitext(fname)[0]
        jpg = os.path.join(images_dir, stem + ".jpg")
        if not os.path.exists(jpg):
            continue
        tree = ET.parse(os.path.join(annotations_dir, fname))
        multilabel = np.zeros((NUM_CLASSES,), np.float32)
        for obj in tree.findall(".//object/name"):
            idx = cls_index.get(obj.text)
            if idx is not None:
                multilabel[idx] = 1.0
        paths.append(jpg)
        labels.append(multilabel)
    return paths, labels


def _decode_paths(paths: List[str], size: Tuple[int, int]) -> np.ndarray:
    """Batch-decode JPEG files, shared by load() and stream() so their
    pixels cannot drift: threaded libjpeg when the native library is
    present, PIL fallback; an undecodable file becomes a zero image with
    a warning (the index already fixed row/label alignment)."""
    import logging

    from keystone_tpu import native
    from keystone_tpu.loaders.imagenet import _decode_jpeg

    blobs = []
    for p in paths:
        with open(p, "rb") as f:
            blobs.append(f.read())
    out = np.zeros((len(paths), *size, 3), np.uint8)
    decoded = native.decode_jpegs(blobs, size)
    if decoded is not None:
        imgs, ok = decoded
        for j, p in enumerate(paths):
            if ok[j]:
                out[j] = imgs[j]
            else:
                logging.getLogger(__name__).warning(
                    "undecodable JPEG %s; substituting a zero image", p
                )
        return out
    for j, p in enumerate(paths):
        try:
            out[j] = _decode_jpeg(blobs[j], size)
        except Exception:
            logging.getLogger(__name__).warning(
                "undecodable JPEG %s; substituting a zero image", p
            )
    return out
