"""Amazon reviews loader (reference loaders/AmazonReviewsDataLoader.scala):
JSON-lines reviews; binary label = rating ≥ 4 (the reference thresholds
star ratings for its binary classification pipeline)."""

from __future__ import annotations

import json
import os

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset


class AmazonReviewsDataLoader:
    @staticmethod
    def load(path: str, threshold: float = 3.5) -> LabeledData:
        texts, labels = [], []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                texts.append(rec.get("reviewText", rec.get("text", "")))
                rating = float(rec.get("overall", rec.get("rating", 0.0)))
                labels.append(1 if rating > threshold else 0)
        name = f"amazon:{os.path.abspath(path)}:t{threshold}"
        return LabeledData(
            Dataset(texts, name=name),
            Dataset(np.asarray(labels, np.int32), name=name + "-labels"),
        )

    @staticmethod
    def stream(
        path: str,
        threshold: float = 3.5,
        batch_size: int = 1024,
        prefetch: int = 2,
    ) -> LabeledData:
        """Out-of-core loader: one pass parses only the ratings (labels,
        4 bytes/review); review TEXTS re-parse from the JSON-lines file
        in ``batch_size`` chunks per sweep through a host StreamDataset."""
        from keystone_tpu.workflow.dataset import StreamDataset

        labels = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                rating = float(rec.get("overall", rec.get("rating", 0.0)))
                labels.append(1 if rating > threshold else 0)
        n = len(labels)

        def batches():
            chunk = []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    chunk.append(rec.get("reviewText", rec.get("text", "")))
                    if len(chunk) == batch_size:
                        yield chunk
                        chunk = []
            if chunk:
                yield chunk

        name = f"amazon-stream:{os.path.abspath(path)}:t{threshold}:b{batch_size}"
        return LabeledData(
            StreamDataset(batches, n, name=name, prefetch=prefetch, host=True),
            Dataset(np.asarray(labels, np.int32), name=name + "-labels"),
        )

    @staticmethod
    def synthetic(n: int = 600, seed: int = 0) -> LabeledData:
        rng = np.random.default_rng(seed)
        pos = ["great", "excellent", "love", "perfect", "amazing", "best"]
        neg = ["terrible", "broken", "waste", "awful", "disappointed", "worst"]
        neutral = [f"filler{i}" for i in range(40)]
        texts, labels = [], []
        for _ in range(n):
            lab = int(rng.integers(0, 2))
            vocab = pos if lab else neg
            words = list(rng.choice(vocab, size=int(rng.integers(3, 8)))) + list(
                rng.choice(neutral, size=int(rng.integers(10, 25)))
            )
            rng.shuffle(words)
            texts.append(" ".join(words))
            labels.append(lab)
        name = f"amazon-synth-n{n}-s{seed}"
        return LabeledData(
            Dataset(texts, name=name),
            Dataset(np.asarray(labels, np.int32), name=name + "-labels"),
        )
