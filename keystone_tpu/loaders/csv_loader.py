"""CSV row loader (reference loaders/CsvDataLoader.scala)."""

from __future__ import annotations

import os

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset


def _read_csv_matrix(path: str, delimiter: str) -> np.ndarray:
    """Native mmap parser when available (comma-delimited), numpy fallback."""
    if delimiter == ",":
        from keystone_tpu import native

        mat = native.read_csv(path)
        if mat is not None:
            return mat
    mat = np.loadtxt(path, delimiter=delimiter, dtype=np.float32)
    if mat.ndim == 1:
        mat = mat[None, :]
    return mat


class CsvDataLoader:
    """CSV rows → feature vectors; optionally the first column is the label
    (the MNIST pipeline's input format: label, 784 pixels)."""

    @staticmethod
    def load(path: str, label_col: int = 0, delimiter: str = ",") -> LabeledData:
        mat = _read_csv_matrix(path, delimiter)
        labels = mat[:, label_col].astype(np.int32)
        feats = np.delete(mat, label_col, axis=1)
        name = f"csv:{os.path.abspath(path)}:l{label_col}:d{delimiter!r}"
        return LabeledData(
            Dataset(feats, name=name), Dataset(labels, name=name + "-labels")
        )

    @staticmethod
    def load_unlabeled(path: str, delimiter: str = ",") -> Dataset:
        return Dataset(
            _read_csv_matrix(path, delimiter),
            name=f"csv:{os.path.abspath(path)}:d{delimiter!r}",
        )
