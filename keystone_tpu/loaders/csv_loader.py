"""CSV row loader (reference loaders/CsvDataLoader.scala)."""

from __future__ import annotations

import os

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset


def _read_csv_matrix(path: str, delimiter: str) -> np.ndarray:
    """Native mmap parser when available (comma-delimited), numpy fallback."""
    if delimiter == ",":
        from keystone_tpu import native

        mat = native.read_csv(path)
        if mat is not None:
            return mat
    mat = np.loadtxt(path, delimiter=delimiter, dtype=np.float32)
    if mat.ndim == 1:
        mat = mat[None, :]
    return mat


class CsvDataLoader:
    """CSV rows → feature vectors; optionally the first column is the label
    (the MNIST pipeline's input format: label, 784 pixels)."""

    @staticmethod
    def load(path: str, label_col: int = 0, delimiter: str = ",") -> LabeledData:
        mat = _read_csv_matrix(path, delimiter)
        labels = mat[:, label_col].astype(np.int32)
        feats = np.delete(mat, label_col, axis=1)
        name = f"csv:{os.path.abspath(path)}:l{label_col}:d{delimiter!r}"
        return LabeledData(
            Dataset(feats, name=name), Dataset(labels, name=name + "-labels")
        )

    @staticmethod
    def load_unlabeled(path: str, delimiter: str = ",") -> Dataset:
        return Dataset(
            _read_csv_matrix(path, delimiter),
            name=f"csv:{os.path.abspath(path)}:d{delimiter!r}",
        )

    @staticmethod
    def stream(
        path: str,
        label_col: int = 0,
        delimiter: str = ",",
        batch_size: int = 4096,
        prefetch: int = 2,
    ) -> LabeledData:
        """Out-of-core loader: one cheap line pass reads only the label
        column and fixes ``n``; features re-parse from disk in
        ``batch_size``-row chunks each time a stage sweeps the data."""
        from keystone_tpu.workflow.dataset import StreamDataset

        labels = []
        with open(path) as f:
            for line in f:
                if line.strip():
                    labels.append(float(line.split(delimiter)[label_col]))
        labels = np.asarray(labels, np.float32).astype(np.int32)
        n = len(labels)

        def batches():
            buf = []
            with open(path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    buf.append(line)
                    if len(buf) == batch_size:
                        yield _parse_lines(buf, label_col, delimiter)
                        buf = []
            if buf:
                yield _parse_lines(buf, label_col, delimiter)

        name = (
            f"csv-stream:{os.path.abspath(path)}:l{label_col}"
            f":d{delimiter!r}:b{batch_size}"
        )
        return LabeledData(
            StreamDataset(batches, n, name=name, prefetch=prefetch),
            Dataset(labels, name=name + "-labels"),
        )


def _parse_lines(lines, label_col: int, delimiter: str) -> np.ndarray:
    mat = np.loadtxt(lines, delimiter=delimiter, dtype=np.float32, ndmin=2)
    return np.delete(mat, label_col, axis=1)
