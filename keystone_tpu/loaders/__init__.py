"""Dataset loaders (reference src/main/scala/loaders/).

Every loader returns :class:`LabeledData` (label + datum Datasets, the
loaders/LabeledData.scala analogue).  Because this environment ships no
datasets, each loader also has a ``synthetic(...)`` constructor producing
statistically-plausible data with the real format's shapes — pipelines
and benchmarks run against these when the real files are absent.
"""

from keystone_tpu.loaders.labeled import LabeledData  # noqa: F401
from keystone_tpu.loaders.csv_loader import CsvDataLoader  # noqa: F401
from keystone_tpu.loaders.mnist import MnistLoader  # noqa: F401
from keystone_tpu.loaders.cifar import CifarLoader  # noqa: F401
from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader  # noqa: F401
from keystone_tpu.loaders.timit import TimitFeaturesDataLoader  # noqa: F401
from keystone_tpu.loaders.imagenet import ImageNetLoader  # noqa: F401
from keystone_tpu.loaders.amazon import AmazonReviewsDataLoader  # noqa: F401
from keystone_tpu.loaders.voc import VOCLoader  # noqa: F401
from keystone_tpu.loaders.stream import batched, prefetched  # noqa: F401
