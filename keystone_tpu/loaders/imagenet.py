"""ImageNet loader (reference loaders/ImageNetLoader.scala +
ImageLoaderUtils.scala): tar archives of JPEGs, label derived from the
archive/directory name via a synset→label map; JPEG decode on host CPU
(the reference decodes with javax.imageio inside executors; here PIL
decodes inside the threaded prefetch pool of
:class:`keystone_tpu.loaders.stream.ShardedBatchStream`)."""

from __future__ import annotations

import io
import os
import tarfile
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset


def _decode_jpeg(data: bytes, size: Optional[Tuple[int, int]]) -> np.ndarray:
    from PIL import Image as PILImage

    img = PILImage.open(io.BytesIO(data)).convert("RGB")
    if size is not None:
        img = img.resize((size[1], size[0]))
    # keep uint8: pixels cross host→device at 1 byte each (4× less wire
    # traffic than f32); PixelScaler casts/scales to [0,1] ON DEVICE
    return np.asarray(img, np.uint8)


class ImageNetLoader:
    @staticmethod
    def load(
        path: str,
        label_map: Optional[Dict[str, int]] = None,
        size: Tuple[int, int] = (256, 256),
        limit: Optional[int] = None,
    ) -> LabeledData:
        """``path``: a tar file or a directory of per-synset tars.  Labels
        come from ``label_map[synset]``; by default synsets are enumerated
        in sorted order."""
        tars: List[str] = (
            [path]
            if os.path.isfile(path)
            else [
                os.path.join(path, f)
                for f in sorted(os.listdir(path))
                if f.endswith(".tar")
            ]
        )
        if label_map is None:
            label_map = {
                os.path.splitext(os.path.basename(t))[0]: i
                for i, t in enumerate(tars)
            }
        from keystone_tpu import native

        images, labels = [], []
        for t in tars:
            synset = os.path.splitext(os.path.basename(t))[0]
            lab = label_map.get(synset, 0)
            # fast path: native tar index + threaded libjpeg batch decode
            index = native.tar_index(t)
            if index is not None:
                blobs = []
                with open(t, "rb") as f:
                    for _, off, sz in index:
                        if limit is not None and len(images) + len(blobs) >= limit:
                            break
                        f.seek(off)
                        blobs.append(f.read(sz))
                decoded = native.decode_jpegs(blobs, size) if blobs else None
                if decoded is not None:
                    imgs, ok = decoded  # uint8, straight from libjpeg
                    for i in range(imgs.shape[0]):
                        if ok[i]:
                            images.append(imgs[i])
                            labels.append(lab)
                    if limit is not None and len(images) >= limit:
                        break
                    continue
            with tarfile.open(t) as tf:
                for m in tf.getmembers():
                    if not m.isfile():
                        continue
                    data = tf.extractfile(m).read()
                    try:
                        img = _decode_jpeg(data, size)
                    except Exception:
                        continue  # skip undecodable members (native-path parity)
                    images.append(img)
                    labels.append(lab)
                    if limit is not None and len(images) >= limit:
                        break
            if limit is not None and len(images) >= limit:
                break
        x = np.stack(images) if images else np.zeros((0, *size, 3), np.uint8)
        name = f"imagenet:{os.path.abspath(path)}:{size[0]}x{size[1]}:lim{limit}"
        return LabeledData(
            Dataset(x, name=name),
            Dataset(np.asarray(labels, np.int32), name=name + "-labels"),
        )

    @staticmethod
    def synthetic(
        n: int = 64,
        num_classes: int = 16,
        size: Tuple[int, int] = (64, 64),
        seed: int = 0,
    ) -> LabeledData:
        """Class-structured texture images (oriented gratings + color bias
        per class) so SIFT/LCS features carry label signal."""
        rng = np.random.default_rng(seed)
        h, w = size
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
        labels = rng.integers(0, num_classes, size=n)
        imgs = np.zeros((n, h, w, 3), np.float32)
        for i in range(n):
            c = labels[i]
            angle = np.pi * c / num_classes
            freq = 0.2 + 0.05 * (c % 4)
            phase = rng.uniform(0, 2 * np.pi)
            grating = 0.5 + 0.5 * np.sin(
                freq * (np.cos(angle) * xx + np.sin(angle) * yy) + phase
            )
            color = 0.3 + 0.6 * np.array(
                [((c >> b) & 1) for b in range(3)], np.float32
            )
            img = grating[..., None] * color[None, None, :]
            img += 0.05 * rng.normal(size=(h, w, 3))
            imgs[i] = np.clip(img, 0, 1)
        pixels = np.rint(imgs * 255.0).astype(np.uint8)
        name = f"imagenet-synth-n{n}-c{num_classes}-{size[0]}x{size[1]}-s{seed}"
        return LabeledData(
            Dataset(pixels, name=name),
            Dataset(labels.astype(np.int32), name=name + "-labels"),
        )
