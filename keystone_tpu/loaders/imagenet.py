"""ImageNet loader (reference loaders/ImageNetLoader.scala +
ImageLoaderUtils.scala): tar archives of JPEGs, label derived from the
archive/directory name via a synset→label map; JPEG decode on host CPU
(the reference decodes with javax.imageio inside executors; here
libjpeg/PIL decode on the stream's prefetch thread).

Two entry points mirror the reference's scaling story:

- :meth:`ImageNetLoader.load` — decode everything into one in-memory
  Dataset (small data / tests);
- :meth:`ImageNetLoader.stream` — the out-of-core path: a cheap index
  pass over the tar headers fixes ``n`` and the labels, then a
  re-iterable :class:`~keystone_tpu.workflow.dataset.StreamDataset`
  decodes batches on a background thread each time a pipeline stage
  sweeps the data.  The reference starts its larger-than-memory story at
  exactly this loader (tar shards streamed through RDD partitions).
"""

from __future__ import annotations

import io
import logging
import os
import tarfile
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset, StreamDataset

logger = logging.getLogger(__name__)


def _decode_jpeg(data: bytes, size: Optional[Tuple[int, int]]) -> np.ndarray:
    from PIL import Image as PILImage

    img = PILImage.open(io.BytesIO(data)).convert("RGB")
    if size is not None:
        img = img.resize((size[1], size[0]))
    # keep uint8: pixels cross host→device at 1 byte each (4× less wire
    # traffic than f32); PixelScaler casts/scales to [0,1] ON DEVICE
    return np.asarray(img, np.uint8)


def _list_tars(path: str) -> List[str]:
    if os.path.isfile(path):
        return [path]
    return [
        os.path.join(path, f)
        for f in sorted(os.listdir(path))
        if f.endswith(".tar")
    ]


def _default_label_map(tars: List[str]) -> Dict[str, int]:
    return {
        os.path.splitext(os.path.basename(t))[0]: i for i, t in enumerate(tars)
    }


def _decode_entry_batch(
    entries: List[Tuple[str, int, int, int]], size: Tuple[int, int]
) -> np.ndarray:
    """Decode one batch of index entries → (m, H, W, 3) uint8.

    Undecodable members become zero images WITH their label kept (a
    warning is logged): the streaming path must preserve row/label
    alignment fixed by the index pass, where :meth:`ImageNetLoader.load`
    can simply skip bad members."""
    from keystone_tpu import native

    by_tar: Dict[str, List[int]] = {}
    for j, (t, off, sz, _lab) in enumerate(entries):
        by_tar.setdefault(t, []).append(j)
    blobs: List[bytes] = [b""] * len(entries)
    for t, idxs in by_tar.items():
        with open(t, "rb") as f:
            for j in idxs:
                _, off, sz, _ = entries[j]
                f.seek(off)
                blobs[j] = f.read(sz)
    out = np.zeros((len(entries), *size, 3), np.uint8)
    decoded = native.decode_jpegs(blobs, size)
    if decoded is not None:
        imgs, ok = decoded
        for j in range(len(entries)):
            if ok[j]:
                out[j] = imgs[j]
            else:
                logger.warning(
                    "undecodable member in %s at offset %d; substituting "
                    "a zero image (label kept)",
                    entries[j][0],
                    entries[j][1],
                )
        return out
    for j, (t, off, sz, _lab) in enumerate(entries):
        try:
            out[j] = _decode_jpeg(blobs[j], size)
        except Exception:
            logger.warning(
                "undecodable member in %s at offset %d; substituting a "
                "zero image (label kept)",
                t,
                off,
            )
    return out


class ImageNetLoader:
    @staticmethod
    def index(
        path: str, label_map: Optional[Dict[str, int]] = None
    ) -> List[Tuple[str, int, int, int]]:
        """Cheap header-only pass: ``(tar, offset, size, label)`` per
        file member.  Fixes ``n`` and the label vector for streaming
        without decoding a single JPEG."""
        tars = _list_tars(path)
        if label_map is None:
            label_map = _default_label_map(tars)
        from keystone_tpu import native

        entries: List[Tuple[str, int, int, int]] = []
        for t in tars:
            synset = os.path.splitext(os.path.basename(t))[0]
            lab = label_map.get(synset, 0)
            idx = native.tar_index(t)
            if idx is not None:
                for _, off, sz in idx:
                    entries.append((t, off, sz, lab))
                continue
            with tarfile.open(t) as tf:
                for m in tf.getmembers():
                    if m.isfile():
                        entries.append((t, m.offset_data, m.size, lab))
        return entries

    @staticmethod
    def stream(
        path: str,
        label_map: Optional[Dict[str, int]] = None,
        size: Tuple[int, int] = (256, 256),
        batch_size: int = 64,
        limit: Optional[int] = None,
        prefetch: int = 2,
    ) -> LabeledData:
        """Out-of-core loader: labels from an index pass, pixels from a
        re-iterable decoded stream.

        Each pipeline stage that sweeps the data re-decodes from the tar
        shards (the out-of-core contract: disk is the backing tier, host
        RAM holds ``prefetch + 1`` batches).  Labels stay in memory —
        they are 4 bytes/image."""
        entries = ImageNetLoader.index(path, label_map)
        if limit is not None:
            entries = entries[:limit]
        labels = np.asarray([e[3] for e in entries], np.int32)
        n = len(entries)

        def batches() -> Iterator[np.ndarray]:
            for i in range(0, n, batch_size):
                yield _decode_entry_batch(entries[i : i + batch_size], size)

        name = (
            f"imagenet-stream:{os.path.abspath(path)}:{size[0]}x{size[1]}"
            f":lim{limit}:b{batch_size}"
        )
        return LabeledData(
            StreamDataset(batches, n, name=name, prefetch=prefetch),
            Dataset(labels, name=name + "-labels"),
        )

    @staticmethod
    def load(
        path: str,
        label_map: Optional[Dict[str, int]] = None,
        size: Tuple[int, int] = (256, 256),
        limit: Optional[int] = None,
    ) -> LabeledData:
        """``path``: a tar file or a directory of per-synset tars.  Labels
        come from ``label_map[synset]``; by default synsets are enumerated
        in sorted order."""
        tars = _list_tars(path)
        if label_map is None:
            label_map = _default_label_map(tars)
        from keystone_tpu import native

        images, labels = [], []
        for t in tars:
            synset = os.path.splitext(os.path.basename(t))[0]
            lab = label_map.get(synset, 0)
            # fast path: native tar index + threaded libjpeg batch decode
            index = native.tar_index(t)
            if index is not None:
                blobs = []
                with open(t, "rb") as f:
                    for _, off, sz in index:
                        if limit is not None and len(images) + len(blobs) >= limit:
                            break
                        f.seek(off)
                        blobs.append(f.read(sz))
                decoded = native.decode_jpegs(blobs, size) if blobs else None
                if decoded is not None:
                    imgs, ok = decoded  # uint8, straight from libjpeg
                    for i in range(imgs.shape[0]):
                        if ok[i]:
                            images.append(imgs[i])
                            labels.append(lab)
                    if limit is not None and len(images) >= limit:
                        break
                    continue
            with tarfile.open(t) as tf:
                for m in tf.getmembers():
                    if not m.isfile():
                        continue
                    data = tf.extractfile(m).read()
                    try:
                        img = _decode_jpeg(data, size)
                    except Exception:
                        continue  # skip undecodable members (native-path parity)
                    images.append(img)
                    labels.append(lab)
                    if limit is not None and len(images) >= limit:
                        break
            if limit is not None and len(images) >= limit:
                break
        x = np.stack(images) if images else np.zeros((0, *size, 3), np.uint8)
        name = f"imagenet:{os.path.abspath(path)}:{size[0]}x{size[1]}:lim{limit}"
        return LabeledData(
            Dataset(x, name=name),
            Dataset(np.asarray(labels, np.int32), name=name + "-labels"),
        )

    @staticmethod
    def synthetic(
        n: int = 64,
        num_classes: int = 16,
        size: Tuple[int, int] = (64, 64),
        seed: int = 0,
    ) -> LabeledData:
        """Class-structured texture images (oriented gratings + color bias
        per class) so SIFT/LCS features carry label signal."""
        labels, pixels = _synth_all(n, num_classes, size, seed)
        name = f"imagenet-synth-n{n}-c{num_classes}-{size[0]}x{size[1]}-s{seed}"
        return LabeledData(
            Dataset(pixels, name=name),
            Dataset(labels.astype(np.int32), name=name + "-labels"),
        )

    @staticmethod
    def synthetic_stream(
        n: int = 64,
        num_classes: int = 16,
        size: Tuple[int, int] = (64, 64),
        seed: int = 0,
        batch_size: int = 32,
        prefetch: int = 2,
    ) -> LabeledData:
        """Streaming variant of :meth:`synthetic` — PIXEL-IDENTICAL to it
        for the same (n, num_classes, size, seed): each iteration replays
        the same generator sequence, materializing only ``batch_size``
        images at a time.  The stream-vs-in-memory demo/test path."""
        rng = np.random.default_rng(seed)
        labels = rng.integers(0, num_classes, size=n).astype(np.int32)

        def batches() -> Iterator[np.ndarray]:
            gen_rng = np.random.default_rng(seed)
            labs = gen_rng.integers(0, num_classes, size=n)
            buf: List[np.ndarray] = []
            for i in range(n):
                buf.append(_synth_image(labs[i], num_classes, size, gen_rng))
                if len(buf) == batch_size:
                    yield np.stack(buf)
                    buf = []
            if buf:
                yield np.stack(buf)

        name = (
            f"imagenet-synth-stream-n{n}-c{num_classes}"
            f"-{size[0]}x{size[1]}-s{seed}-b{batch_size}"
        )
        return LabeledData(
            StreamDataset(batches, n, name=name, prefetch=prefetch),
            Dataset(labels, name=name + "-labels"),
        )


def _synth_image(
    c: int, num_classes: int, size: Tuple[int, int], rng: np.random.Generator
) -> np.ndarray:
    """One class-structured texture image (uint8).  Draws exactly one
    uniform (phase) then one normal block (noise) from ``rng`` — the
    sequence :func:`_synth_all` and ``synthetic_stream`` both replay."""
    h, w = size
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    angle = np.pi * c / num_classes
    freq = 0.2 + 0.05 * (c % 4)
    phase = rng.uniform(0, 2 * np.pi)
    grating = 0.5 + 0.5 * np.sin(
        freq * (np.cos(angle) * xx + np.sin(angle) * yy) + phase
    )
    color = 0.3 + 0.6 * np.array([((c >> b) & 1) for b in range(3)], np.float32)
    img = grating[..., None] * color[None, None, :]
    img += 0.05 * rng.normal(size=(h, w, 3))
    return np.rint(np.clip(img, 0, 1) * 255.0).astype(np.uint8)


def _synth_all(
    n: int, num_classes: int, size: Tuple[int, int], seed: int
) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n)
    pixels = np.stack(
        [_synth_image(labels[i], num_classes, size, rng) for i in range(n)]
    )
    return labels, pixels
