"""LabeledData — (labels, data) pair (reference loaders/LabeledData.scala)."""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from keystone_tpu.workflow.dataset import Dataset, as_dataset


@dataclasses.dataclass
class LabeledData:
    data: Dataset
    labels: Dataset

    @classmethod
    def of(cls, data, labels) -> "LabeledData":
        return cls(as_dataset(data), as_dataset(labels))

    @property
    def n(self) -> int:
        return self.data.n

    def split(self, fraction: float, seed: int = 0):
        """Deterministic train/test split (host-side shuffle)."""
        if self.data.is_host:
            idx = np.random.default_rng(seed).permutation(self.n)
            cut = int(self.n * fraction)
            items = self.data.items
            labs = self.labels.numpy()
            a = LabeledData(
                Dataset([items[i] for i in idx[:cut]]), Dataset(labs[idx[:cut]])
            )
            b = LabeledData(
                Dataset([items[i] for i in idx[cut:]]), Dataset(labs[idx[cut:]])
            )
            return a, b
        idx = np.random.default_rng(seed).permutation(self.n)
        cut = int(self.n * fraction)
        x = self.data.numpy()
        y = self.labels.numpy()
        return (
            LabeledData(Dataset(x[idx[:cut]]), Dataset(y[idx[:cut]])),
            LabeledData(Dataset(x[idx[cut:]]), Dataset(y[idx[cut:]])),
        )
