"""MNIST loader (CSV format, as the reference's MnistRandomFFT consumes it
via loaders/CsvDataLoader.scala: rows of `label, 784 pixel values`)."""

from __future__ import annotations

import os

import numpy as np

from keystone_tpu.loaders.csv_loader import CsvDataLoader
from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset

NUM_CLASSES = 10
DIM = 784


class MnistLoader:
    @staticmethod
    def load(path: str) -> LabeledData:
        return CsvDataLoader.load(path, label_col=0)

    @staticmethod
    def stream(path: str, batch_size: int = 4096, prefetch: int = 2) -> LabeledData:
        """Out-of-core: CSV rows re-parse per sweep (CsvDataLoader.stream)."""
        return CsvDataLoader.stream(
            path, label_col=0, batch_size=batch_size, prefetch=prefetch
        )

    @staticmethod
    def synthetic(n: int = 2048, seed: int = 0) -> LabeledData:
        """Class-dependent blobs in 784-d pixel space, scaled like MNIST
        (pixels in [0, 255])."""
        rng = np.random.default_rng(seed)
        labels = rng.integers(0, NUM_CLASSES, size=n)
        # class prototypes come from a FIXED generator so different seeds
        # draw train/test samples from the same distribution
        prototypes = (
            np.random.default_rng(1234)
            .uniform(0, 255, size=(NUM_CLASSES, DIM))
            .astype(np.float32)
        )
        # low-rank structure + noise so linear models are non-trivial
        x = prototypes[labels] * 0.3 + rng.normal(0, 25.0, size=(n, DIM)).astype(
            np.float32
        )
        x = np.clip(x, 0, 255)
        # named datasets: prefix signatures stay stable across processes,
        # so SavedStateLoadRule can reload featurized prefixes (state.py)
        name = f"mnist-synth-n{n}-s{seed}"
        return LabeledData(
            Dataset(x, name=name),
            Dataset(labels.astype(np.int32), name=name + "-labels"),
        )
