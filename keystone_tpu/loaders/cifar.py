"""CIFAR-10 binary loader (reference loaders/CifarLoader.scala).

Format: records of 3073 bytes — 1 label byte + 3×32×32 pixel bytes in
channel-major (R plane, G plane, B plane) order; emitted as NHWC floats.
"""

from __future__ import annotations

import os

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset

NUM_CLASSES = 10
H = W = 32
C = 3
RECORD = 1 + H * W * C


class CifarLoader:
    @staticmethod
    def load(path: str) -> LabeledData:
        from keystone_tpu import native

        name = f"cifar:{os.path.abspath(path)}"
        res = native.read_cifar(path)
        if res is not None:
            pixels, labels = res
            return LabeledData(
                Dataset(pixels, name=name), Dataset(labels, name=name + "-labels")
            )
        raw = np.fromfile(path, dtype=np.uint8)
        if raw.size % RECORD != 0:
            raise ValueError(f"{path}: size {raw.size} not a multiple of {RECORD}")
        recs = raw.reshape(-1, RECORD)
        labels = recs[:, 0].astype(np.int32)
        return LabeledData(
            Dataset(_decode_records(recs), name=name),
            Dataset(labels, name=name + "-labels"),
        )

    @staticmethod
    def stream(path: str, batch_size: int = 1024, prefetch: int = 2) -> LabeledData:
        """Out-of-core loader: fixed-size binary records make this the
        simplest streaming format — one cheap size check fixes ``n``,
        labels come from a single strided read of the first record
        bytes, pixels re-read from disk in ``batch_size``-record chunks
        per sweep."""
        from keystone_tpu.workflow.dataset import StreamDataset

        size = os.path.getsize(path)
        if size % RECORD != 0:
            raise ValueError(f"{path}: size {size} not a multiple of {RECORD}")
        n = size // RECORD
        if n == 0:  # np.memmap refuses empty files; match load()'s result
            return CifarLoader.load(path)
        mm = np.memmap(path, dtype=np.uint8, mode="r").reshape(-1, RECORD)
        labels = np.array(mm[:, 0], np.int32)  # 1 byte/record: stays in RAM

        def batches():
            m = np.memmap(path, dtype=np.uint8, mode="r").reshape(-1, RECORD)
            for i in range(0, n, batch_size):
                yield _decode_records(np.asarray(m[i : i + batch_size]))

        name = f"cifar-stream:{os.path.abspath(path)}:b{batch_size}"
        return LabeledData(
            StreamDataset(batches, n, name=name, prefetch=prefetch),
            Dataset(labels, name=name + "-labels"),
        )

    @staticmethod
    def synthetic(n: int = 1024, seed: int = 0) -> LabeledData:
        """Class-colored noise images in [0,1] NHWC."""
        rng = np.random.default_rng(seed)
        labels = rng.integers(0, NUM_CLASSES, size=n)
        # fixed prototype generator: train/test share the class structure
        base = (
            np.random.default_rng(1234)
            .uniform(0.2, 0.8, size=(NUM_CLASSES, 1, 1, C))
            .astype(np.float32)
        )
        x = base[labels] + rng.normal(0, 0.15, size=(n, H, W, C)).astype(np.float32)
        # add class-dependent spatial structure (a bright patch per class)
        for k in range(NUM_CLASSES):
            idx = labels == k
            y0, x0 = 3 * (k % 3) + 4, 3 * (k // 3) + 4
            x[idx, y0 : y0 + 6, x0 : x0 + 6, :] += 0.5
        name = f"cifar-synth-n{n}-s{seed}"
        return LabeledData(
            Dataset(np.clip(x, 0, 1), name=name),
            Dataset(labels.astype(np.int32), name=name + "-labels"),
        )


def _decode_records(recs: np.ndarray) -> np.ndarray:
    """(m, RECORD) uint8 records → (m, H, W, C) float32 in [0,1] —
    shared by load()'s fallback and stream() so the two paths cannot
    drift."""
    return (
        recs[:, 1:].reshape(-1, C, H, W).transpose(0, 2, 3, 1).astype(np.float32)
        / 255.0
    )
