"""20 Newsgroups loader (reference loaders/NewsgroupsDataLoader.scala):
a directory tree ``root/<group-name>/<doc-file>`` of plain-text posts."""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset

# canonical class order (reference hard-codes the 20 group names)
NEWSGROUPS = [
    "alt.atheism", "comp.graphics", "comp.os.ms-windows.misc",
    "comp.sys.ibm.pc.hardware", "comp.sys.mac.hardware", "comp.windows.x",
    "misc.forsale", "rec.autos", "rec.motorcycles", "rec.sport.baseball",
    "rec.sport.hockey", "sci.crypt", "sci.electronics", "sci.med",
    "sci.space", "soc.religion.christian", "talk.politics.guns",
    "talk.politics.mideast", "talk.politics.misc", "talk.religion.misc",
]


class NewsgroupsDataLoader:
    @staticmethod
    def load(root: str, groups: Optional[Sequence[str]] = None) -> LabeledData:
        groups = list(groups) if groups is not None else sorted(os.listdir(root))
        texts: List[str] = []
        labels: List[int] = []
        for gi, g in enumerate(groups):
            gdir = os.path.join(root, g)
            if not os.path.isdir(gdir):
                continue
            for fname in sorted(os.listdir(gdir)):
                fpath = os.path.join(gdir, fname)
                try:
                    with open(fpath, "r", errors="replace") as f:
                        texts.append(f.read())
                    labels.append(gi)
                except OSError:
                    continue
        name = f"newsgroups:{os.path.abspath(root)}"
        return LabeledData(
            Dataset(texts, name=name),
            Dataset(np.asarray(labels, np.int32), name=name + "-labels"),
        )

    @staticmethod
    def stream(
        root: str,
        groups: Optional[Sequence[str]] = None,
        batch_size: int = 512,
        prefetch: int = 2,
    ) -> LabeledData:
        """Out-of-core loader: one cheap directory walk fixes the file
        list and labels; document TEXTS re-read from disk in
        ``batch_size`` chunks per sweep through a HOST StreamDataset —
        the raw corpus never materializes in RAM."""
        from keystone_tpu.workflow.dataset import StreamDataset

        groups = list(groups) if groups is not None else sorted(os.listdir(root))
        paths: List[str] = []
        labels: List[int] = []
        for gi, g in enumerate(groups):
            gdir = os.path.join(root, g)
            if not os.path.isdir(gdir):
                continue
            for fname in sorted(os.listdir(gdir)):
                paths.append(os.path.join(gdir, fname))
                labels.append(gi)
        n = len(paths)

        def batches():
            for i in range(0, n, batch_size):
                chunk = []
                for p in paths[i : i + batch_size]:
                    try:
                        with open(p, "r", errors="replace") as f:
                            chunk.append(f.read())
                    except OSError:
                        chunk.append("")  # keep row/label alignment
                yield chunk

        name = f"newsgroups-stream:{os.path.abspath(root)}:b{batch_size}"
        return LabeledData(
            StreamDataset(batches, n, name=name, prefetch=prefetch, host=True),
            Dataset(np.asarray(labels, np.int32), name=name + "-labels"),
        )

    @staticmethod
    def synthetic(
        n: int = 400, num_classes: int = 4, seed: int = 0
    ) -> LabeledData:
        """Topic-specific vocabulary mixtures — enough signal for tf/NB."""
        rng = np.random.default_rng(seed)
        shared = [f"word{i}" for i in range(50)]
        topics = [
            [f"topic{c}term{i}" for i in range(30)] for c in range(num_classes)
        ]
        texts, labels = [], []
        for _ in range(n):
            c = int(rng.integers(0, num_classes))
            k_topic = int(rng.integers(10, 30))
            k_shared = int(rng.integers(10, 30))
            words = list(rng.choice(topics[c], size=k_topic)) + list(
                rng.choice(shared, size=k_shared)
            )
            rng.shuffle(words)
            texts.append(" ".join(words))
            labels.append(c)
        name = f"newsgroups-synth-n{n}-c{num_classes}-s{seed}"
        return LabeledData(
            Dataset(texts, name=name),
            Dataset(np.asarray(labels, np.int32), name=name + "-labels"),
        )
