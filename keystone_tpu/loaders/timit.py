"""TIMIT frame loader (reference loaders/TimitFeaturesDataLoader.scala):
pre-extracted MFCC frames (440-d: 40-d filterbank × 11-frame context
window in the standard prep) with per-frame labels over 147 phone states.
"""

from __future__ import annotations

import os

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.workflow.dataset import Dataset

NUM_CLASSES = 147
DIM = 440


class TimitFeaturesDataLoader:
    @staticmethod
    def load(features_path: str, labels_path: str) -> LabeledData:
        """features: CSV/NPY (n, 440); labels: one int per line/entry."""
        feats = (
            np.load(features_path)
            if features_path.endswith(".npy")
            else np.loadtxt(features_path, delimiter=",", dtype=np.float32)
        )
        labels = (
            np.load(labels_path)
            if labels_path.endswith(".npy")
            else np.loadtxt(labels_path, dtype=np.int64)
        )
        name = (
            f"timit:{os.path.abspath(features_path)}"
            f":{os.path.abspath(labels_path)}"
        )
        return LabeledData(
            Dataset(feats.astype(np.float32), name=name),
            Dataset(labels.astype(np.int32), name=name + "-labels"),
        )

    @staticmethod
    def stream(
        features_path: str,
        labels_path: str,
        batch_size: int = 8192,
        prefetch: int = 2,
    ) -> LabeledData:
        """Out-of-core loader: ``.npy`` features are memory-mapped and
        re-read in ``batch_size``-frame chunks per sweep (labels — 4
        bytes/frame — stay in memory).  CSV features fall back to the
        CsvDataLoader-style chunked re-parse."""
        from keystone_tpu.workflow.dataset import StreamDataset

        labels = (
            np.load(labels_path)
            if labels_path.endswith(".npy")
            else np.loadtxt(labels_path, dtype=np.int64)
        ).astype(np.int32)
        n = len(labels)
        name = (
            f"timit-stream:{os.path.abspath(features_path)}"
            f":{os.path.abspath(labels_path)}:b{batch_size}"
        )

        if features_path.endswith(".npy"):

            def batches():
                mm = np.load(features_path, mmap_mode="r")
                for i in range(0, n, batch_size):
                    yield np.asarray(mm[i : i + batch_size], np.float32)

        else:

            def batches():
                buf = []
                with open(features_path) as f:
                    for line in f:
                        if not line.strip():
                            continue
                        buf.append(line)
                        if len(buf) == batch_size:
                            yield np.loadtxt(
                                buf, delimiter=",", dtype=np.float32, ndmin=2
                            )
                            buf = []
                if buf:
                    yield np.loadtxt(buf, delimiter=",", dtype=np.float32, ndmin=2)

        return LabeledData(
            StreamDataset(batches, n, name=name, prefetch=prefetch),
            Dataset(labels, name=name + "-labels"),
        )

    @staticmethod
    def synthetic(n: int = 4096, num_classes: int = NUM_CLASSES, seed: int = 0) -> LabeledData:
        rng = np.random.default_rng(seed)
        labels = rng.integers(0, num_classes, size=n)
        # fixed prototype generator: train/test share the class structure
        prototypes = (
            np.random.default_rng(1234)
            .normal(size=(num_classes, DIM))
            .astype(np.float32)
        )
        x = prototypes[labels] + 0.8 * rng.normal(size=(n, DIM)).astype(np.float32)
        name = f"timit-synth-n{n}-c{num_classes}-s{seed}"
        return LabeledData(
            Dataset(x, name=name),
            Dataset(labels.astype(np.int32), name=name + "-labels"),
        )
