"""ctypes binding for the native IO library (native/keystone_native.cpp).

The reference loads its C++ via JNI ``System.loadLibrary`` with the .so
bundled in jar resources (SURVEY.md §2.8); here the .so lives next to
this module, is built lazily with ``make -C native`` on first use, and
every entry point has a pure-Python fallback in the loaders — the
framework works without a compiler, it's just slower at ingest.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_SO_PATH = os.path.join(os.path.dirname(__file__), "libkeystone_native.so")
_ABI_VERSION = 4  # must match ks_version() in native/keystone_native.cpp
_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def build_and_load(
    so_path: str, make_target: Optional[str] = None
) -> Optional[ctypes.CDLL]:
    """Build (via ``make -C native [target]``) if missing, then CDLL-load.

    Shared by the IO binding below and the XLA-FFI binding
    (ops/fisher_ffi.py).  Returns None when the toolchain or library is
    unavailable — callers fall back to pure-Python paths.

    make always runs (a no-op when the .so is fresh) so edits to the C++
    sources rebuild instead of silently loading a stale binary; if make
    itself is unavailable, an existing .so is still loaded."""
    cmd = ["make", "-C", os.path.abspath(_NATIVE_DIR)]
    if make_target:
        cmd.append(make_target)
    try:
        subprocess.run(
            cmd, capture_output=True, text=True, timeout=300, check=True
        )
    except (subprocess.SubprocessError, OSError) as e:
        logger.debug("native build failed: %s", e)
    if not os.path.exists(so_path):
        return None
    try:
        return ctypes.CDLL(os.path.abspath(so_path))
    except OSError as e:
        logger.warning("could not load native library %s: %s", so_path, e)
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        lib = build_and_load(_SO_PATH)
        if lib is None:
            return None
        # ABI check: build_and_load loads a pre-existing .so even when make
        # is unavailable, so a stale binary with the old float-pixel
        # ks_decode_jpegs ABI would otherwise yield garbage uint8 data.
        try:
            lib.ks_version.restype = ctypes.c_int
            version = lib.ks_version()
        except AttributeError:
            version = 0
        if version != _ABI_VERSION:
            logger.warning(
                "native library %s has ABI version %d (want %d); ignoring "
                "it — pure-Python fallbacks will be used. Rebuild with "
                "`make -C native`.",
                _SO_PATH,
                version,
                _ABI_VERSION,
            )
            return None
        lib.ks_read_csv.restype = ctypes.c_int
        lib.ks_read_csv.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.ks_read_cifar.restype = ctypes.c_int
        lib.ks_read_cifar.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.ks_tar_index.restype = ctypes.c_int
        lib.ks_tar_index.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.ks_decode_jpegs.restype = ctypes.c_int
        lib.ks_decode_jpegs.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ]
        lib.ks_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def _take_array(lib, ptr, shape, dtype):
    """Copy a malloc'd native buffer into numpy and free it."""
    count = int(np.prod(shape))
    ctype = {
        np.float32: ctypes.c_float,
        np.int32: ctypes.c_int32,
        np.uint8: ctypes.c_uint8,
    }[dtype]
    arr = np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(ctype)), shape=(count,)
    ).copy()
    lib.ks_free(ptr)
    return arr.reshape(shape).astype(dtype, copy=False)


def read_csv(path: str) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_float)()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.ks_read_csv(path.encode(), ctypes.byref(out), ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        return None
    return _take_array(lib, out, (rows.value, cols.value), np.float32)


def read_cifar(path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    lib = get_lib()
    if lib is None:
        return None
    px = ctypes.POINTER(ctypes.c_float)()
    lb = ctypes.POINTER(ctypes.c_int32)()
    n = ctypes.c_int64()
    rc = lib.ks_read_cifar(path.encode(), ctypes.byref(px), ctypes.byref(lb), ctypes.byref(n))
    if rc != 0:
        return None
    pixels = _take_array(lib, px, (n.value, 32, 32, 3), np.float32)
    labels = _take_array(lib, lb, (n.value,), np.int32)
    return pixels, labels


def tar_index(path: str) -> Optional[list]:
    """[(name, offset, size), ...] for regular members of a tar archive."""
    lib = get_lib()
    if lib is None:
        return None
    names = ctypes.POINTER(ctypes.c_char)()
    offs = ctypes.POINTER(ctypes.c_int64)()
    sizes = ctypes.POINTER(ctypes.c_int64)()
    n = ctypes.c_int64()
    rc = lib.ks_tar_index(
        path.encode(), ctypes.byref(names), ctypes.byref(offs),
        ctypes.byref(sizes), ctypes.byref(n),
    )
    if rc != 0:
        return None
    count = n.value
    out = []
    for i in range(count):
        raw = ctypes.string_at(ctypes.addressof(names.contents) + i * 101, 101)
        name = raw.split(b"\x00", 1)[0].decode(errors="replace")
        out.append((name, offs[i], sizes[i]))
    lib.ks_free(names)
    lib.ks_free(offs)
    lib.ks_free(sizes)
    return out


def decode_jpegs(
    blobs: list, target_hw: Tuple[int, int], threads: int = 0
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Decode a list of JPEG byte strings to (n, H, W, 3) uint8.
    Returns (images, ok_mask).  uint8 keeps host buffers and the
    host→device transfer at 1 byte/pixel; PixelScaler casts on device."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(blobs)
    blob = b"".join(blobs)
    offsets = np.zeros((n,), np.int64)
    sizes = np.asarray([len(b) for b in blobs], np.int64)
    if n > 1:
        offsets[1:] = np.cumsum(sizes)[:-1]
    th, tw = target_hw
    out = ctypes.POINTER(ctypes.c_uint8)()
    ok = ctypes.POINTER(ctypes.c_int32)()
    blob_arr = np.frombuffer(blob, np.uint8)
    rc = lib.ks_decode_jpegs(
        blob_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, th, tw, threads,
        ctypes.byref(out), ctypes.byref(ok),
    )
    if rc != 0:
        return None
    images = _take_array(lib, out, (n, th, tw, 3), np.uint8)
    ok_mask = _take_array(lib, ok, (n,), np.int32)
    return images, ok_mask == 0
