"""keystone_tpu — a TPU-native ML pipeline framework.

A from-scratch rebuild of the capabilities of KeystoneML
(stephentu/keystone, the AMPLab Scala/Spark pipeline framework) on
JAX/XLA/Pallas.  Composable Transformer/Estimator pipelines for classical
large-scale ML: dense image features (SIFT/LCS/DAISY, Fisher vectors,
random-patch convolutions), random-feature and n-gram featurization, and
distributed linear/kernel solvers (block least squares, weighted block LS,
L-BFGS, kernel ridge regression).

Architecture (see SURVEY.md for the reference layer map):

  - ``keystone_tpu.parallel``  — device mesh, shardings, collectives
    (replaces Spark treeReduce/broadcast: reference src/main/scala layer L0).
  - ``keystone_tpu.workflow``  — Transformer/Estimator/Pipeline DSL, DAG,
    executor, whole-pipeline optimizer (reference workflow/ layer L3).
  - ``keystone_tpu.models``    — learning nodes / solvers (reference
    nodes/learning/ layer L4).
  - ``keystone_tpu.ops``       — feature ops: images, stats, nlp, util
    (reference nodes/{images,stats,nlp,misc,util}/ layer L4).
  - ``keystone_tpu.loaders``   — dataset loaders (reference loaders/ L2).
  - ``keystone_tpu.evaluation``— evaluators (reference evaluation/ L5).
  - ``keystone_tpu.pipelines`` — example applications (reference
    pipelines/ L6).
  - ``keystone_tpu.utils``     — image types, matrix helpers, stats.
"""

__version__ = "0.1.0"

from keystone_tpu import faults  # noqa: F401
from keystone_tpu import obs  # noqa: F401
from keystone_tpu.workflow import (  # noqa: F401
    Transformer,
    Estimator,
    LabelEstimator,
    Pipeline,
)
