"""Shared small-filter helpers for the image feature extractors.

One Gaussian-kernel builder and one separable depthwise blur, used by
dense SIFT (per-scale pre-smoothing) and DAISY (orientation-map
pooling) — keeping truncation and padding semantics in one place.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax


def gaussian_kernel1d(sigma: float, truncate: float = 3.0) -> np.ndarray:
    """Normalized 1-D Gaussian, radius ⌈truncate·σ⌉ (≥1)."""
    r = max(1, int(np.ceil(truncate * sigma)))
    xs = np.arange(-r, r + 1, dtype=np.float32)
    k = np.exp(-0.5 * (xs / sigma) ** 2)
    return k / k.sum()


def separable_gaussian_blur(x, sigma: float):
    """Depthwise separable Gaussian blur of (n, h, w, c) maps.

    SAME zero padding (matches scipy ``mode="constant"``); accumulation
    in f32 regardless of input dtype."""
    c = x.shape[-1]
    k1 = jnp.asarray(gaussian_kernel1d(sigma))
    eye = jnp.eye(c)[None, None]
    out = lax.conv_general_dilated(
        x,
        k1.reshape(-1, 1, 1, 1) * eye,
        (1, 1),
        "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )
    return lax.conv_general_dilated(
        out,
        k1.reshape(1, -1, 1, 1) * eye,
        (1, 1),
        "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )
