"""Shared small-filter helpers for the image feature extractors.

One Gaussian-kernel builder and one separable blur, used by dense SIFT
(per-scale pre-smoothing) and DAISY (orientation-map pooling) — keeping
truncation and padding semantics in one place.

The blur's default physical form is two banded-matrix MXU einsums (the
same linear-map-as-matmul rework `ops/sift._window_matrix` applied to
the SIFT windowing in r3): the r4 multi-scale roofline measured the
depthwise-conv form at ~0.1× of its HBM byte bound (~50 µs per conv,
8 convs per multi-scale batch — the conv emitter's fixed costs dominate
at these tiny kernels), where a (extent, extent) banded matmul is a few
µs of MXU work.  The conv form stays as the parity fallback.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np
from jax import lax

from keystone_tpu.utils import precision


def gaussian_kernel1d(sigma: float, truncate: float = 3.0) -> np.ndarray:
    """Normalized 1-D Gaussian, radius ⌈truncate·σ⌉ (≥1)."""
    r = max(1, int(np.ceil(truncate * sigma)))
    xs = np.arange(-r, r + 1, dtype=np.float32)
    k = np.exp(-0.5 * (xs / sigma) ** 2)
    return k / k.sum()


@functools.lru_cache(maxsize=64)
def _blur_matrix(extent: int, sigma: float, truncate: float = 3.0) -> np.ndarray:
    """(extent, extent) banded operator ≡ the SAME-zero-padded 1-D
    Gaussian conv along one axis: row i holds the kernel centered at i,
    TRUNCATED at the image edge without renormalization (zero padding's
    semantics — matches scipy ``mode="constant"``)."""
    k1 = gaussian_kernel1d(sigma, truncate)
    r = (k1.size - 1) // 2
    b = np.zeros((extent, extent), np.float32)
    for i in range(extent):
        lo, hi = i - r, i + r + 1
        klo = max(0, -lo)
        khi = k1.size - max(0, hi - extent)
        b[i, max(lo, 0) : min(hi, extent)] = k1[klo:khi]
    return b


#: image extent above which the banded-matmul blur falls back to the
#: conv form: the dense (extent, extent) operator makes the matmul pass
#: O(extent³) per axis vs the conv's O(k·extent²), and the measured win
#: (BASELINE.md r4) is at 128 px where the conv emitter's fixed costs
#: dominate.  512 px keeps the matmul pass within ~4 GF/axis/image —
#: still cheap MXU work — while callers on larger maps (e.g. DAISY on
#: full-resolution inputs) keep the byte-bound conv (ADVICE r4).
_MATMUL_BLUR_MAX_EXTENT = 512


def separable_apply(bh, bw, x, mxu: str = "f32"):
    """Apply a separable (rows-operator, cols-operator) pair to
    (n, h, w, c) maps as two MXU einsums: out = bh · x · bwᵀ per
    channel.  The single physical form shared by the banded-matrix blur
    below and the LCS box sums (ops/lcs.py); under the ``bf16_apply``
    policy both einsums cast their inputs to bf16 with f32 accumulation
    (utils/precision.apply_einsum), inert otherwise."""
    out = precision.apply_einsum("ph,nhwc->npwc", bh, x, mode=mxu)
    return precision.apply_einsum("qw,npwc->npqc", bw, out, mode=mxu)


def separable_gaussian_blur(x, sigma: float, strategy: str = "matmul", mxu: str = "f32"):
    """Separable Gaussian blur of (n, h, w, c) maps.

    SAME zero padding (matches scipy ``mode="constant"``); accumulation
    in f32 regardless of input dtype.  ``strategy="matmul"`` (default)
    runs the two 1-D passes as banded-matrix einsums on the MXU, falling
    back to conv above ``_MATMUL_BLUR_MAX_EXTENT``; ``"conv"`` keeps the
    depthwise-conv form (parity reference).  ``mxu`` is the resolved
    precision-policy mode: under ``bf16_apply`` the banded einsums cast
    their inputs to bf16 (utils/precision.apply_einsum), accumulation
    staying f32; the conv fallback stays true f32 in every mode."""
    if strategy == "matmul" and max(x.shape[1], x.shape[2]) > _MATMUL_BLUR_MAX_EXTENT:
        strategy = "conv"
    if strategy == "matmul":
        h, w = x.shape[1], x.shape[2]
        bh = jnp.asarray(_blur_matrix(h, float(sigma)))
        bw = jnp.asarray(_blur_matrix(w, float(sigma)))
        return separable_apply(bh, bw, x, mxu=mxu)
    c = x.shape[-1]
    k1 = jnp.asarray(gaussian_kernel1d(sigma))
    eye = jnp.eye(c)[None, None]
    out = lax.conv_general_dilated(
        x,
        k1.reshape(-1, 1, 1, 1) * eye,
        (1, 1),
        "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )
    return lax.conv_general_dilated(
        out,
        k1.reshape(1, -1, 1, 1) * eye,
        (1, 1),
        "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )
