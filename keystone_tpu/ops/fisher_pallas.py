"""Pallas TPU kernel for Fisher-vector encoding.

The XLA path (ops/fisher.py § _fisher_encode) materializes the
responsibility tensor γ (n, T, K) in HBM between the softmax and the two
sufficient-statistic einsums.  For FV workloads γ is as large as the
descriptors themselves (T≈10³ descriptors × K≈256 components per image),
so the op is HBM-bandwidth bound — exactly the case the Pallas guide
calls for a fused kernel.

This kernel streams descriptor tiles through VMEM once per image:

    per (image i, tile t):
      logp  = log w + log N(x; μ, σ²)      (two MXU matmuls)
      γ     = softmax_K(logp) · mask       (VPU, never leaves VMEM)
      s0   += Σ_t γ;  s1 += γᵀx;  s2 += γᵀx²   (MXU, VMEM accumulators)
    on the last tile: Φ¹, Φ² from (s0, s1, s2) → out[i]

Accumulators live in VMEM scratch (K + 2·K·D floats ≪ 16 MB), so HBM
traffic is exactly one read of the descriptors and one write of the FV.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LOG2PI = 1.8378770664093453


def _precision():
    # deferred: keeps this kernel module importable without dragging the
    # policy module into jax.experimental import time
    from keystone_tpu.utils import precision

    return precision


def _compiler_params(**kw):
    """``pltpu.CompilerParams`` across jax versions (older releases ship
    it as ``TPUCompilerParams``) — the kernels must import-and-run on
    both the TPU fleet's jax and the CPU test container's."""
    cp = getattr(pltpu, "CompilerParams", None)
    if cp is None:
        cp = pltpu.TPUCompilerParams
    return cp(**kw)

# Max descriptors per VMEM tile when the GMM shape is unknown.  Measured
# on v5 lite (T=784, K=256, d=64): one whole-image tile runs the kernel
# at ~42 TF/s vs ~14 TF/s with 128-row tiles — per-program overhead
# (accumulator init/finalize, revolving windows) dominates small tiles,
# and M=T-sized matmuls feed the MXU far better.
TILE_T_MAX = 1024
#: VMEM bytes budgeted for the per-tile intermediates (γ/logp/e are
#: (tile, K) f32 — ~3 live copies — plus x and x² at (tile, d)); the
#: rest of the ~16 MB budget holds the (K, d) accumulators + constants.
_VMEM_TILE_BUDGET = 12 << 20


def _tile_t(t: int, k: int | None = None, d: int | None = None) -> int:
    """Fewest tiles covering t under the VMEM budget.

    With the GMM shape (k, d) known, the cap comes from the budget —
    measured r4 at the multi-scale config (T=2520, K=256): one 2520-row
    tile runs 620→524 µs/batch vs 3×896 tiles, because the fixed-cap
    tiling both paid per-tile overhead AND padded the whole descriptor
    tensor 2520→2688 (a 130 µs jnp.pad copy).  Single tile: any sublane
    multiple (8) works.  Multiple tiles: the mask block rides T as its
    LANE dim, so the tile must be a 128-multiple."""
    cap = TILE_T_MAX
    if k is not None and d is not None:
        rows = _VMEM_TILE_BUDGET // (4 * (3 * k + 2 * d))
        # floor of 8 (one sublane group), NOT some larger convenience
        # minimum: a floor above the budget would silently re-breach the
        # VMEM limit the cap exists to respect.  (Multi-tile tiles are
        # ≥128 regardless — the mask lane-dim constraint — so K large
        # enough that 128 rows overflow VMEM fails at Mosaic compile,
        # as it would have at any tile size.)
        cap = max(8, min(4096, rows // 8 * 8))
    tiles = -(-t // cap)
    while True:
        if tiles == 1:
            return -(-t // 8) * 8
        tile = -(-t // tiles // 128) * 128
        # the 128-up-rounding can push one tile count past the cap;
        # adding a tile shrinks it (terminates at tile=128)
        if tile <= max(cap, 128):
            return tile
        tiles += 1


def _fv_tile_body(x, m, logw_ref, mu_ref, inv_ref, lognorm_ref,
                  out_ref, s0_ref, s1_ref, s2_ref, cnt_ref):
    """Shared FV accumulation over one descriptor tile: posterior gemms
    → masked softmax → sufficient-statistic accumulators → Φ¹/Φ² on the
    last tile.  ``x`` (TILE_T, d) f32 in VMEM; ``m`` (TILE_T, 1) mask.
    Both the plain FV kernel and the fused sift-normalize→PCA→FV
    megakernel end here, so their math cannot drift apart."""
    t = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(t == 0)
    def _init():
        s0_ref[:] = jnp.zeros_like(s0_ref)
        s1_ref[:] = jnp.zeros_like(s1_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)
        cnt_ref[0] = 0.0

    mu_inv = mu_ref[:] * inv_ref[:]  # (K, d)

    # log N(x; μ_k, σ²_k) via the gemm expansion (all on the MXU)
    quad = (
        jnp.dot(x * x, inv_ref[:].T, preferred_element_type=jnp.float32)
        - 2.0 * jnp.dot(x, mu_inv.T, preferred_element_type=jnp.float32)
        + jnp.sum(mu_ref[:] * mu_inv, axis=1)[None, :]
    )
    logp = logw_ref[0][None, :] + lognorm_ref[0][None, :] - 0.5 * quad

    # row softmax over K — γ never leaves VMEM
    mx = jnp.max(logp, axis=1, keepdims=True)
    e = jnp.exp(logp - mx)
    gamma = (e / jnp.sum(e, axis=1, keepdims=True)) * m  # (TILE_T, K)

    s0_ref[0, :] += jnp.sum(gamma, axis=0)
    s1_ref[:] += jnp.dot(gamma.T, x, preferred_element_type=jnp.float32)
    s2_ref[:] += jnp.dot(gamma.T, x * x, preferred_element_type=jnp.float32)
    cnt_ref[0] += jnp.sum(m)

    @pl.when(t == nt - 1)
    def _finalize():
        k, d = s1_ref.shape
        s0 = s0_ref[0, :]  # (K,)
        s1 = s1_ref[:]
        s2 = s2_ref[:]
        mu = mu_ref[:]
        var = 1.0 / inv_ref[:]
        sigma = jnp.sqrt(var)
        w = jnp.exp(logw_ref[0])
        tn = jnp.maximum(cnt_ref[0], 1.0)
        phi1 = (s1 - s0[:, None] * mu) / sigma
        phi2 = (s2 - 2.0 * mu * s1 + s0[:, None] * (mu * mu)) / var - s0[:, None]
        phi1 = phi1 / (tn * jnp.sqrt(w)[:, None])
        phi2 = phi2 / (tn * jnp.sqrt(2.0 * w)[:, None])
        # keep 2-D: Mosaic can't shape-cast (K, d) -> (K*d); the caller
        # flattens (n, 2K, d) -> (n, 2KD) outside the kernel
        out_ref[0, :k, :] = phi1
        out_ref[0, k:, :] = phi2


def _fv_kernel(x_ref, mask_ref, logw_ref, mu_ref, inv_ref, lognorm_ref,
               out_ref, s0_ref, s1_ref, s2_ref, cnt_ref):
    # descriptors may arrive bf16 (halved HBM traffic — the kernel is
    # bandwidth bound); compute stays f32 in VMEM
    x = x_ref[0].astype(jnp.float32)  # (TILE_T, d)
    # mask arrives (1, 1, TILE_T) with T on the LANE dim: a (n, T, 1)
    # input would be lane-padded to 128 by TPU tiling — 128× the HBM
    # traffic for the same bits.  The (1,T)→(T,1) relayout is per-tile
    # VPU work on ~10³ elements, noise next to the saved DMA.
    m = mask_ref[0].T  # (TILE_T, 1)
    _fv_tile_body(x, m, logw_ref, mu_ref, inv_ref, lognorm_ref,
                  out_ref, s0_ref, s1_ref, s2_ref, cnt_ref)


def _fv_fused_kernel(x_ref, mask_ref, comp_ref, mean_ref, logw_ref, mu_ref,
                     inv_ref, lognorm_ref, out_ref, s0_ref, s1_ref, s2_ref,
                     cnt_ref, *, normalize: bool):
    """Fused forward tile: [SIFT normalize →] PCA project → FV
    accumulate, one VMEM pass per descriptor tile.

    The unfused chain writes the normalized (T, d_in) descriptors AND
    the projected (T, d) descriptors back to HBM between stages (and on
    the un-jitted serve path pays a program launch per stage); here raw
    descriptors stream from HBM exactly once and only the FV leaves.
    ``normalize`` is a Python-static flag (functools.partial at
    pallas_call time): True when the feed is RAW windowed SIFT output
    (the extractor's normalize tail absorbed in-kernel), False when the
    producer already normalized."""
    x = x_ref[0].astype(jnp.float32)  # (TILE_T, d_in) descriptor tile
    if normalize:
        # SIFT normalize: L2 → clamp 0.2 → re-L2 (VPU; same form and
        # epsilons as ops/sift._sift_normalize, the parity reference)
        nrm = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True))
        x = x / jnp.maximum(nrm, 1e-8)
        x = jnp.minimum(x, 0.2)
        nrm = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True))
        x = x / jnp.maximum(nrm, 1e-8)
    # PCA projection on the MXU: (TILE_T, d_in) × (d_in, d), f32
    # accumulation.  Tile padding rows project to (−μ)·C ≠ 0, but the
    # mask zeroes their γ so they contribute nothing downstream.
    z = jnp.dot(
        x - mean_ref[0][None, :], comp_ref[:],
        preferred_element_type=jnp.float32,
    )
    m = mask_ref[0].T  # (TILE_T, 1) — see _fv_kernel on the lane layout
    _fv_tile_body(z, m, logw_ref, mu_ref, inv_ref, lognorm_ref,
                  out_ref, s0_ref, s1_ref, s2_ref, cnt_ref)


@functools.partial(jax.jit, static_argnames=("interpret", "mxu"))
def fisher_encode_pallas(
    xs, mask, w, mu, var, interpret: bool = False, mxu: str = "f32"
):
    """xs: (n, T, d); mask: (n, T); GMM (w (K,), mu/var (K, d)) → (n, 2KD).

    Matches ops/fisher.py § _fisher_encode up to f32 rounding.  With
    ``mxu='bf16'`` (the featurize policy) or ``mxu='bf16_apply'`` (the
    apply policy — utils/precision.fdtype maps both to bf16) descriptors
    stream from HBM as bf16 (half the read traffic of the
    bandwidth-bound kernel); all VMEM compute stays f32.
    """
    n, t, d = xs.shape
    k = mu.shape[0]
    tile_t = _tile_t(t, k, d)
    tiles = -(-t // tile_t)
    if tiles * tile_t != t:
        pad = tiles * tile_t - t
        xs = jnp.pad(xs, ((0, 0), (0, pad), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    inv = 1.0 / var
    logw = jnp.log(w).reshape(1, k)
    lognorm = (-0.5 * (jnp.sum(jnp.log(var), axis=1) + d * _LOG2PI)).reshape(1, k)

    grid = (n, tiles)
    out = pl.pallas_call(
        _fv_kernel,
        grid=grid,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        in_specs=[
            pl.BlockSpec((1, tile_t, d), lambda i, t: (i, t, 0)),
            pl.BlockSpec((1, 1, tile_t), lambda i, t: (i, 0, t)),
            pl.BlockSpec((1, k), lambda i, t: (0, 0)),
            pl.BlockSpec((k, d), lambda i, t: (0, 0)),
            pl.BlockSpec((k, d), lambda i, t: (0, 0)),
            pl.BlockSpec((1, k), lambda i, t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 2 * k, d), lambda i, t: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 2 * k, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((1, k), jnp.float32),
            pltpu.VMEM((k, d), jnp.float32),
            pltpu.VMEM((k, d), jnp.float32),
            pltpu.SMEM((1,), jnp.float32),
        ],
        interpret=interpret,
    )(
        xs.astype(_precision().fdtype(mxu)),
        mask.astype(jnp.float32)[:, None, :],
        logw.astype(jnp.float32),
        mu.astype(jnp.float32),
        inv.astype(jnp.float32),
        lognorm.astype(jnp.float32),
    )
    return out.reshape(n, 2 * k * d)


@functools.partial(
    jax.jit, static_argnames=("interpret", "mxu", "normalize")
)
def fused_forward_pallas(
    desc,
    mask,
    components,
    mean,
    w,
    mu,
    var,
    interpret: bool = False,
    mxu: str = "f32",
    normalize: bool = True,
):
    """[SIFT-normalize →] PCA-project → FV-encode as ONE Pallas kernel.

    ``desc``: (n, T, d_in) descriptors — RAW (pre-normalize) windowed
    SIFT output with ``normalize=True``, already-normalized descriptors
    with ``normalize=False``; ``mask``: (n, T); ``components``:
    (d_in, d) PCA projection; ``mean``: (d_in,) or None; GMM
    ``(w (K,), mu/var (K, d))`` → (n, 2·K·D).

    Matches the per-stage chain ``ops/sift._sift_normalize →
    models/pca.PCATransformer → ops/fisher._fisher_encode`` to f32
    rounding.  HBM traffic collapses from three round trips (normalized
    descriptors out+in, projected descriptors out+in, FV out) to one
    descriptor read and one FV write; on the un-jitted serve path the
    three program launches become one.  Under ``mxu='bf16'`` /
    ``'bf16_apply'`` the descriptor stream crosses HBM at half width;
    all VMEM compute stays f32."""
    n, t, d_in = desc.shape
    k, d = mu.shape
    # VMEM budget must hold BOTH descriptor widths per tile (raw d_in
    # and projected d) on top of the γ/logp copies
    tile_t = _tile_t(t, k, d_in + d)
    tiles = -(-t // tile_t)
    if tiles * tile_t != t:
        pad = tiles * tile_t - t
        desc = jnp.pad(desc, ((0, 0), (0, pad), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    inv = 1.0 / var
    logw = jnp.log(w).reshape(1, k)
    lognorm = (-0.5 * (jnp.sum(jnp.log(var), axis=1) + d * _LOG2PI)).reshape(1, k)
    mean_row = (
        jnp.zeros((1, d_in), jnp.float32)
        if mean is None
        else jnp.asarray(mean, jnp.float32).reshape(1, d_in)
    )

    grid = (n, tiles)
    out = pl.pallas_call(
        functools.partial(_fv_fused_kernel, normalize=bool(normalize)),
        grid=grid,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        in_specs=[
            pl.BlockSpec((1, tile_t, d_in), lambda i, t: (i, t, 0)),
            pl.BlockSpec((1, 1, tile_t), lambda i, t: (i, 0, t)),
            pl.BlockSpec((d_in, d), lambda i, t: (0, 0)),
            pl.BlockSpec((1, d_in), lambda i, t: (0, 0)),
            pl.BlockSpec((1, k), lambda i, t: (0, 0)),
            pl.BlockSpec((k, d), lambda i, t: (0, 0)),
            pl.BlockSpec((k, d), lambda i, t: (0, 0)),
            pl.BlockSpec((1, k), lambda i, t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 2 * k, d), lambda i, t: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 2 * k, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((1, k), jnp.float32),
            pltpu.VMEM((k, d), jnp.float32),
            pltpu.VMEM((k, d), jnp.float32),
            pltpu.SMEM((1,), jnp.float32),
        ],
        interpret=interpret,
    )(
        desc.astype(_precision().fdtype(mxu)),
        mask.astype(jnp.float32)[:, None, :],
        components.astype(jnp.float32),
        mean_row,
        logw.astype(jnp.float32),
        mu.astype(jnp.float32),
        inv.astype(jnp.float32),
        lognorm.astype(jnp.float32),
    )
    return out.reshape(n, 2 * k * d)


def pallas_supported(x=None) -> bool:
    """True when the computation targets a device that can run TPU pallas
    kernels.  The target is resolved in priority order: the active
    framework mesh (covers CPU-mesh dryruns on TPU hosts), the concrete
    input array's committed devices, then the default backend."""
    _TPU = ("tpu", "axon")
    try:
        from keystone_tpu.parallel.mesh import active_mesh

        m = active_mesh()
        if m is not None and m.devices.size:
            return m.devices.flat[0].platform in _TPU
    except Exception:
        pass
    if x is not None:
        try:
            devs = x.devices() if callable(getattr(x, "devices", None)) else None
            if devs:
                return next(iter(devs)).platform in _TPU
        except Exception:
            pass  # tracers and numpy inputs carry no device info
    try:
        return jax.devices()[0].platform in _TPU
    except Exception:
        return False
