"""Image feature ops (reference src/main/scala/nodes/images/).

Images are batched NHWC float arrays.  The reference's per-image
im2col + BLAS gemm loops (executor map tasks) become whole-batch XLA
convolutions that tile directly onto the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from keystone_tpu.workflow.transformer import Transformer
from keystone_tpu.utils import precision


class Convolver(Transformer):
    """Convolution of K learned/random filters over images
    (nodes/images/Convolver.scala — the CIFAR feature extractor).

    ``filters``: (num_filters, fh, fw, c).  The reference's optional patch
    whitening is folded into the filters/offset via
    :meth:`from_whitened_patches`: convolving ZCA-whitened patches with
    raw filters equals convolving raw patches with ``W_zca·filters`` plus
    a constant offset — one gemm instead of two.

    Two physical forms (the reference's NodeOptimizationRule chose conv
    strategies the same way — SURVEY.md §2.1):

    - ``"direct"`` — ``lax.conv_general_dilated``, XLA's native conv path;
    - ``"im2col"`` — explicit patch extraction + ONE (N·OH·OW, fh·fw·c) ×
      (fh·fw·c, K) gemm, the reference's own execution strategy and a
      better MXU mapping when the patch dim and filter count are both
      MXU-friendly (≥~128) while the conv is small;
    - ``"auto"`` (default) — resolved per shape from the measured
      crossover (BASELINE.md "Convolver strategy crossover"), pinned to a
      concrete form by the optimizer's NodeChoiceRule when it samples.
    """

    strategy = "auto"  # class default for pre-strategy pickles
    # fitted filters/offset ride as traced jit arguments (refits and
    # sibling instances share programs; no lowering read-back)
    traced_attrs = ("filters", "offset")

    def jit_static(self):
        return (self.stride, self.strategy)

    def __init__(
        self,
        filters: jnp.ndarray,
        stride: int = 1,
        offset=None,
        strategy: str = "auto",
    ):
        if strategy not in ("auto", "direct", "im2col"):
            raise ValueError(f"unknown Convolver strategy {strategy!r}")
        self.filters = jnp.asarray(filters, jnp.float32)
        self.stride = int(stride)
        self.offset = offset  # (num_filters,) additive term
        self.strategy = strategy

    @classmethod
    def from_whitened_patches(
        cls, patches: jnp.ndarray, whitener, patch_shape, stride: int = 1
    ) -> "Convolver":
        """Build from flat random patches + a fitted ZCAWhitener
        (RandomPatchCifar pattern): filters = (W_zca · Pᵀ) reshaped,
        offset = −mean·W_zca·Pᵀ."""
        fh, fw, c = patch_shape
        p = jnp.asarray(patches, jnp.float32)  # (K, fh*fw*c), whitened space
        w_eff = whitener.whitener @ p.T  # (d, K)
        offset = -(whitener.mean @ w_eff)  # (K,)
        filters = w_eff.T.reshape(-1, fh, fw, c)
        return cls(filters, stride=stride, offset=offset)

    def params(self):
        from keystone_tpu.utils.hashing import cached_fingerprint

        if self.offset is None:
            fp = cached_fingerprint(self, "_fp", self.filters)
        else:
            fp = cached_fingerprint(self, "_fp", self.filters, self.offset)
        return (
            self.filters.shape,
            fp,
            self.stride,
            self.offset is None,
            self.strategy,
        )

    def choose_physical(self, sample):
        """Pin ``"auto"`` to the measured-best concrete strategy for the
        sampled image shape (NodeOptimizationRule conv choice)."""
        if self.strategy != "auto" or sample is None or sample.is_host:
            return self
        shape = tuple(sample.array.shape)
        if len(shape) == 3:
            shape = shape + (1,)
        if len(shape) != 4:
            return self
        picked = _pick_conv_strategy(
            shape[1], shape[2], self.filters.shape, self.stride
        )
        return Convolver(
            self.filters, stride=self.stride, offset=self.offset, strategy=picked
        )

    def apply_batch(self, xs, mask=None):
        # The FEATURIZE bf16 policy skips the Convolver (XLA's default
        # precision already runs f32 convs as bf16-grade MXU passes;
        # explicit casts measured 0.94× at CIFAR shapes in isolation).
        # The opt-in APPLY policy ('bf16_apply') converts it anyway: in a
        # fused forward program the casts halve the inter-stage streams,
        # and accumulation stays f32 (utils/precision.apply_dot/acast).
        # apply_mode() is resolved at trace time; every jit wrapper that
        # traces this (per-instance, class-shared, fused-chain) keys its
        # cache on the resolved mode.
        if xs.ndim == 3:
            xs = xs[..., None]
        xs = xs.astype(jnp.float32)
        mxu = precision.apply_mode()
        strategy = self.strategy
        if strategy == "auto":
            strategy = _pick_conv_strategy(
                xs.shape[1], xs.shape[2], self.filters.shape, self.stride
            )
        if strategy == "im2col":
            out = self._apply_im2col(xs, mxu)
        else:
            rhs = jnp.transpose(self.filters, (1, 2, 3, 0))  # HWIO
            if mxu == "bf16_apply":
                xs_c, rhs_c = precision.acast(xs, rhs, mode=mxu)
                out = lax.conv_general_dilated(
                    xs_c,
                    rhs_c,
                    window_strides=(self.stride, self.stride),
                    padding="VALID",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    preferred_element_type=jnp.float32,
                )
            else:
                out = lax.conv_general_dilated(
                    xs,
                    rhs,
                    window_strides=(self.stride, self.stride),
                    padding="VALID",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )
        if self.offset is not None:
            out = out + self.offset
        return out

    def _apply_im2col(self, xs, mxu: str = "f32"):
        """Patch extraction + one gemm — the reference's own execution
        plan (Windower im2col → BLAS gemm, SURVEY.md §3.3), mapped to the
        MXU as a single (N·OH·OW, fh·fw·c) × (fh·fw·c, K) contraction."""
        k, fh, fw, c = self.filters.shape
        n, h, w, _ = xs.shape
        patches = lax.conv_general_dilated_patches(
            xs,
            filter_shape=(fh, fw),
            window_strides=(self.stride, self.stride),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # (n, oh, ow, c*fh*fw) — channel-major patch layout
        oh, ow = patches.shape[1], patches.shape[2]
        # filters (k, fh, fw, c) -> (c, fh, fw, k) flattened to match the
        # patches' (c, fh, fw) minor order
        rhs = jnp.transpose(self.filters, (3, 1, 2, 0)).reshape(c * fh * fw, k)
        out = precision.apply_dot(
            patches.reshape(n * oh * ow, c * fh * fw), rhs, mode=mxu
        )
        return out.reshape(n, oh, ow, k)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


#: measured crossover, TPU v5 lite (BASELINE.md "Convolver strategy
#: crossover"): the im2col patches tensor per image — (oh·ow) positions
#: × (fh·fw·c) patch dim — below this many elements, patch-extract+gemm
#: beats XLA's conv emitter (its fixed per-conv costs dominate small
#: convs); above it, materializing patches loses to the fused conv.
_IM2COL_MAX_PATCH_ELEMENTS = 58_000


def _pick_conv_strategy(h: int, w: int, filter_shape, stride: int) -> str:
    k, fh, fw, c = filter_shape
    oh = max(0, (h - fh) // stride + 1)
    ow = max(0, (w - fw) // stride + 1)
    if oh * ow * fh * fw * c <= _IM2COL_MAX_PATCH_ELEMENTS:
        return "im2col"
    return "direct"


class Pooler(Transformer):
    """Spatial pooling over a grid with a pluggable pixel function
    (nodes/images/Pooler.scala): out[g] = Σ_{p∈cell g} pixel_fn(x[p])."""

    def __init__(
        self,
        stride: int,
        pool_size: int,
        pixel_fn: Optional[Callable] = None,
        pool_mode: str = "sum",
    ):
        self.stride = int(stride)
        self.pool_size = int(pool_size)
        self.pixel_fn = pixel_fn
        self.pool_mode = pool_mode

    def params(self):
        return (self.stride, self.pool_size, self.pool_mode, self.pixel_fn is None)

    def apply_batch(self, xs, mask=None):
        x = xs.astype(jnp.float32)
        if self.pixel_fn is not None:
            x = self.pixel_fn(x)
        dims = (1, self.pool_size, self.pool_size, 1)
        strides = (1, self.stride, self.stride, 1)
        if self.pool_mode == "sum":
            return lax.reduce_window(x, 0.0, lax.add, dims, strides, "VALID")
        if self.pool_mode == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, "VALID")
        raise ValueError(f"unknown pool mode {self.pool_mode}")

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


class SymmetricRectifier(Transformer):
    """Channel-doubling rectifier [max(0, x−α), max(0, −x−α)]
    (nodes/images/SymmetricRectifier.scala)."""

    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = float(max_val)
        self.alpha = float(alpha)

    def params(self):
        return (self.max_val, self.alpha)

    def apply_batch(self, xs, mask=None):
        pos = jnp.maximum(xs - self.alpha, self.max_val)
        neg = jnp.maximum(-xs - self.alpha, self.max_val)
        return jnp.concatenate([pos, neg], axis=-1)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


class GrayScaler(Transformer):
    """NHWC → NHW luminance via channel mean (nodes/images/GrayScaler.scala)."""

    def params(self):
        return ()

    def apply_batch(self, xs, mask=None):
        if xs.ndim == 3 or xs.shape[-1] == 1:
            return xs.reshape(xs.shape[:3])
        return jnp.mean(xs, axis=-1)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


class ImageVectorizer(Transformer):
    """Image → flat vector (nodes/images/ImageVectorizer.scala)."""

    def params(self):
        return ()

    def apply_batch(self, xs, mask=None):
        return xs.reshape(xs.shape[0], -1)

    def apply_one(self, x):
        return x.reshape(-1)


class PixelScaler(Transformer):
    """uint8 pixels → [0,1] floats (nodes/images/PixelScaler.scala).

    ``only_if_integer=True`` divides only integer inputs and passes
    floating inputs through as f32 — for pipelines whose loaders ship
    uint8 (cheap transfer) but that must also accept pre-normalized
    [0,1] float arrays without silently collapsing them to ~1/255 scale.
    (The default stays unconditional: e.g. MNIST CSV loads *floats* in
    [0,255] that genuinely need the division.)  The dtype check is
    static at trace time — no runtime branch under jit.
    """

    def __init__(self, scale: float = 255.0, only_if_integer: bool = False):
        self.scale = float(scale)
        self.only_if_integer = bool(only_if_integer)

    def params(self):
        return (self.scale, self.only_if_integer)

    def apply_batch(self, xs, mask=None):
        if self.only_if_integer and jnp.issubdtype(
            jnp.asarray(xs).dtype, jnp.floating
        ):
            return jnp.asarray(xs, jnp.float32)
        return xs.astype(jnp.float32) / self.scale

    def apply_one(self, x):
        return self.apply_batch(jnp.asarray(x)[None])[0]


class Windower(Transformer):
    """Sliding-window patch extraction (nodes/images/Windower.scala):
    (n, H, W, C) → (n, num_windows, wh·ww·C) flat patches."""

    def __init__(self, step: int, window_size: int):
        self.step = int(step)
        self.window_size = int(window_size)

    def params(self):
        return (self.step, self.window_size)

    def apply_batch(self, xs, mask=None):
        if xs.ndim == 3:
            xs = xs[..., None]
        n, h, w, c = xs.shape
        ws = self.window_size
        patches = lax.conv_general_dilated_patches(
            xs.astype(jnp.float32),
            filter_shape=(ws, ws),
            window_strides=(self.step, self.step),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # (n, H', W', C*ws*ws) with feature index (c, dy, dx)
        hp, wp = patches.shape[1], patches.shape[2]
        # reorder feature dim (c, dy, dx) -> (dy, dx, c) to match
        # row-major patch flattening
        patches = patches.reshape(n, hp * wp, c, ws, ws)
        patches = jnp.transpose(patches, (0, 1, 3, 4, 2))
        return patches.reshape(n, hp * wp, ws * ws * c)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


class RandomPatcher(Transformer):
    """Random patch extraction (nodes/images/RandomPatcher.scala):
    (n, H, W, C) → (n·num_patches, ph·pw·C) — train-time feature learning."""

    fusable = False

    def __init__(self, num_patches: int, patch_h: int, patch_w: int, seed: int = 0):
        self.num_patches = int(num_patches)
        self.patch_h = int(patch_h)
        self.patch_w = int(patch_w)
        self.seed = int(seed)

    def params(self):
        return (self.num_patches, self.patch_h, self.patch_w, self.seed)

    def apply_batch(self, xs, mask=None):
        if xs.ndim == 3:
            xs = xs[..., None]
        return _random_patches(
            xs.astype(jnp.float32),
            self.num_patches,
            self.patch_h,
            self.patch_w,
            jax.random.PRNGKey(self.seed),
        )

    def apply_dataset(self, ds):
        out = self.apply_batch(ds.array[: ds.n])
        from keystone_tpu.workflow.dataset import Dataset

        return Dataset(out)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


@partial(jax.jit, static_argnames=("k", "ph", "pw"))
def _random_patches(xs, k, ph, pw, key):
    n, h, w, c = xs.shape
    ky, kx = jax.random.split(key)
    ys = jax.random.randint(ky, (n, k), 0, h - ph + 1)
    xoff = jax.random.randint(kx, (n, k), 0, w - pw + 1)

    def one(img, yy, xx):
        def slice_one(y0, x0):
            return lax.dynamic_slice(img, (y0, x0, 0), (ph, pw, c))

        return jax.vmap(slice_one)(yy, xx)

    patches = jax.vmap(one)(xs, ys, xoff)  # (n, k, ph, pw, c)
    return patches.reshape(n * k, ph * pw * c)


class CenterCornerPatcher(Transformer):
    """Center + 4 corner crops, optionally horizontally flipped
    (nodes/images/CenterCornerPatcher.scala) — the 10-view test-time
    augmentation for ImageNet.  Output: (n, num_views, ph, pw, C)."""

    def __init__(self, patch_h: int, patch_w: int, horizontal_flips: bool = False):
        self.patch_h = int(patch_h)
        self.patch_w = int(patch_w)
        self.horizontal_flips = horizontal_flips

    def params(self):
        return (self.patch_h, self.patch_w, self.horizontal_flips)

    def apply_batch(self, xs, mask=None):
        if xs.ndim == 3:
            xs = xs[..., None]
        n, h, w, c = xs.shape
        ph, pw = self.patch_h, self.patch_w
        starts = [
            (0, 0),
            (0, w - pw),
            (h - ph, 0),
            (h - ph, w - pw),
            ((h - ph) // 2, (w - pw) // 2),
        ]
        views = [xs[:, y : y + ph, x : x + pw, :] for (y, x) in starts]
        if self.horizontal_flips:
            views = views + [v[:, :, ::-1, :] for v in views]
        return jnp.stack(views, axis=1)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]
