"""Feature ops (reference src/main/scala/nodes/{stats,images,nlp,misc,util}/)."""

from keystone_tpu.ops.stats import (  # noqa: F401
    ColumnSampler,
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    Sampler,
    SignedHellingerMapper,
    StandardScaler,
    StandardScalerModel,
)
from keystone_tpu.ops.sparse import (  # noqa: F401
    BucketedSparseRows,
    PaddedSparseRows,
)
from keystone_tpu.ops.util import (  # noqa: F401
    ClassLabelIndicators,
    Densify,
    FloatToDouble,
    MaxClassifier,
    Sparsify,
    TopKClassifier,
    VectorCombiner,
    VectorSplitter,
)
from keystone_tpu.ops.images import (  # noqa: F401
    CenterCornerPatcher,
    Convolver,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    Pooler,
    RandomPatcher,
    SymmetricRectifier,
    Windower,
)
from keystone_tpu.ops.sift import SIFTExtractor  # noqa: F401
from keystone_tpu.ops.lcs import LCSExtractor  # noqa: F401
from keystone_tpu.ops.daisy import DaisyExtractor  # noqa: F401
from keystone_tpu.ops.fisher import (  # noqa: F401
    FisherVector,
    FusedPcaFisherVector,
    GMMFisherVectorEstimator,
)
from keystone_tpu.ops.nlp import (  # noqa: F401
    CommonSparseFeatures,
    HashingTF,
    LowerCase,
    NGramsCounts,
    NGramsFeaturizer,
    StupidBackoffLM,
    TermFrequency,
    log_tf,
    Tokenizer,
    Trimmer,
)
