"""Dense SIFT.

Reference: nodes/images/external/SIFTExtractor.scala → JNI
utils/external/VLFeat.scala (``vl_dsift_*`` C library; params: step,
scales, bin size; returns 128 × #keypoints per image).  SURVEY.md §2.8
calls for a first-class TPU-era equivalent; this is dense SIFT as
vectorized JAX: gradient → 8-orientation soft binning → then, by
default ("matmul" windowing), triangular spatial windowing + 4×4 bin
extraction as TWO dense MXU einsums over precomputed (centers·4, extent)
window operators — the conv+strided-slice+transpose chain is a linear
map, and running it as matmuls removes the depthwise convs and the
layout copies the r2 trace showed at ~40% of headline device time.  The
"conv" windowing (depthwise conv → strided bin slices) remains as the
fallback and the parity reference.  Then the standard SIFT normalize
(L2, clamp 0.2, re-L2).  The whole extractor is one jitted program over
the batch; per-image descriptor counts are fixed by the image size, so
outputs are dense (n, K, 128) with an all-ones mask joining the ragged
pipeline downstream.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.transformer import Transformer
from keystone_tpu.utils import precision

_NUM_ORIENTATIONS = 8
_GRID = 4  # 4x4 spatial bins -> 128-d descriptors

#: DESCRIPTOR LAYOUT CONTRACT (decided r5, VERDICT r4 item 3).  The
#: canonical 128-d feature order is (y_bin, x_bin, orientation) —
#: feature index f = gy·(4·8) + gx·8 + o, matching VLFeat's vl_dsift
#: layout, produced by an explicit (ky,4,kx,4)→(ky,kx,4,4) transpose
#: on both windowing paths.  The alternative the r4 roadmap proposed —
#: absorbing the permutation by emitting T-contiguous output straight
#: from the second windowing einsum ("xqw,nygwo->nyxqgo") — was BUILT
#: AND REFUTED by the r5 per-op device trace: XLA materializes the
#: requested dot output order as epilogue copies (~483 µs/multi-scale
#: batch) plus new reshape copies (~231 µs), for 2115 µs device-busy
#: vs 1528 µs with the explicit transpose (~190 µs).  The transpose IS
#: the measured-optimal form of the layout price; golden VLFeat
#: vectors, when available, compare directly with no permutation.
_DESCRIPTOR_ORDER = ("y_bin", "x_bin", "orientation")


class SIFTExtractor(Transformer):
    """Dense SIFT descriptors on a keypoint grid.

    Input: grayscale images (n, H, W).  Output: ragged-style
    ((n, K, 128), mask) descriptor sets, K = Σ_scales Ky·Kx.
    """

    fusable = False
    # Class-level default so pipelines pickled before smoothing existed
    # unpickle to the behavior they were fitted with (no smoothing).
    smoothing_magnif = 0.0
    # pre-windowing pickles ran the conv path
    windowing = "conv"
    # pre-fused-forward pickles always normalized
    normalize = True

    def __init__(
        self,
        step: int = 4,
        bin_sizes: Sequence[int] = (4,),
        smoothing_magnif: float = 6.0,
        windowing: str = "matmul",
        normalize: bool = True,
    ):
        if windowing not in ("conv", "matmul"):
            raise ValueError(f"unknown SIFT windowing {windowing!r}")
        #: VLFeat smoothing: before gradients, each scale's image is
        #: blurred with σ = √((bin/magnif)² − 0.25) (``vl_phow``'s
        #: convention; the −0.25 discounts the camera's implicit ~0.5px
        #: blur).  magnif=6 matches VLFeat's default; 0 disables (the
        #: round-1 behavior, and the single-scale fast path when σ≲0.2).
        self.step = int(step)
        self.bin_sizes = tuple(int(b) for b in bin_sizes)
        self.smoothing_magnif = float(smoothing_magnif)
        #: "matmul" (default): windowing + bin extraction as two MXU
        #: einsums.  Wall-clock is WITHIN NOISE of the conv path at the
        #: headline config (BASELINE.md r3 A/B: both ~7 µs/image — the
        #: conv windowing was device time already overlapped with other
        #: stages); matmul stays default because it removes the
        #: layout-copy stage from the graph and is exactly parity-tested.
        #: "conv" keeps the r2 path.
        self.windowing = windowing
        #: False emits RAW windowed descriptors (the L2→clamp→re-L2 tail
        #: skipped) — set by the optimizer's PallasFvFusionRule when the
        #: downstream fused forward megakernel absorbs the normalize
        #: in-VMEM (ops/fisher_pallas.fused_forward_pallas).  Raw
        #: descriptors are NOT scale-invariant; only a consumer that
        #: normalizes should ever see them.
        self.normalize = bool(normalize)

    def params(self):
        return (
            self.step,
            self.bin_sizes,
            self.smoothing_magnif,
            self.windowing,
            self.normalize,
        )

    def _sigma(self, bin_size: int) -> float:
        if self.smoothing_magnif <= 0:
            return 0.0
        s2 = (bin_size / self.smoothing_magnif) ** 2 - 0.25
        return float(np.sqrt(s2)) if s2 > 0.04 else 0.0

    def apply_batch(self, xs, mask=None):
        xs = jnp.asarray(xs, jnp.float32)
        if xs.ndim == 4 and xs.shape[-1] == 1:
            xs = xs[..., 0]
        descs = []
        for b in self.bin_sizes:
            descs.append(
                _dsift(
                    xs,
                    self.step,
                    b,
                    mxu=precision.matmul_mode(),
                    sigma=self._sigma(b),
                    windowing=self.windowing,
                    normalize=self.normalize,
                )
            )
        out = jnp.concatenate(descs, axis=1)
        return out, jnp.ones(out.shape[:2], jnp.float32)

    def apply_one(self, x):
        d, m = self.apply_batch(x[None])
        return d[0]


def _triangular_kernel(bin_size: int) -> np.ndarray:
    """VLFeat's bilinear spatial window: support 2·bin_size−1."""
    r = np.arange(1 - bin_size, bin_size, dtype=np.float32)
    return np.maximum(0.0, 1.0 - np.abs(r) / bin_size)


def _bin_offsets(bin_size: int) -> np.ndarray:
    """The 4 bin-center offsets.  Truncation toward zero for odd bin
    sizes is part of the descriptor definition — the conv and matmul
    windowing paths MUST share it or their parity silently breaks."""
    return ((np.arange(_GRID) - (_GRID - 1) / 2.0) * bin_size).astype(np.int64)


def _keypoint_grid(extent: int, step: int, bin_size: int) -> np.ndarray:
    """Descriptor-center coordinates along one axis.

    A descriptor centered at c covers c ± (2·bin_size − 0.5) pixels
    (4 bins of bin_size with the triangular window); keep centers whose
    support fits in the image.
    """
    margin = 2 * bin_size
    lo, hi = margin, extent - margin
    if hi <= lo:
        return np.zeros((0,), np.int32)
    return np.arange(lo, hi, step, dtype=np.int32)


def _window_matrix(
    extent: int, step: int, bin_size: int
) -> Tuple[np.ndarray, int]:
    """Dense windowing operator A (num_centers·4, extent): row (c, b)
    holds the triangular window centered at keypoint-center c plus bin
    offset b, zero outside the image (== the SAME-padded conv).

    The separable conv + strided slice + transpose chain is a LINEAR map
    of the orientation planes, so it can run as ONE (P, extent) matmul
    per axis on the MXU instead of a depthwise conv (VPU/bandwidth
    bound) followed by slices and layout copies — the r2 trace showed
    those fusions + copies at ~40% of headline device time."""
    centers = _keypoint_grid(extent, step, bin_size)
    if centers.size == 0:
        return np.zeros((0, extent), np.float32), 0
    offs = _bin_offsets(bin_size)
    k1 = _triangular_kernel(bin_size)  # support 2*bin-1, centered
    a = np.zeros((centers.size * _GRID, extent), np.float32)
    half = bin_size - 1
    for ci, c in enumerate(centers):
        for bi, off in enumerate(offs):
            mid = int(c + off)
            lo, hi = mid - half, mid + half + 1
            klo = max(0, -lo)
            khi = k1.size - max(0, hi - extent)
            a[ci * _GRID + bi, max(lo, 0) : min(hi, extent)] = k1[klo:khi]
    return a, centers.size


def _gradient_orientation_map(imgs):
    """Gradient → 8-orientation soft binning: (n, h, w) → (n, h, w, 8).

    Central-difference gradients (vl_dsift's convention), then magnitude
    linearly interpolated between the two adjacent orientation bins.
    Shared by both windowing paths; the elementwise producer of the
    windowing einsums' input."""
    dy = jnp.pad(imgs[:, 2:, :] - imgs[:, :-2, :], ((0, 0), (1, 1), (0, 0))) * 0.5
    dx = jnp.pad(imgs[:, :, 2:] - imgs[:, :, :-2], ((0, 0), (0, 0), (1, 1))) * 0.5
    mag = jnp.sqrt(dx * dx + dy * dy)
    ang = jnp.arctan2(dy, dx)  # [-pi, pi]

    o = _NUM_ORIENTATIONS
    theta = (ang % (2 * jnp.pi)) * (o / (2 * jnp.pi))  # [0, 8)
    lo_bin = jnp.floor(theta)
    frac = theta - lo_bin
    lo_bin = lo_bin.astype(jnp.int32) % o
    hi_bin = (lo_bin + 1) % o
    bins = jnp.arange(o)[None, None, None, :]
    return mag[..., None] * (
        (bins == lo_bin[..., None]) * (1.0 - frac[..., None])
        + (bins == hi_bin[..., None]) * frac[..., None]
    )  # (n, h, w, 8)


@partial(
    jax.jit,
    static_argnames=(
        "step", "bin_size", "mxu", "sigma", "windowing", "normalize"
    ),
)
def _dsift(
    imgs,
    step,
    bin_size,
    mxu: str = "f32",
    sigma: float = 0.0,
    windowing: str = "matmul",
    normalize: bool = True,
):
    from keystone_tpu.ops.filters import separable_gaussian_blur

    n, h, w = imgs.shape

    # --- per-scale Gaussian smoothing (vl_dsift applies it per bin size
    # when smoothing != 0).  The blur's physical form follows the
    # windowing choice: the matmul path runs it as banded-matrix MXU
    # einsums (r4 roofline: the depthwise convs ran at ~0.1× of their
    # byte bound); the conv path stays the bit-stable parity reference.
    # The policy mode rides along so bf16_apply halves the blur's input
    # stream too (the banded einsums are the first contraction the
    # images hit).
    if sigma > 0.0:
        imgs = separable_gaussian_blur(
            imgs[..., None], sigma, strategy=windowing, mxu=mxu
        )[..., 0]

    o = _NUM_ORIENTATIONS
    omap = _gradient_orientation_map(imgs)  # (n, h, w, 8)

    if windowing == "matmul":
        # --- windowing + bin extraction as two MXU matmuls ---
        ay, ky = _window_matrix(h, step, bin_size)
        ax, kx = _window_matrix(w, step, bin_size)
        if ky == 0 or kx == 0:
            return jnp.zeros((n, 0, _GRID * _GRID * o), jnp.float32)
        ay_c, ax_c, omap_c = precision.fcast(
            jnp.asarray(ay), jnp.asarray(ax), omap, mode=mxu
        )
        # contract image rows then columns; output arrives already in
        # descriptor-major bins — no strided slices.  The explicit
        # (ky,4,kx,4) transpose below IS the measured-optimal layout
        # form: emitting T-contiguous output straight from the second
        # einsum ("xqw,nygwo->nyxqgo", r5 experiment) made XLA pay
        # dot-epilogue + reshape copies of 2115 µs multi-scale
        # device-busy vs 1528 µs for this transpose (_DESCRIPTOR_ORDER).
        r1 = jnp.einsum(
            "ph,nhwo->npwo", ay_c, omap_c, preferred_element_type=jnp.float32
        )
        r1_c = precision.fcast(r1, mode=mxu)
        g = jnp.einsum(
            "qw,npwo->npqo", ax_c, r1_c, preferred_element_type=jnp.float32
        )
        g = g.reshape(n, ky, _GRID, kx, _GRID, o)
        desc = jnp.transpose(g, (0, 1, 3, 2, 4, 5)).reshape(
            n, ky * kx, _GRID * _GRID * o
        )
        return _sift_normalize(desc) if normalize else desc

    # --- spatial triangular windowing: separable depthwise conv ---
    k1 = jnp.asarray(_triangular_kernel(bin_size))
    kh = k1.reshape(-1, 1, 1, 1) * jnp.eye(o)[None, None]  # (kh, 1, 8, 8)
    kw = k1.reshape(1, -1, 1, 1) * jnp.eye(o)[None, None]
    # bf16 windowing with f32 accumulation under the bf16 policy: the
    # window is a smooth positive kernel and descriptors are L2-normalized
    # and clamped downstream, so bf16 input rounding is within the
    # tolerance the parity tests assert (tests/test_precision.py)
    omap_c, kh_c, kw_c = precision.fcast(omap, kh, kw, mode=mxu)
    smoothed = lax.conv_general_dilated(
        omap_c,
        kh_c,
        (1, 1),
        "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )
    smoothed_c = precision.fcast(smoothed, mode=mxu)
    smoothed = lax.conv_general_dilated(
        smoothed_c,
        kw_c,
        (1, 1),
        "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )

    # --- extract 4x4 bin responses around each keypoint ---
    # Keypoint centers and bin offsets are both uniform grids, so the
    # "gather" is 16 STRIDED SLICES (stack over bin offsets), not a
    # dynamic gather — device traces showed the gather's index staging
    # costing ~15% of the whole forward per iteration.
    ys = _keypoint_grid(h, step, bin_size)  # numpy, uniform stride=step
    xs_ = _keypoint_grid(w, step, bin_size)
    ky, kx = ys.shape[0], xs_.shape[0]
    if ky == 0 or kx == 0:  # scale too large for the image: no keypoints
        return jnp.zeros((n, 0, _GRID * _GRID * o), jnp.float32)
    offs = _bin_offsets(bin_size)

    def bin_slices(arr, centers, axis):
        """(…, len(centers), _GRID, …): strided slice per bin offset."""
        parts = []
        for off in offs:
            lo = int(centers[0] + off)
            hi = int(centers[-1] + off) + 1
            parts.append(
                lax.slice_in_dim(arr, lo, hi, stride=step, axis=axis)
            )
        return jnp.stack(parts, axis=axis + 1)

    g = bin_slices(smoothed, ys, 1)  # (n, ky, 4, w, 8)
    g = bin_slices(g, xs_, 3)  # (n, ky, 4, kx, 4, 8)
    desc = jnp.transpose(g, (0, 1, 3, 2, 4, 5)).reshape(n, ky * kx, _GRID * _GRID * o)
    return _sift_normalize(desc) if normalize else desc


def _sift_normalize(desc):
    """SIFT normalization: L2 -> clamp 0.2 -> L2."""

    def l2(v):
        return v / jnp.maximum(jnp.linalg.norm(v, axis=-1, keepdims=True), 1e-8)

    desc = l2(desc)
    desc = jnp.minimum(desc, 0.2)
    return l2(desc)


def sift_output_count(h: int, w: int, step: int, bin_sizes: Sequence[int]) -> int:
    return sum(
        len(_keypoint_grid(h, step, b)) * len(_keypoint_grid(w, step, b))
        for b in bin_sizes
    )
