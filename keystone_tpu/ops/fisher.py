"""Fisher-vector encoding.

Reference: nodes/images/external/FisherVector.scala +
GMMFisherVectorEstimator → JNI utils/external/EncEval.scala (C++ GMM EM +
FV encode; SURVEY.md §2.8 "must get first-class TPU-era equivalents").

FV of a descriptor set {x_t} against a diagonal GMM (w, μ, σ²)
(Perronnin–Sánchez improved Fisher vector):

    γ_tk   = posterior responsibility of component k for x_t
    Φ¹_k   = 1/(T·√w_k)    · Σ_t γ_tk (x_t − μ_k)/σ_k
    Φ²_k   = 1/(T·√(2w_k)) · Σ_t γ_tk ((x_t − μ_k)²/σ²_k − 1)

concatenated to a 2·K·D vector per image.  Power/L2 normalization are the
separate SignedHellingerMapper / NormalizeRows nodes, as in the reference
pipeline.  The encode is a batched einsum over (n, max_k, d) ragged
descriptor sets with masks — MXU-shaped, replacing the per-image C++ loop.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from keystone_tpu.models.gmm import GaussianMixtureModel, GaussianMixtureModelEstimator
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import Estimator
from keystone_tpu.workflow.transformer import Transformer
from keystone_tpu.utils import precision


class FisherVector(Transformer):
    """Input: ragged ((n, max_k, d), mask) descriptor sets.
    Output: dense (n, 2·K·D) Fisher vectors.

    ``use_pallas`` — True routes through the fused VMEM-resident TPU
    kernel (ops/fisher_pallas.py); False forces the XLA einsum path; None
    (default) picks per call: the fused kernel on TPU when the
    responsibility tensor γ (T·K floats per image) is large enough to be
    HBM-bandwidth bound (re-measured r2 with the whole-image-tile
    kernel on v5 lite: 1.7× at T=784/K=64, 3× at T=784/K=256; parity
    at T ≤ 256 for any K), einsum otherwise.
    """

    fusable = False

    # per-image γ elements above which the fused kernel measurably wins
    _PALLAS_GAMMA_THRESHOLD = 32768

    # the fitted GMM (a registered pytree) rides as a traced argument:
    # both branch FV nodes share one compiled encode per shape, and the
    # vocabulary is never read back at lowering time
    traced_attrs = ("gmm",)

    def __init__(
        self, gmm: GaussianMixtureModel, use_pallas: Optional[bool] = None
    ):
        self.gmm = gmm
        self.use_pallas = use_pallas

    def jit_static(self):
        return (self.use_pallas,)

    def params(self):
        from keystone_tpu.utils.hashing import cached_fingerprint

        fp = cached_fingerprint(
            self, "_fp", self.gmm.weights, self.gmm.means, self.gmm.variances
        )
        return (fp, self.use_pallas)

    def apply_batch(self, xs, mask=None):
        if xs.ndim == 2:
            xs = xs[None]
            squeeze = True
        else:
            squeeze = False
        if mask is None:
            mask = jnp.ones(xs.shape[:2], jnp.float32)
        use_pallas = self.use_pallas
        if use_pallas is None:
            from keystone_tpu.ops.fisher_pallas import pallas_supported

            gamma_elems = xs.shape[1] * self.gmm.means.shape[0]
            use_pallas = (
                gamma_elems >= self._PALLAS_GAMMA_THRESHOLD
                and pallas_supported(xs)
            )
        if use_pallas:
            from keystone_tpu.ops.fisher_pallas import fisher_encode_pallas

            out = fisher_encode_pallas(
                xs,
                mask,
                self.gmm.weights,
                self.gmm.means,
                self.gmm.variances,
                mxu=precision.matmul_mode(),
            )
        else:
            out = _fisher_encode(
                xs,
                mask,
                self.gmm.weights,
                self.gmm.means,
                self.gmm.variances,
                mxu=precision.apply_mode(),
            )
        return out[0] if squeeze else out

    def apply_one(self, x):
        return self.apply_batch(x[None].reshape(1, *jnp.asarray(x).shape))[0]


class FusedPcaFisherVector(Transformer):
    """PCA projection + Fisher-vector encode as ONE kernel dispatch —
    the fused forward megakernel (ops/fisher_pallas.fused_forward_pallas).

    With ``sift_normalize=True`` it also absorbs SIFT's final
    L2→clamp→re-L2 tail, so a RAW-descriptor SIFT feed runs
    sift-normalize → PCA → FV in one program.  Built by the optimizer's
    ``PallasFvFusionRule`` from an adjacent single-consumer
    ``PCATransformer → FisherVector`` pair on Pallas-capable devices;
    off-TPU (or ``use_pallas=False``) it applies the IDENTICAL math as
    the per-stage XLA chain, so the transformer stays portable and
    parity-testable on CPU meshes.

    Not ``fusable``: like FisherVector it reduces a ragged (desc, mask)
    pair to a dense row — the generic chain fuser has no mask story.
    """

    fusable = False

    # fitted arrays ride as traced jit arguments (shared compiled
    # programs across refits; nothing read back at lowering time)
    traced_attrs = ("components", "mean", "gmm")

    def __init__(
        self,
        pca,
        gmm: GaussianMixtureModel,
        sift_normalize: bool = False,
        use_pallas: Optional[bool] = None,
    ):
        self.components = pca.components  # (d_in, d)
        self.mean = pca.mean  # (d_in,) or None
        self.gmm = gmm
        self.sift_normalize = bool(sift_normalize)
        self.use_pallas = use_pallas

    @property
    def label(self):
        tail = "SiftNorm > PCA > FV" if self.sift_normalize else "PCA > FV"
        return f"FusedFV[{tail}]"

    def jit_static(self):
        return (self.use_pallas, self.sift_normalize, self.mean is None)

    def params(self):
        from keystone_tpu.utils.hashing import cached_fingerprint

        arrays = [self.components]
        if self.mean is not None:
            arrays.append(self.mean)
        arrays += [self.gmm.weights, self.gmm.means, self.gmm.variances]
        fp = cached_fingerprint(self, "_fp", *arrays)
        return (fp, self.sift_normalize, self.use_pallas, self.mean is None)

    def apply_batch(self, xs, mask=None):
        if xs.ndim == 2:
            xs = xs[None]
            squeeze = True
        else:
            squeeze = False
        if mask is None:
            mask = jnp.ones(xs.shape[:2], jnp.float32)
        use_pallas = self.use_pallas
        if use_pallas is None:
            from keystone_tpu.ops.fisher_pallas import pallas_supported

            gamma_elems = xs.shape[1] * self.gmm.means.shape[0]
            use_pallas = (
                gamma_elems >= FisherVector._PALLAS_GAMMA_THRESHOLD
                and pallas_supported(xs)
            )
        if use_pallas:
            from keystone_tpu.ops.fisher_pallas import fused_forward_pallas

            out = fused_forward_pallas(
                xs,
                mask,
                self.components,
                self.mean,
                self.gmm.weights,
                self.gmm.means,
                self.gmm.variances,
                mxu=precision.matmul_mode(),
                normalize=self.sift_normalize,
            )
        else:
            # per-stage XLA fallback: bit-for-bit the unfused chain
            # (sift normalize → PCATransformer's matmul → _fisher_encode)
            z = xs
            if self.sift_normalize:
                from keystone_tpu.ops.sift import _sift_normalize

                z = _sift_normalize(z)
            if self.mean is not None:
                z = z - self.mean
            z_c, comp_c = precision.fcast(z, self.components)
            z = jnp.matmul(z_c, comp_c, preferred_element_type=jnp.float32)
            out = _fisher_encode(
                z,
                mask,
                self.gmm.weights,
                self.gmm.means,
                self.gmm.variances,
                mxu=precision.apply_mode(),
            )
        return out[0] if squeeze else out

    def apply_one(self, x):
        return self.apply_batch(jnp.asarray(x)[None])[0]


class GMMFisherVectorEstimator(Estimator):
    """Fits the GMM vocabulary on (sampled) descriptors and returns the
    FisherVector transformer (nodes/images/external/GMMFisherVectorEstimator)."""

    def __init__(self, k: int, max_iterations: int = 25, seed: int = 0):
        self.k = int(k)
        self.max_iterations = int(max_iterations)
        self.seed = int(seed)

    def params(self):
        return (self.k, self.max_iterations, self.seed)

    def fit_dataset(self, data: Dataset) -> FisherVector:
        gmm = GaussianMixtureModelEstimator(
            self.k, max_iterations=self.max_iterations, seed=self.seed
        ).fit_dataset(data)
        return FisherVector(gmm)

    def fit_arrays(self, x) -> FisherVector:
        gmm = GaussianMixtureModelEstimator(
            self.k, max_iterations=self.max_iterations, seed=self.seed
        ).fit_arrays(x)
        return FisherVector(gmm)


@partial(jax.jit, static_argnames=("mxu",))
def _fisher_encode(xs, mask, w, mu, var, mxu: str = "f32"):
    """xs: (n, T, d); mask: (n, T); w: (K,); mu, var: (K, d).

    NOT under the FEATURIZE bf16 policy: the sufficient-statistic einsums
    contract only over T and are OUTPUT-bound ((n, K, d) stays f32 either
    way), so bf16 input casts measured 0.64× in isolation at K=256,
    T=512 on v5 lite; the Pallas path gets its bf16 win at the HBM
    boundary instead (ops/fisher_pallas.py).  The opt-in APPLY policy
    (``mxu='bf16_apply'``, utils/precision.py) converts the posterior
    gemms and the s1/s2 einsums anyway — inside a fused forward program
    the casts also halve the γ/descriptor streams between contractions,
    and accumulation stays f32.  Inert modes trace the exact pre-policy
    graph (CPU meshes bit-identical).
    """
    sigma = jnp.sqrt(var)  # (K, d)
    # responsibilities, batched over images
    from keystone_tpu.models.gmm import _log_gaussians

    n, t, d = xs.shape
    flat = xs.reshape(n * t, d)
    if mxu == "bf16_apply":
        # the two (n·t, d)×(d, K) posterior gemms under the apply
        # policy; one copy of the math lives in gmm._log_gaussians, and
        # EM fitting (solver math) keeps the inert default dot.
        lg = _log_gaussians(
            flat, mu, var, jnp.log(w),
            dot=partial(precision.apply_dot, mode=mxu),
        )  # (n*t, K)
    else:
        lg = _log_gaussians(flat, mu, var, jnp.log(w))  # (n*t, K)
    lr = lg - jax.scipy.special.logsumexp(lg, axis=1, keepdims=True)
    gamma = (jnp.exp(lr).reshape(n, t, -1)) * mask[..., None]  # (n, T, K)

    counts = jnp.maximum(jnp.sum(mask, axis=1), 1.0)  # (n,) = T per image

    # standardized descriptors per component: (x − μ_k)/σ_k
    # Σ_t γ_tk x_t  and  Σ_t γ_tk x_t²  via einsum (MXU), then recombine
    s0 = jnp.einsum("ntk->nk", gamma)  # (n, K)
    s1 = precision.apply_einsum("ntk,ntd->nkd", gamma, xs, mode=mxu)
    s2 = precision.apply_einsum("ntk,ntd->nkd", gamma, xs * xs, mode=mxu)

    # Φ¹ = (s1 − s0·μ)/σ;  Φ² = (s2 − 2μ·s1 + s0·μ²)/σ² − s0
    phi1 = (s1 - s0[..., None] * mu) / sigma
    phi2 = (s2 - 2.0 * mu * s1 + s0[..., None] * (mu * mu)) / var - s0[..., None]

    tnorm = counts[:, None, None]
    phi1 = phi1 / (tnorm * jnp.sqrt(w)[None, :, None])
    phi2 = phi2 / (tnorm * jnp.sqrt(2.0 * w)[None, :, None])
    k, dd = mu.shape
    return jnp.concatenate(
        [phi1.reshape(n, k * dd), phi2.reshape(n, k * dd)], axis=1
    )
