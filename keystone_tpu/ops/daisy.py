"""DAISY dense descriptors.

Reference: nodes/images/DaisyExtractor.scala — pure-Scala DAISY
(oriented-gradient maps, Gaussian pooling at increasing scales, sampled at
a center + concentric rings; Tola et al. 2010).

TPU form: orientation maps are rectified directional gradients; each
ring's Gaussian pooling is one separable depthwise conv; ring samples are
static gathers.  Descriptor dim = (1 + rings·ring_points)·orientations
(default (1+3·8)·8 = 200).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from keystone_tpu.workflow.transformer import Transformer


class DaisyExtractor(Transformer):
    """Input: grayscale (n, H, W).  Output: ((n, K, D), mask)."""

    fusable = False

    def __init__(
        self,
        step: int = 4,
        radius: int = 15,
        rings: int = 3,
        ring_points: int = 8,
        orientations: int = 8,
    ):
        self.step = int(step)
        self.radius = int(radius)
        self.rings = int(rings)
        self.ring_points = int(ring_points)
        self.orientations = int(orientations)

    def params(self):
        return (self.step, self.radius, self.rings, self.ring_points, self.orientations)

    @property
    def descriptor_dim(self) -> int:
        return (1 + self.rings * self.ring_points) * self.orientations

    def apply_batch(self, xs, mask=None):
        xs = jnp.asarray(xs, jnp.float32)
        if xs.ndim == 4 and xs.shape[-1] == 1:
            xs = xs[..., 0]
        out = _daisy(
            xs, self.step, self.radius, self.rings, self.ring_points, self.orientations
        )
        return out, jnp.ones(out.shape[:2], jnp.float32)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0][0]


def _sep_gauss(omap, sigma):
    """Separable Gaussian depthwise blur of (n, h, w, o) maps."""
    from keystone_tpu.ops.filters import separable_gaussian_blur

    return separable_gaussian_blur(omap, sigma)


@partial(jax.jit, static_argnames=("step", "radius", "rings", "ring_points", "orients"))
def _daisy(imgs, step, radius, rings, ring_points, orients):
    n, h, w = imgs.shape
    dy = jnp.pad(imgs[:, 2:, :] - imgs[:, :-2, :], ((0, 0), (1, 1), (0, 0))) * 0.5
    dx = jnp.pad(imgs[:, :, 2:] - imgs[:, :, :-2], ((0, 0), (0, 0), (1, 1))) * 0.5
    # oriented gradient maps: max(0, cos(θ−o_k))·|g| == max(0, g·u_k)
    angles = np.arange(orients) * 2.0 * np.pi / orients
    ux = jnp.asarray(np.cos(angles), jnp.float32)
    uy = jnp.asarray(np.sin(angles), jnp.float32)
    omap = jnp.maximum(dx[..., None] * ux + dy[..., None] * uy, 0.0)

    # Gaussian pooling per ring (σ grows with radius, as in DAISY)
    ring_radii = [radius * (i + 1) / rings for i in range(rings)]
    sigmas = [max(0.5, rr / 2.0) for rr in [radius / rings] + ring_radii[:-1]]
    center_sigma = max(0.5, radius / (2.0 * rings))
    blurred = [_sep_gauss(omap, center_sigma)]
    for s in sigmas[1:] + [max(0.5, ring_radii[-1] / 2.0)]:
        blurred.append(_sep_gauss(omap, s))

    margin = int(radius + 3 * max(0.5, ring_radii[-1] / 2.0)) + 1
    ys = np.arange(margin, h - margin, step, dtype=np.int32)
    xs_ = np.arange(margin, w - margin, step, dtype=np.int32)
    if len(ys) == 0 or len(xs_) == 0:
        return jnp.zeros((n, 0, (1 + rings * ring_points) * orients), jnp.float32)

    pieces = [blurred[0][:, jnp.asarray(ys), :, :][:, :, jnp.asarray(xs_), :]]
    for ri, rr in enumerate(ring_radii):
        bmap = blurred[min(ri + 1, len(blurred) - 1)]
        for p in range(ring_points):
            a = 2.0 * np.pi * p / ring_points
            oy = int(round(rr * np.sin(a)))
            ox = int(round(rr * np.cos(a)))
            pieces.append(
                bmap[:, jnp.asarray(ys + oy), :, :][:, :, jnp.asarray(xs_ + ox), :]
            )
    stacked = jnp.stack(pieces, axis=3)  # (n, Ky, Kx, P, o)
    ky, kx = len(ys), len(xs_)
    desc = stacked.reshape(n, ky * kx, -1)
    # per-histogram L2 normalization (DAISY normalizes each histogram)
    dd = desc.reshape(n, ky * kx, -1, orients)
    dd = dd / jnp.maximum(jnp.linalg.norm(dd, axis=-1, keepdims=True), 1e-8)
    return dd.reshape(n, ky * kx, -1)
