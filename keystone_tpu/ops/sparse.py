"""TPU-native sparse feature representation: padded COO rows.

Reference: nodes/learning/LBFGS.scala § LeastSquaresSparseGradient — the
reference keeps CSR feature rows on executors and computes least-squares
gradients without ever densifying the n×d matrix (SURVEY.md §2.2).

The TPU analogue is pad-and-mask, the same strategy the framework uses
for ragged descriptor sets: each row carries up to ``nnz_max``
(index, value) pairs, padding entries have value 0.0 (index 0), so they
contribute nothing to either the forward gather-matvec or the gradient
scatter-add — no separate mask array is needed.  Memory is n·nnz·8 bytes
instead of n·d·4: at a 100k+ vocabulary and ~10² nonzeros per document
this is ~3 orders of magnitude smaller, which is what lets the text
pipelines run at realistic vocab sizes without densifying.

Shapes are static (nnz_max fixed at construction), so everything jits.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp

from keystone_tpu.parallel import mesh as _mesh


def is_scipy_sparse_rows(items) -> bool:
    """True for a non-empty sequence of scipy sparse row vectors."""
    return len(items) > 0 and all(
        hasattr(r, "tocoo") and hasattr(r, "shape") for r in items[:2]
    )


class PaddedSparseRows:
    """(n, nnz_max) int32 indices + float32 values + feature count.

    ``indices``/``values`` live on device, row-sharded over the mesh
    'data' axis like any Dataset array; rows past ``n`` and entries past
    a row's true nnz are value-0 padding.
    """

    def __init__(self, indices, values, num_features: int, n: Optional[int] = None,
                 shard: bool = True):
        self.n = int(np.shape(indices)[0] if n is None else n)
        self.num_features = int(num_features)
        if shard:
            self.indices = _mesh.shard_batch(np.asarray(indices, np.int32))
            self.values = _mesh.shard_batch(np.asarray(values, np.float32))
        else:
            self.indices = jnp.asarray(indices, jnp.int32)
            self.values = jnp.asarray(values, jnp.float32)

    @property
    def nnz_max(self) -> int:
        return int(self.indices.shape[1])

    @property
    def shape(self):
        return (self.n, self.num_features)

    @property
    def nbytes(self) -> int:
        return int(self.indices.size * 4 + self.values.size * 4)

    @staticmethod
    def from_scipy_rows(
        rows: Sequence, num_features: Optional[int] = None
    ) -> "PaddedSparseRows":
        """Build from scipy sparse row vectors (what ``Sparsify`` emits)."""
        coos = [r.tocoo() for r in rows]
        d = int(num_features if num_features is not None else coos[0].shape[-1])
        widths = {int(c.shape[-1]) for c in coos}
        if widths - {d}:
            # JAX's gather clamps out-of-range indices, so a
            # featurizer/weights width mismatch would silently mis-score;
            # fail loudly like the dense path's shape error instead.
            raise ValueError(
                f"sparse rows have width(s) {sorted(widths)} but "
                f"num_features={d}"
            )
        nnz_max = max(1, max((c.nnz for c in coos), default=1))
        n = len(coos)
        idx = np.zeros((n, nnz_max), np.int32)
        val = np.zeros((n, nnz_max), np.float32)
        for i, c in enumerate(coos):
            idx[i, : c.nnz] = c.col
            val[i, : c.nnz] = c.data
        return PaddedSparseRows(idx, val, d, n=n)

    @staticmethod
    def from_dense(x, threshold: float = 0.0) -> "PaddedSparseRows":
        x = np.asarray(x)
        mask = np.abs(x) > threshold
        nnz_max = max(1, int(mask.sum(axis=1).max()))
        n, d = x.shape
        idx = np.zeros((n, nnz_max), np.int32)
        val = np.zeros((n, nnz_max), np.float32)
        for i in range(n):
            cols = np.nonzero(mask[i])[0]
            idx[i, : cols.size] = cols
            val[i, : cols.size] = x[i, cols]
        return PaddedSparseRows(idx, val, d, n=n)

    def toarray(self) -> np.ndarray:
        """Dense (n, d) host copy (tests / small data only)."""
        idx = np.asarray(self.indices)[: self.n]
        val = np.asarray(self.values)[: self.n]
        out = np.zeros((self.n, self.num_features), np.float32)
        for i in range(self.n):
            np.add.at(out[i], idx[i], val[i])
        return out

    def matmul(self, w, intercept=None):
        """Gather-based ``X @ w`` without densifying: (n_rows, k)."""
        out = sparse_matmul(self.indices, self.values, jnp.asarray(w))
        if intercept is not None:
            out = out + intercept
        return out


def sparse_matmul(indices, values, w):
    """(rows, nnz) COO × (d, k) → (rows, k): gather rows of w, weight, sum.

    Padding entries (value 0) contribute nothing regardless of index."""
    wg = w[indices]  # (rows, nnz, k)
    return jnp.einsum(
        "rn,rnk->rk", values, wg, preferred_element_type=jnp.float32
    )


def align_label_rows(y, n: int, rows: int):
    """Validate + re-pad a label matrix for a sparse feature matrix.

    ``n`` true rows must all be present; rows beyond ``n`` are padding on
    both sides (possibly from different meshes), so truncating/expanding
    to ``rows`` drops no real data.  Raises on missing labels — silently
    zero-padding real rows would actively train toward a wrong model."""
    import jax.numpy as jnp

    y = jnp.asarray(y, jnp.float32)
    if y.shape[0] < n:
        raise ValueError(
            f"labels have {y.shape[0]} rows but the sparse matrix has "
            f"{n} true rows"
        )
    y = y[:rows]
    if y.shape[0] < rows:
        y = jnp.pad(y, ((0, rows - y.shape[0]), (0, 0)))
    return y


def score_sparse_dataset(ds, weights, intercept=None):
    """Score a host Dataset of scipy sparse rows against dense weights
    by gathering weight rows (shared by LinearMapper and the logistic
    model — n×d never densifies)."""
    sp = PaddedSparseRows.from_scipy_rows(
        ds.items, num_features=weights.shape[0]
    )
    return ds.with_array(sp.matmul(weights, intercept))


def sparse_grad(indices, values, r, d):
    """``Xᵀ r`` by scatter-add: (d, k) from (rows, nnz) COO and (rows, k).

    Duplicate indices accumulate (jnp ``.at[].add``); padding entries add
    zero."""
    k = r.shape[1]
    contrib = values[..., None] * r[:, None, :]  # (rows, nnz, k)
    return (
        jnp.zeros((d, k), jnp.float32)
        .at[indices.reshape(-1)]
        .add(contrib.reshape(-1, k))
    )
