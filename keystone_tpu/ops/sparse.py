"""TPU-native sparse feature representation: padded COO rows.

Reference: nodes/learning/LBFGS.scala § LeastSquaresSparseGradient — the
reference keeps CSR feature rows on executors and computes least-squares
gradients without ever densifying the n×d matrix (SURVEY.md §2.2).

The TPU analogue is pad-and-mask, the same strategy the framework uses
for ragged descriptor sets: each row carries up to ``nnz_max``
(index, value) pairs, padding entries have value 0.0 (index 0), so they
contribute nothing to either the forward gather-matvec or the gradient
scatter-add — no separate mask array is needed.  Memory is n·nnz·8 bytes
instead of n·d·4: at a 100k+ vocabulary and ~10² nonzeros per document
this is ~3 orders of magnitude smaller, which is what lets the text
pipelines run at realistic vocab sizes without densifying.

Shapes are static (nnz_max fixed at construction), so everything jits.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp

from keystone_tpu.parallel import mesh as _mesh


def is_scipy_sparse_rows(items) -> bool:
    """True for a non-empty sequence of scipy sparse row vectors."""
    return len(items) > 0 and all(
        hasattr(r, "tocoo") and hasattr(r, "shape") for r in items[:2]
    )


class PaddedSparseRows:
    """(n, nnz_max) int32 indices + float32 values + feature count.

    ``indices``/``values`` live on device, row-sharded over the mesh
    'data' axis like any Dataset array; rows past ``n`` and entries past
    a row's true nnz are value-0 padding.
    """

    def __init__(self, indices, values, num_features: int, n: Optional[int] = None,
                 shard: bool = True):
        self.n = int(np.shape(indices)[0] if n is None else n)
        self.num_features = int(num_features)
        if shard:
            self.indices = _mesh.shard_batch(np.asarray(indices, np.int32))
            self.values = _mesh.shard_batch(np.asarray(values, np.float32))
        else:
            self.indices = jnp.asarray(indices, jnp.int32)
            self.values = jnp.asarray(values, jnp.float32)

    @property
    def nnz_max(self) -> int:
        return int(self.indices.shape[1])

    @property
    def shape(self):
        return (self.n, self.num_features)

    @property
    def nbytes(self) -> int:
        return int(self.indices.size * 4 + self.values.size * 4)

    @staticmethod
    def from_scipy_rows(
        rows: Sequence, num_features: Optional[int] = None
    ) -> "PaddedSparseRows":
        """Build from scipy sparse row vectors (what ``Sparsify`` emits)."""
        coos = [r.tocoo() for r in rows]
        d = int(num_features if num_features is not None else coos[0].shape[-1])
        widths = {int(c.shape[-1]) for c in coos}
        if widths - {d}:
            # JAX's gather clamps out-of-range indices, so a
            # featurizer/weights width mismatch would silently mis-score;
            # fail loudly like the dense path's shape error instead.
            raise ValueError(
                f"sparse rows have width(s) {sorted(widths)} but "
                f"num_features={d}"
            )
        nnz_max = max(1, max((c.nnz for c in coos), default=1))
        n = len(coos)
        idx = np.zeros((n, nnz_max), np.int32)
        val = np.zeros((n, nnz_max), np.float32)
        for i, c in enumerate(coos):
            idx[i, : c.nnz] = c.col
            val[i, : c.nnz] = c.data
        return PaddedSparseRows(idx, val, d, n=n)

    @staticmethod
    def from_dense(x, threshold: float = 0.0) -> "PaddedSparseRows":
        x = np.asarray(x)
        mask = np.abs(x) > threshold
        nnz_max = max(1, int(mask.sum(axis=1).max()))
        n, d = x.shape
        idx = np.zeros((n, nnz_max), np.int32)
        val = np.zeros((n, nnz_max), np.float32)
        for i in range(n):
            cols = np.nonzero(mask[i])[0]
            idx[i, : cols.size] = cols
            val[i, : cols.size] = x[i, cols]
        return PaddedSparseRows(idx, val, d, n=n)

    def toarray(self) -> np.ndarray:
        """Dense (n, d) host copy (tests / small data only)."""
        idx = np.asarray(self.indices)[: self.n]
        val = np.asarray(self.values)[: self.n]
        out = np.zeros((self.n, self.num_features), np.float32)
        for i in range(self.n):
            np.add.at(out[i], idx[i], val[i])
        return out

    def matmul(self, w, intercept=None, mode: Optional[str] = None):
        """Gather-based ``X @ w`` without densifying: (n_rows, k).

        ``mode=None`` resolves the apply precision policy (this is the
        SCORING path — LinearMapper / logistic inference); solver
        callers contract through :func:`sparse_matmul` directly, whose
        default stays inert f32."""
        from keystone_tpu.utils import precision

        if mode is None:
            mode = precision.apply_mode()
        out = sparse_matmul(self.indices, self.values, jnp.asarray(w), mode=mode)
        if intercept is not None:
            out = out + intercept
        return out


# Row-chunked kernels: the forward gather and the gradient scatter both
# flow through a (rows, nnz, k) contribution tensor; at TIMIT-like k=147
# and 10²–10³ nnz that is GBs if materialized whole (VERDICT r2 item 4).
# Chunking the row axis through lax.scan bounds the live intermediate at
# _CHUNK_BUDGET bytes regardless of (rows, nnz, k); XLA hoists the
# loop-invariant pad/reshape of the COO arrays out of optimizer loops.
_CHUNK_BUDGET = 64 << 20  # ≈100 MB working-set sweet spot, minus headroom


def _auto_chunk(rows: int, nnz: int, k: int) -> int:
    per_row = max(1, nnz * max(k, 1)) * 4
    c = max(128, _CHUNK_BUDGET // per_row)
    return 1 << int(np.floor(np.log2(c)))  # pow2 keeps compiled shapes few


def _chunk_coo(indices, values, chunk: int):
    rows = indices.shape[0]
    nc = -(-rows // chunk)
    pad = nc * chunk - rows
    idx = jnp.pad(indices, ((0, pad), (0, 0))).reshape(nc, chunk, -1)
    val = jnp.pad(values, ((0, pad), (0, 0))).reshape(nc, chunk, -1)
    return idx, val


def sparse_matmul(indices, values, w, mode: str = "f32"):
    """(rows, nnz) COO × (d, k) → (rows, k): gather rows of w, weight, sum.

    Padding entries (value 0) contribute nothing regardless of index.
    Large inputs are row-chunked so the (chunk, nnz, k) gather stays
    within the working-set budget.

    ``mode`` is the apply precision policy (utils/precision.py): the
    default 'f32' is INERT — solver callers (logistic / L-BFGS
    gradients) rely on that; scoring paths (PaddedSparseRows.matmul)
    pass the resolved policy, under which the per-row contraction runs
    with bf16 values/gathered weights and f32 accumulation."""
    from jax import lax

    from keystone_tpu.utils import precision

    indices = jnp.asarray(indices)
    values = jnp.asarray(values)
    w = jnp.asarray(w)
    rows, nnz = indices.shape
    k = w.shape[-1]
    chunk = _auto_chunk(rows, nnz, k)
    if rows <= chunk:
        wg = w[indices]  # (rows, nnz, k)
        return precision.apply_einsum("rn,rnk->rk", values, wg, mode=mode)
    idx, val = _chunk_coo(indices, values, chunk)

    def step(_, iv):
        i, v = iv
        out = precision.apply_einsum("rn,rnk->rk", v, w[i], mode=mode)
        return None, out

    _, out = lax.scan(step, None, (idx, val))
    return out.reshape(-1, k)[:rows]


class BucketedSparseRows:
    """Rows grouped into nnz buckets, each padded only to ITS cap.

    The global-``nnz_max`` cliff (VERDICT r2 item 4): one dense-ish row
    in :class:`PaddedSparseRows` inflates every row's padding to the
    global max.  Here rows are permuted so similar-nnz rows share a
    bucket with a power-of-two cap; total memory is ≤2× Σ nnz when every
    natural cap keeps its own bucket, and the ``max_buckets`` merge picks
    whichever adjacent-cap merge adds the least padding.  ``perm[i]`` is
    the ORIGINAL index of sorted row i; the
    label matrix must be permuted the same way before a bucketed fit,
    and bucket scores scatter back through ``perm`` (least-squares /
    logistic losses are row-permutation invariant, so training on the
    permuted order is exact, not approximate).
    """

    def __init__(self, buckets, perm, num_features: int, n: int):
        self.buckets = list(buckets)  # List[PaddedSparseRows]
        self.perm = np.asarray(perm, np.int64)
        self.num_features = int(num_features)
        self.n = int(n)

    @property
    def shape(self):
        return (self.n, self.num_features)

    @property
    def nbytes(self) -> int:
        return sum(b.nbytes for b in self.buckets)

    @staticmethod
    def from_scipy_rows(
        rows: Sequence,
        num_features: Optional[int] = None,
        max_buckets: int = 6,
    ) -> "BucketedSparseRows":
        coos = [r.tocoo() for r in rows]
        d = int(num_features if num_features is not None else coos[0].shape[-1])
        widths = {int(c.shape[-1]) for c in coos}
        if widths - {d}:
            raise ValueError(
                f"sparse rows have width(s) {sorted(widths)} but "
                f"num_features={d}"
            )
        n = len(coos)
        nnz = np.asarray([max(c.nnz, 1) for c in coos])
        caps = 1 << np.ceil(np.log2(nnz)).astype(np.int64)
        # merge caps until ≤ max_buckets distinct, always merging the
        # ADJACENT pair that adds the least total padding (merging the
        # smallest cap blindly into the next PRESENT cap could jump many
        # octaves and re-create the global-padding cliff for the bulk of
        # the rows)
        uniq = sorted(set(caps.tolist()))
        while len(uniq) > max_buckets:
            costs = [
                int((caps == uniq[i]).sum()) * (uniq[i + 1] - uniq[i])
                for i in range(len(uniq) - 1)
            ]
            i = int(np.argmin(costs))
            caps[caps == uniq[i]] = uniq[i + 1]
            uniq.pop(i)
        # stable argsort by cap groups rows bucket-by-bucket; perm[i] is
        # the original index of the i-th row in concatenated-bucket order
        perm = np.argsort(caps, kind="stable")
        buckets = []
        for cap in sorted(set(caps.tolist())):
            sel = perm[caps[perm] == cap]
            m = len(sel)
            idx = np.zeros((m, cap), np.int32)
            val = np.zeros((m, cap), np.float32)
            for i, ri in enumerate(sel):
                c = coos[ri]
                idx[i, : c.nnz] = c.col
                val[i, : c.nnz] = c.data
            buckets.append(PaddedSparseRows(idx, val, d, n=m))
        return BucketedSparseRows(buckets, perm, d, n)

    def matmul(self, w, intercept=None) -> np.ndarray:
        """``X @ w`` (+ intercept) with per-bucket gathers; returns a
        HOST (n, k) array in the ORIGINAL row order."""
        w = jnp.asarray(w)
        out = np.empty((self.n, int(w.shape[-1])), np.float32)
        start = 0
        for b in self.buckets:
            scores = np.asarray(b.matmul(w))[: b.n]
            out[self.perm[start : start + b.n]] = scores
            start += b.n
        if intercept is not None:
            out = out + np.asarray(intercept)
        return out


def host_onehot(y, k: int) -> np.ndarray:
    """(n,) int class ids or (n, K) indicator matrix → float32 one-hot,
    built ON HOST: the sparse fit paths permute labels in numpy anyway,
    so a device one-hot would cross the host↔device link twice for
    nothing (~0.6 GB at n=10⁶, K=147 over this backend's slow tunnel)."""
    y = np.asarray(y)
    if y.ndim == 1:
        out = np.zeros((y.shape[0], k), np.float32)
        out[np.arange(y.shape[0]), y.astype(np.int64)] = 1.0
        return out
    return (y > 0).astype(np.float32)


def bucketize_with_labels(sp, y, n: Optional[int] = None, intercept: bool = False):
    """Per-bucket (indices, values, labels, mask) tuples for bucketed
    solvers.

    ``sp``: PaddedSparseRows or BucketedSparseRows; ``y``: (≥n, k) host
    or device label/target matrix aligned with the ORIGINAL row order.
    Rows whose original index ≥ ``n`` are treated as padding (matrix
    built over a padded Dataset) — their values and labels are zeroed
    and they are excluded from the masks.  Values are also zeroed on
    bucket shard-padding rows; labels are permuted into bucket order and
    shard-padded per bucket; with ``intercept`` each row gains a
    constant feature at index ``sp.num_features`` (value 1 on valid rows
    only).  Returns ``(bidx, bvals, by, n, d_aug, brow_ok)`` where
    ``brow_ok`` holds per-bucket (rows_b,) float masks of VALID rows —
    traced solver inputs (never static: counts changing within a shard
    multiple must not trigger recompiles).
    """
    from keystone_tpu.parallel import mesh as _mesh_mod

    if isinstance(sp, PaddedSparseRows):
        sp = BucketedSparseRows([sp], np.arange(sp.n), sp.num_features, sp.n)
    n = sp.n if n is None else int(n)
    y = np.asarray(y, np.float32)
    if y.shape[0] < n:
        raise ValueError(
            f"labels have {y.shape[0]} rows but the sparse matrix has "
            f"{n} true rows"
        )
    # rows past n (padding of the source Dataset) get zero labels
    y_ext = np.zeros((sp.n, y.shape[1]), np.float32)
    y_ext[:n] = y[:n]
    d = sp.num_features
    bidx, bvals, by, brow_ok = [], [], [], []
    start = 0
    for b in sp.buckets:
        sel = sp.perm[start : start + b.n]
        start += b.n
        rows_b = int(b.indices.shape[0])  # mesh-padded row count
        row_ok = np.zeros((rows_b,), np.float32)
        row_ok[: b.n] = (sel < n).astype(np.float32)
        yb = np.zeros((rows_b, y.shape[1]), np.float32)
        yb[: b.n] = y_ext[sel]
        row_ok_dev = _mesh_mod.shard_batch(row_ok)
        idx, vals = b.indices, b.values * row_ok_dev[:, None]
        if intercept:
            idx = jnp.concatenate(
                [idx, jnp.full((rows_b, 1), d, jnp.int32)], axis=1
            )
            vals = jnp.concatenate([vals, row_ok_dev[:, None]], axis=1)
        bidx.append(idx)
        bvals.append(vals)
        by.append(_mesh_mod.shard_batch(yb))
        brow_ok.append(row_ok_dev)
    return (
        tuple(bidx),
        tuple(bvals),
        tuple(by),
        n,
        d + 1 if intercept else d,
        tuple(brow_ok),
    )


def score_sparse_dataset(ds, weights, intercept=None):
    """Score a host Dataset of scipy sparse rows against dense weights
    by gathering weight rows (shared by LinearMapper and the logistic
    model — n×d never densifies).  Rows are nnz-bucketed so one heavy
    row doesn't inflate the whole batch's padding."""
    sp = BucketedSparseRows.from_scipy_rows(
        ds.items, num_features=weights.shape[0]
    )
    return ds.with_array(jnp.asarray(sp.matmul(weights, intercept)))


def sparse_grad(indices, values, r, d):
    """``Xᵀ r`` by scatter-add: (d, k) from (rows, nnz) COO and (rows, k).

    Duplicate indices accumulate (jnp ``.at[].add``); padding entries add
    zero.  Large inputs are row-chunked: the (chunk, nnz, k) contribution
    tensor is the only live intermediate, accumulated into the (d, k)
    output across scan steps."""
    from jax import lax

    indices = jnp.asarray(indices)
    values = jnp.asarray(values)
    r = jnp.asarray(r)
    rows, nnz = indices.shape
    k = r.shape[1]
    chunk = _auto_chunk(rows, nnz, k)
    if rows <= chunk:
        contrib = values[..., None] * r[:, None, :]  # (rows, nnz, k)
        return (
            jnp.zeros((d, k), jnp.float32)
            .at[indices.reshape(-1)]
            .add(contrib.reshape(-1, k))
        )
    idx, val = _chunk_coo(indices, values, chunk)
    nc = idx.shape[0]
    pad = nc * chunk - rows
    r3 = jnp.pad(r, ((0, pad), (0, 0))).reshape(nc, chunk, k)

    def step(acc, ivr):
        i, v, rc = ivr
        contrib = v[..., None] * rc[:, None, :]  # (chunk, nnz, k)
        return acc.at[i.reshape(-1)].add(contrib.reshape(-1, k)), None

    acc, _ = lax.scan(step, jnp.zeros((d, k), jnp.float32), (idx, val, r3))
    return acc
