"""Text / NLP nodes.

Reference: src/main/scala/nodes/nlp/ (Tokenizer, LowerCase, Trim,
NGramsFeaturizer, NGramsCounts, StupidBackoff, NGramIndexer) and
nodes/misc/ (TermFrequency, CommonSparseFeatures).

Strings are host objects; these nodes run on the host side of the input
pipeline and hand dense arrays to the device at the CommonSparseFeatures /
HashingTF boundary (TPUs want dense MXU tiles — Densify is built in here
rather than a separate physical cast).
"""

from __future__ import annotations

import re
from collections import Counter, defaultdict
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import Estimator
from keystone_tpu.workflow.transformer import Transformer


class Trimmer(Transformer):
    """Strip leading/trailing whitespace (nodes/nlp/Trim)."""

    is_host = True
    parallel_host = False  # one str method per item: IPC > work

    def params(self):
        return ()

    def apply_one(self, s: str) -> str:
        return s.strip()


class LowerCase(Transformer):
    is_host = True
    parallel_host = False  # one str method per item: IPC > work

    def params(self):
        return ()

    def apply_one(self, s: str) -> str:
        return s.lower()


class Tokenizer(Transformer):
    """Regex tokenization (nodes/nlp/Tokenizer.scala; default splits on
    non-word chars like the reference's "[\\s]+"-style patterns)."""

    is_host = True

    def __init__(self, pattern: str = r"[^a-zA-Z0-9']+"):
        self.pattern = pattern
        self._re = re.compile(pattern)

    def params(self):
        return (self.pattern,)

    def apply_one(self, s: str) -> List[str]:
        return [t for t in self._re.split(s) if t]


class NGramsFeaturizer(Transformer):
    """tokens → all n-grams for n in ``orders``
    (nodes/nlp/NGramsFeaturizer.scala)."""

    is_host = True

    def __init__(self, orders: Sequence[int] = (1, 2)):
        self.orders = tuple(int(n) for n in orders)

    def params(self):
        return (self.orders,)

    def apply_one(self, tokens: List[str]) -> List[Tuple[str, ...]]:
        out: List[Tuple[str, ...]] = []
        for n in self.orders:
            if n == 1:
                # fast path: ~3x the sliced-window loop (measured; this
                # map is the host text stage's per-doc hot loop)
                out.extend((t,) for t in tokens)
            else:
                out.extend(zip(*(tokens[i:] for i in range(n))))
        return out


def log_tf(v: float) -> float:
    """log(1 + count) — the reference pipelines' log-tf weighting.
    Module-level (not a lambda) so fitted pipelines embedding
    ``TermFrequency(log_tf)`` stay picklable (--model-path)."""
    import math

    return math.log(v + 1.0)


class TermFrequency(Transformer):
    """n-gram list → {ngram: weighted count}
    (nodes/misc/TermFrequency.scala; ``fn`` e.g. log1p for log-tf)."""

    is_host = True

    def __init__(self, fn: Optional[Callable[[float], float]] = None):
        self.fn = fn

    def params(self):
        return None if self.fn is not None else ("identity",)

    def apply_one(self, ngrams: List) -> Dict:
        counts = Counter(ngrams)
        if self.fn is None:
            return dict(counts)
        return {k: self.fn(float(v)) for k, v in counts.items()}


def _native_chain(ds):
    """(cfg, base_dataset) when ``ds`` carries host-chain provenance the
    native text path supports (ops/nlp_native), else None — the one
    gating prologue for every native consumer (df fit, vocab featurize,
    hashing featurize; stream and in-memory)."""
    from keystone_tpu.ops import nlp_native

    chain = getattr(ds, "_host_chain", None)
    if chain is None or not nlp_native.available():
        return None
    cfg = nlp_native.chain_config(chain[1])
    if cfg is None:
        return None
    return cfg, chain[0]


def _base_docs(base) -> Optional[list]:
    """Raw doc list of an in-memory host base dataset, or None.

    Checks EVERY item, not just ``docs[0]``: a heterogeneous host list
    (one stray non-str doc) must fall back to the Python path like the
    stream variants do, instead of dying in native packing with an
    ``AttributeError`` on ``.encode``."""
    if not base.is_host:
        return None
    docs = base.items
    if docs and not all(isinstance(d, str) for d in docs):
        return None
    return docs


class CommonSparseFeaturesModel(Transformer):
    """doc term-dict → row over the learned vocabulary.

    ``sparse_output`` emits scipy CSR rows (the reference's
    SparseVector) instead of dense — at 10⁵-feature vocabularies dense
    rows multiply memory by the zero fraction, and the sparse solvers /
    LinearMapper's gather scoring consume CSR directly."""

    is_host = True
    fusable = False
    # Class-level default: models pickled before sparse_output existed
    # unpickle to the dense rows they were fitted with.
    sparse_output = False

    def __init__(self, vocab: Dict, num_features: int, sparse_output: bool = False):
        self.vocab = vocab
        self.num_features = int(num_features)
        self.sparse_output = bool(sparse_output)

    def apply_one(self, term_dict: Dict):
        if self.sparse_output:
            cols, vals = [], []
            for term, val in term_dict.items():
                idx = self.vocab.get(term)
                if idx is not None:
                    cols.append(idx)
                    vals.append(val)
            return _csr_row(cols, vals, self.num_features)
        row = np.zeros((self.num_features,), np.float32)
        for term, val in term_dict.items():
            idx = self.vocab.get(term)
            if idx is not None:
                row[idx] = val
        return row

    def apply_dataset(self, ds: Dataset) -> Dataset:
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(ds, StreamDataset) and ds.is_host:
            native = self._apply_native_stream(ds)
            if native is not None:
                return native
            return _featurize_host_stream(self, ds)
        if ds.is_host:
            native = self._apply_native_items(ds)
            if native is not None:
                return native
        from keystone_tpu.utils.hostmap import host_map

        if self.sparse_output:
            return ds.with_items(host_map(self.apply_one, ds.items))
        rows = np.stack(host_map(self.apply_one, ds.items))
        return Dataset(rows)

    def __getstate__(self):
        # the packed-vocab blob is a multi-MB derived cache (native fast
        # path); saved models must not duplicate the vocab dict with it
        state = self.__dict__.copy()
        state.pop("_native_vocab", None)
        return state

    def _apply_native_stream(self, ds):
        """Fused C++ featurize straight from the RAW doc stream when the
        host-chain provenance matches (ops/nlp_native); None = Python
        path.  Mirrors _featurize_host_stream's payload contract: sparse
        → lazy host stream of CSR rows, dense → device stream."""
        from keystone_tpu.ops import nlp_native

        nc = _native_chain(ds)
        if nc is None:
            return None
        cfg, base = nc
        if not hasattr(self, "_native_vocab"):
            self._native_vocab = nlp_native.pack_vocab(self.vocab)
        blob, offs, vsize = self._native_vocab
        nf, sparse = self.num_features, self.sparse_output

        def fn(batch, _mask):
            if batch and not isinstance(batch[0], str):
                raise TypeError("native text path expects raw doc strings")
            return nlp_native.featurize_docs(
                batch, blob, offs, vsize, cfg, nf, sparse
            )

        return base.map_batches(fn, host=True if sparse else False)

    def _apply_native_items(self, ds):
        """In-memory twin of _apply_native_stream (the non-stream apps):
        featurize the base dataset's raw docs in one native call."""
        from keystone_tpu.ops import nlp_native

        nc = _native_chain(ds)
        if nc is None:
            return None
        cfg, base = nc
        docs = _base_docs(base)
        if docs is None:
            return None
        if not hasattr(self, "_native_vocab"):
            self._native_vocab = nlp_native.pack_vocab(self.vocab)
        blob, offs, vsize = self._native_vocab
        rows = nlp_native.featurize_docs(
            docs, blob, offs, vsize, cfg, self.num_features, self.sparse_output
        )
        if self.sparse_output:
            return ds.with_items(rows)
        return Dataset(rows)


def _featurize_host_stream(model, ds):
    """Shared host-stream featurization for the sparse-capable text
    featurizers: sparse output stays a lazy HOST stream of CSR rows
    (small; downstream fits collect them — Transformer's generic
    host-item mapping), dense output becomes a DEVICE stream so array
    consumers keep working."""
    from keystone_tpu.workflow.transformer import Transformer

    if model.sparse_output:
        return Transformer.apply_dataset(model, ds)
    from keystone_tpu.utils.hostmap import host_map

    return ds.map_batches(
        lambda batch, _m: np.stack(host_map(model.apply_one, batch)),
        host=False,
    )


class CommonSparseFeatures(Estimator):
    """Vocabulary = top-k terms by document frequency
    (nodes/misc/CommonSparseFeatures.scala).  The fitted transformer
    emits dense rows by default; ``sparse_output=True`` keeps CSR rows
    so the optimizer's physical choice can pick the sparse solvers."""

    def __init__(self, num_features: int, sparse_output: bool = False):
        self.num_features = int(num_features)
        self.sparse_output = bool(sparse_output)

    def params(self):
        return (self.num_features, self.sparse_output)

    def fit_dataset(self, data: Dataset) -> CommonSparseFeaturesModel:
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(data, StreamDataset) and data.is_host:
            native = self._fit_native_stream(data)
            if native is not None:
                return native
            # streaming document-frequency pass: one sweep, Counter-sized
            # state — the raw corpus never materializes (fit_arrays
            # consumes any iterable, so feed it the stream lazily)
            return self.fit_arrays(
                d for batch in data.batches() for d in batch
            )
        if data.is_host:
            native = self._fit_native_items(data)
            if native is not None:
                return native
        return self.fit_arrays(data.items)

    def _fit_native_stream(self, data) -> Optional[CommonSparseFeaturesModel]:
        """Native df sweep over the RAW doc stream when this stream's
        host-chain provenance matches the fused C++ path (ops/nlp_native
        — skips every intermediate Python token list / term dict).
        Returns None to use the Python path.  Tie-break divergence is
        documented in nlp_native's module docstring."""
        from keystone_tpu.ops import nlp_native

        nc = _native_chain(data)
        if nc is None:
            return None
        cfg, base = nc
        acc = nlp_native.DfAccumulator(cfg)
        try:
            for batch in base.batches():
                if batch and not isinstance(batch[0], str):
                    return None  # base stream is not raw text
                acc.update(batch)
            top = acc.topn(self.num_features)
        finally:
            acc.close()
        vocab = {t: i for i, (t, _) in enumerate(top)}
        return CommonSparseFeaturesModel(
            vocab, self.num_features, self.sparse_output
        )

    def _fit_native_items(self, data) -> Optional[CommonSparseFeaturesModel]:
        """In-memory twin of _fit_native_stream (the non-stream apps)."""
        from keystone_tpu.ops import nlp_native

        nc = _native_chain(data)
        if nc is None:
            return None
        cfg, base = nc
        docs = _base_docs(base)
        if docs is None:
            return None
        acc = nlp_native.DfAccumulator(cfg)
        try:
            for i in range(0, len(docs), 8192):
                acc.update(docs[i : i + 8192])
            top = acc.topn(self.num_features)
        finally:
            acc.close()
        vocab = {t: i for i, (t, _) in enumerate(top)}
        return CommonSparseFeaturesModel(
            vocab, self.num_features, self.sparse_output
        )

    def fit_arrays(self, docs: Iterable[Dict]) -> CommonSparseFeaturesModel:
        df: Counter = Counter()
        for d in docs:
            df.update(set(d.keys()))
        return self._from_df(df)

    def _from_df(self, df: Counter) -> CommonSparseFeaturesModel:
        top = [t for t, _ in df.most_common(self.num_features)]
        vocab = {t: i for i, t in enumerate(top)}
        return CommonSparseFeaturesModel(
            vocab, self.num_features, self.sparse_output
        )


def _csr_row(cols, vals, num_features: int):
    """One CSR row via the direct (data, indices, indptr) constructor —
    2.4x the COO-style constructor (measured; scipy's COO path re-sorts
    and deduplicates, which vocab/accumulator rows never need).  The
    direct constructor skips scipy's bounds validation, so it is
    reinstated here: a vocab/num_features mismatch must raise, never
    silently zero the features."""
    import scipy.sparse as sp

    idx = np.asarray(cols, np.int32)
    if idx.size and (
        int(idx.max()) >= num_features or int(idx.min()) < 0
    ):
        raise ValueError(
            f"column index out of bounds for {num_features} features "
            f"(got {int(idx.max())}/{int(idx.min())})"
        )
    return sp.csr_matrix(
        (
            np.asarray(vals, np.float32),
            idx,
            np.array([0, len(cols)], np.int32),
        ),
        shape=(1, num_features),
        copy=False,
    )


#: term → hash memo.  The corpus term distribution is zipfian, so a plain
#: dict (5.5x blake2b re-hashing, measured) almost always hits; the cap
#: bounds memory on adversarial vocabularies — once full, new terms hash
#: uncached (the hot head is already resident).  2^17 (~25 MB of tuple
#: keys at typical n-gram sizes, not 2^20's ~200 MB): the memo is
#: per-process, and host_map worker processes each hold their own copy.
_TERM_HASH_MEMO: Dict = {}
_TERM_HASH_MEMO_CAP = 1 << 17


def stable_term_hash(term) -> int:
    """Process-independent term hash.  Python's built-in ``hash(str)`` is
    salted per process (PYTHONHASHSEED), which silently scrambles every
    HashingTF feature when a fitted model crosses a process boundary
    (--model-path scoring runs were reduced to chance accuracy).  blake2b
    of the term's repr is stable everywhere."""
    h = _TERM_HASH_MEMO.get(term)
    if h is None:
        import hashlib

        digest = hashlib.blake2b(repr(term).encode(), digest_size=8).digest()
        h = int.from_bytes(digest, "little")
        if len(_TERM_HASH_MEMO) < _TERM_HASH_MEMO_CAP:
            _TERM_HASH_MEMO[term] = h
    return h


class HashingTF(Transformer):
    """Feature hashing to a fixed dimension — the scale-friendly
    alternative to CommonSparseFeatures (no fitted vocabulary; same role
    as Spark's HashingTF, which the reference text pipelines predate).
    Hashing is process-independent (see stable_term_hash), so fitted
    models score identically after save/load into another process."""

    is_host = True
    fusable = False
    # Class-level default for pre-sparse_output pickles (see
    # CommonSparseFeaturesModel above).
    sparse_output = False

    def __init__(self, num_features: int = 2**16, sparse_output: bool = False):
        self.num_features = int(num_features)
        self.sparse_output = bool(sparse_output)

    def params(self):
        return (self.num_features, self.sparse_output)

    def apply_one(self, term_dict: Dict):
        if self.sparse_output:
            acc: Dict[int, float] = defaultdict(float)
            for term, val in term_dict.items():
                acc[stable_term_hash(term) % self.num_features] += float(val)
            return _csr_row(list(acc.keys()), list(acc.values()), self.num_features)
        row = np.zeros((self.num_features,), np.float32)
        for term, val in term_dict.items():
            row[stable_term_hash(term) % self.num_features] += val
        return row

    def apply_dataset(self, ds: Dataset) -> Dataset:
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(ds, StreamDataset) and ds.is_host:
            native = self._apply_native_stream(ds)
            if native is not None:
                return native
            return _featurize_host_stream(self, ds)
        if ds.is_host:
            native = self._apply_native_items(ds)
            if native is not None:
                return native
        from keystone_tpu.utils.hostmap import host_map

        if self.sparse_output:
            return ds.with_items(host_map(self.apply_one, ds.items))
        rows = np.stack(host_map(self.apply_one, ds.items))
        return Dataset(rows)

    def _apply_native_stream(self, ds):
        """Fused C++ hash-featurize from the RAW doc stream (native
        blake2b twin of stable_term_hash); None = Python path.  Same
        payload contract as CommonSparseFeaturesModel's native apply."""
        from keystone_tpu.ops import nlp_native

        if self.num_features > (1 << 31) - 1:
            return None  # native columns are int32; Python handles wider
        nc = _native_chain(ds)
        if nc is None:
            return None
        cfg, base = nc
        nf, sparse = self.num_features, self.sparse_output

        def fn(batch, _mask):
            if batch and not isinstance(batch[0], str):
                raise TypeError("native text path expects raw doc strings")
            return nlp_native.hashtf_docs(batch, cfg, nf, sparse)

        return base.map_batches(fn, host=True if sparse else False)

    def _apply_native_items(self, ds):
        """In-memory twin of _apply_native_stream."""
        from keystone_tpu.ops import nlp_native

        if self.num_features > (1 << 31) - 1:
            return None
        nc = _native_chain(ds)
        if nc is None:
            return None
        cfg, base = nc
        docs = _base_docs(base)
        if docs is None:
            return None
        rows = nlp_native.hashtf_docs(
            docs, cfg, self.num_features, self.sparse_output
        )
        if self.sparse_output:
            return ds.with_items(rows)
        return Dataset(rows)


class NGramsCounts(Transformer):
    """Corpus-level n-gram count aggregation
    (nodes/nlp/NGramsCounts.scala): dataset of n-gram lists → one Counter.
    A host-side reduction (the reference's reduceByKey)."""

    is_host = True
    fusable = False

    def params(self):
        return ()

    def apply_dataset(self, ds: Dataset) -> Dataset:
        total: Counter = Counter()
        for ngrams in ds.items:
            total.update(ngrams)
        return ds.with_items([total])

    def apply_one(self, ngrams):
        return Counter(ngrams)


class NGramIndexer:
    """Packs n-grams of word ids into single int64 keys
    (nodes/nlp/NGramIndexer.scala — the reference packs up to 3 word ids
    into a long for compact distributed count tables).

    ``bits`` per word id (default 21 → 3-grams fit one int64, vocab ≤ 2M).
    """

    def __init__(self, bits: int = 21):
        self.bits = int(bits)
        self._vocab: Dict[str, int] = {}
        self._reverse: Dict[int, str] = {}

    def word_id(self, word: str) -> int:
        idx = self._vocab.get(word)
        if idx is None:
            idx = len(self._vocab) + 1  # 0 reserved for empty slots
            if idx >= (1 << self.bits):
                raise OverflowError(f"vocabulary exceeds 2^{self.bits} words")
            self._vocab[word] = idx
            self._reverse[idx] = word
        return idx

    def pack(self, ngram: Sequence[str]) -> int:
        if len(ngram) * self.bits > 63:
            raise OverflowError(f"{len(ngram)}-gram at {self.bits} bits/word")
        key = 0
        for w in ngram:
            key = (key << self.bits) | self.word_id(w)
        return key

    def unpack(self, key: int, order: int) -> tuple:
        words = []
        for _ in range(order):
            words.append(self._reverse.get(key & ((1 << self.bits) - 1), "<unk>"))
            key >>= self.bits
        return tuple(reversed(words))


class StupidBackoffLM(Transformer):
    """Stupid-backoff n-gram scorer (nodes/nlp/StupidBackoff.scala):

        S(w_i | w_{i−n+1..i−1}) = count(ngram)/count(context) if seen,
        else α · S(w_i | shorter context), bottoming out at unigram
        frequency; α = 0.4 (Brants et al. 2007).
    """

    is_host = True
    fusable = False

    def __init__(self, counts: Dict[Tuple[str, ...], int], alpha: float = 0.4):
        self.counts = dict(counts)
        self.alpha = float(alpha)
        self.total_unigrams = sum(
            v for k, v in self.counts.items() if len(k) == 1
        )
        # context counts: sum over last word
        self._context: Dict[Tuple[str, ...], int] = defaultdict(int)
        for k, v in self.counts.items():
            if len(k) >= 2:
                self._context[k[:-1]] += v

    def params(self):
        return None

    def score(self, ngram: Tuple[str, ...]) -> float:
        ngram = tuple(ngram)
        if len(ngram) == 1:
            if self.total_unigrams == 0:
                return 0.0
            return self.counts.get(ngram, 0) / self.total_unigrams
        c = self.counts.get(ngram, 0)
        ctx = self._context.get(ngram[:-1], 0)
        if c > 0 and ctx > 0:
            return c / ctx
        return self.alpha * self.score(ngram[1:])

    def apply_one(self, ngram):
        return self.score(tuple(ngram))
