"""Native host-text fast path (VERDICT r4 item 6; SURVEY §2.10 text
pipelines, §7(f)).

The per-doc Python chain trim→lower→tokenize→ngram→tf→{vocab CSR | df}
measured 1.5–3.4k docs/s streaming on this 1-core host (BASELINE.md
"Host text stage") — the reference's answer to the same problem is
native code behind JNI.  Here the whole fused chain runs in
``native/keystone_native.cpp`` (``ks_text_*``): C++ tokenization and
hashing with the GIL released (ctypes) and a thread pool over docs.
The Python implementations remain both the fallback (no compiler,
non-default tokenizer patterns, custom tf functions) and the parity
reference (tests/test_nlp_native.py).

Integration: host StreamDatasets carry provenance (``_host_chain`` —
the base raw-doc stream plus the host transformers applied so far, set
by Transformer.apply_dataset).  ``CommonSparseFeatures.fit_dataset``
and ``CommonSparseFeaturesModel.apply_dataset`` recognize a supported
chain and hand the RAW doc batches to C++, skipping every intermediate
Python object (token lists, tuple n-grams, term dicts).

Known, documented divergences: (1) Unicode case edge cases — a handful
of non-ASCII characters lowercase INTO ASCII in Python (U+0130 'İ',
U+212A Kelvin); the native tokenizer treats their original bytes as
separators, so such docs tokenize differently (ordinary UTF-8 text is
bit-identical; multilingual corpora needing full Unicode case mapping
should use the Python path).  (2) df top-N TIE order.  Python's
``Counter.most_common`` breaks df ties by first-insertion order, which
inherits per-process-salted ``set`` iteration — it is not stable
across processes even Python-vs-Python.  The native path is
deterministic: (-df, first-doc-index, term).  Terms with distinct dfs
are identical.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple

import numpy as np

#: joined-key separator bridging C++ term strings <-> Python token tuples
SEP = "\x1f"

_DEFAULT_TOKEN_PATTERN = r"[^a-zA-Z0-9']+"


def _lib():
    from keystone_tpu.native import get_lib

    lib = get_lib()
    if lib is None or not hasattr(lib, "ks_text_featurize") or not hasattr(
        lib, "ks_text_hashtf"
    ):
        # both entry points ship in the same build (ABI v4); a partial
        # binary means a stale .so — fall back to Python entirely
        return None
    return lib


def available() -> bool:
    return _lib() is not None


def _pack_docs(docs: Sequence[str]) -> Tuple[bytes, np.ndarray]:
    enc = [d.encode("utf-8", "surrogatepass") for d in docs]
    offs = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(b) for b in enc], out=offs[1:])
    return b"".join(enc), offs


def chain_config(stages) -> Optional[dict]:
    """Parse a host-transformer chain into a native config, or None if
    any stage is outside the supported pattern: [Trimmer?] [LowerCase?]
    Tokenizer(default pattern) NGramsFeaturizer(orders within 1..8)
    TermFrequency(None | log_tf)."""
    from keystone_tpu.ops.nlp import (
        LowerCase,
        NGramsFeaturizer,
        TermFrequency,
        Tokenizer,
        Trimmer,
        log_tf,
    )

    stages = list(stages)
    trim = lower = False
    while stages and isinstance(stages[0], (Trimmer, LowerCase)):
        if isinstance(stages[0], Trimmer):
            trim = True
        else:
            lower = True
        stages.pop(0)
    if len(stages) != 3:
        return None
    tok, ngrams, tf = stages
    if not isinstance(tok, Tokenizer) or tok.pattern != _DEFAULT_TOKEN_PATTERN:
        return None
    if not isinstance(ngrams, NGramsFeaturizer) or not all(
        1 <= n <= 8 for n in ngrams.orders
    ):
        return None
    if len(set(ngrams.orders)) != len(ngrams.orders):
        # duplicate orders (e.g. (1, 1)) collapse in the orders_mask, so
        # the native path would emit each n-gram once where the Python
        # path counts it per duplicate — silently halving tf values.
        # Fall back to the Python path, which honors duplicates.
        return None
    if not isinstance(tf, TermFrequency) or tf.fn not in (None, log_tf):
        return None
    mask = 0
    for n in ngrams.orders:
        mask |= 1 << (n - 1)
    return {
        "orders_mask": mask,
        "log_tf": 1 if tf.fn is log_tf else 0,
        "lower": 1 if lower else 0,
        "trim": 1 if trim else 0,
    }



def _unpack_native_rows(lib, indptr, out_idx, out_val, n, num_features,
                        sparse_output):
    """Copy a ks_text_* CSR result out of native memory and build the
    per-doc payload (scipy CSR rows or a dense (n, F) array) — the one
    place that owns the copy-out/free and row-construction contract."""
    import scipy.sparse as sp

    nnz = int(indptr[-1])
    try:
        idx = np.ctypeslib.as_array(out_idx, shape=(max(nnz, 1),))[:nnz].copy()
        val = np.ctypeslib.as_array(out_val, shape=(max(nnz, 1),))[:nnz].copy()
    finally:
        lib.ks_free(out_idx)
        lib.ks_free(out_val)
    if sparse_output:
        rows: List = []
        for i in range(n):
            lo, hi = int(indptr[i]), int(indptr[i + 1])
            rows.append(
                sp.csr_matrix(
                    (val[lo:hi], idx[lo:hi], np.array([0, hi - lo], np.int32)),
                    shape=(1, num_features),
                    copy=False,
                )
            )
        return rows
    dense = np.zeros((n, num_features), np.float32)
    for i in range(n):
        lo, hi = int(indptr[i]), int(indptr[i + 1])
        dense[i, idx[lo:hi]] = val[lo:hi]
    return dense


def featurize_docs(
    docs: Sequence[str],
    vocab_keys_joined: bytes,
    vocab_offs: np.ndarray,
    vsize: int,
    cfg: dict,
    num_features: int,
    sparse_output: bool,
    threads: int = 0,
):
    """Raw docs -> CSR rows (scipy, one per doc) or a dense (n, F) array
    over a prepared vocabulary (see ``pack_vocab``)."""
    import scipy.sparse as sp

    lib = _lib()
    blob, offs = _pack_docs(docs)
    n = len(docs)
    indptr = np.zeros(n + 1, np.int64)
    out_idx = ctypes.POINTER(ctypes.c_int32)()
    out_val = ctypes.POINTER(ctypes.c_float)()
    rc = lib.ks_text_featurize(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n),
        vocab_keys_joined,
        vocab_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(vsize),
        ctypes.c_uint32(cfg["orders_mask"]),
        cfg["log_tf"],
        cfg["lower"],
        cfg["trim"],
        threads,
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.byref(out_idx),
        ctypes.byref(out_val),
    )
    if rc != 0:
        raise RuntimeError(f"ks_text_featurize failed: {rc}")
    return _unpack_native_rows(
        lib, indptr, out_idx, out_val, n, num_features, sparse_output
    )


def hashtf_docs(
    docs: Sequence[str],
    cfg: dict,
    num_features: int,
    sparse_output: bool,
    threads: int = 0,
):
    """Raw docs -> HashingTF rows: col = blake2b8(repr(term)) %
    num_features (stable_term_hash's exact contract, reimplemented in
    C++ from RFC 7693 — parity pinned incl. apostrophe tokens, whose
    repr double-quotes); colliding terms' tf values accumulate."""
    import scipy.sparse as sp

    lib = _lib()
    blob, offs = _pack_docs(docs)
    n = len(docs)
    indptr = np.zeros(n + 1, np.int64)
    out_idx = ctypes.POINTER(ctypes.c_int32)()
    out_val = ctypes.POINTER(ctypes.c_float)()
    rc = lib.ks_text_hashtf(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n),
        ctypes.c_uint32(cfg["orders_mask"]),
        cfg["log_tf"],
        cfg["lower"],
        cfg["trim"],
        ctypes.c_int64(num_features),
        threads,
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.byref(out_idx),
        ctypes.byref(out_val),
    )
    if rc != 0:
        raise RuntimeError(f"ks_text_hashtf failed: {rc}")
    return _unpack_native_rows(
        lib, indptr, out_idx, out_val, n, num_features, sparse_output
    )


def pack_vocab(vocab: dict) -> Tuple[bytes, np.ndarray, int]:
    """Python {token-tuple: col} vocab -> (joined blob, offsets, size),
    ordered by column id so C++ ids equal Python ids."""
    items = sorted(vocab.items(), key=lambda kv: kv[1])
    enc = [SEP.join(t).encode("utf-8", "surrogatepass") for t, _ in items]
    offs = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(b) for b in enc], out=offs[1:])
    return b"".join(enc), offs, len(enc)


class DfAccumulator:
    """Streaming df sweep: feed raw doc batches, then ``topn`` returns
    [(token-tuple, df)] by (-df, first-doc, term)."""

    def __init__(self, cfg: dict):
        lib = _lib()
        lib.ks_text_df_new.restype = ctypes.c_void_p
        self._lib = lib
        self._h = ctypes.c_void_p(
            lib.ks_text_df_new(
                ctypes.c_uint32(cfg["orders_mask"]), cfg["lower"], cfg["trim"]
            )
        )

    def update(self, docs: Sequence[str]) -> None:
        blob, offs = _pack_docs(docs)
        rc = self._lib.ks_text_df_update(
            self._h,
            blob,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(len(docs)),
        )
        if rc != 0:
            raise RuntimeError(f"ks_text_df_update failed: {rc}")

    def topn(self, n: int) -> List[Tuple[tuple, int]]:
        lib = self._lib
        terms = ctypes.POINTER(ctypes.c_char)()
        offs = ctypes.POINTER(ctypes.c_int64)()
        counts = ctypes.POINTER(ctypes.c_int64)()
        out_n = ctypes.c_int64(0)
        rc = lib.ks_text_df_topn(
            self._h,
            ctypes.c_int64(n),
            ctypes.byref(terms),
            ctypes.byref(offs),
            ctypes.byref(counts),
            ctypes.byref(out_n),
        )
        if rc != 0:
            raise RuntimeError(f"ks_text_df_topn failed: {rc}")
        try:
            m = out_n.value
            off = np.ctypeslib.as_array(offs, shape=(m + 1,))
            blob = ctypes.string_at(terms, int(off[m])) if m else b""
            cnt = np.ctypeslib.as_array(counts, shape=(max(m, 1),))
            out = []
            for i in range(m):
                key = blob[int(off[i]) : int(off[i + 1])].decode(
                    "utf-8", "surrogatepass"
                )
                out.append((tuple(key.split(SEP)), int(cnt[i])))
            return out
        finally:
            lib.ks_free(terms)
            lib.ks_free(offs)
            lib.ks_free(counts)

    def close(self) -> None:
        if self._h:
            self._lib.ks_text_df_free(self._h)
            self._h = None

    def __del__(self):  # best-effort; close() is the real contract
        try:
            self.close()
        except Exception:
            pass
