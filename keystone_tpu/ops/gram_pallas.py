"""Pallas TPU kernel for Gaussian gram blocks.

The kernel tier's hot contraction is the gram block K(X_i, Z_j) =
exp(−γ‖x−z‖²): the XLA chain (models/kernel_ridge.py §
GaussianKernelGenerator) lowers the ‖x−z‖² gemm expansion into a matmul
plus THREE full-size (tile_n × tile_m) HBM round trips — the squared
distance, its clamp, and the exp each materialize between fusions when
the block exceeds the fusion budget.  For out-of-core KRR that tensor
is produced nb² times per epoch, so the op is HBM-bandwidth bound on
exactly the sweep the solver spends its life in.

This kernel fuses the whole chain in VMEM per output tile:

    per (row tile i, col tile j):
      cross = x_i · z_jᵀ                      (one MXU matmul, f32 acc)
      sq    = max(‖x‖² − 2·cross + ‖z‖², 0)   (VPU, never leaves VMEM)
      out   = exp(−γ·sq)                      (VPU → one HBM write)

HBM traffic collapses to one read of each operand tile and one write of
the kernel block.  Under ``mxu='bf16'`` / ``'bf16_apply'`` the operand
tiles stream from HBM at half width (a bandwidth lever — the row norms
and all VMEM compute stay f32).  The SOLVER path always streams f32
(``mxu='f32'``): kernel values feed block Cholesky solves, and the
precision contract (analysis/precision.py) keeps solver math
solver-grade under every ``KEYSTONE_MATMUL`` mode.

``gram_block`` is the dispatcher: Pallas on TPU backends
(``pallas_supported()``, ``KEYSTONE_GRAM_PALLAS=0`` escape hatch), and
a bit-identical XLA chain everywhere else — ``_gram_block_xla`` emits
exactly the ``GaussianKernelGenerator`` graph, pinned by test.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from keystone_tpu.ops.fisher_pallas import _compiler_params, pallas_supported


def _precision():
    from keystone_tpu.utils import precision

    return precision


#: VMEM bytes budgeted per program: two (tile, d) operand tiles plus ~3
#: (tile_n, tile_m) f32 intermediates (cross, sq, out) live at once.
_VMEM_BUDGET = 12 << 20

#: features per row above which the untiled-d operand tiles cannot fit
#: VMEM even at the 128-row floor — the dispatcher falls back to the
#: XLA chain rather than asking Mosaic for the impossible.
GRAM_MAX_D = 8192


def _gram_tile(n: int, d: int) -> int:
    """Rows per operand tile under the VMEM budget.  Single-tile inputs
    round to a sublane multiple (8); tiled inputs use a 128-multiple so
    the lane-dim layouts stay native."""
    cap = 512
    while cap > 128 and 4 * (2 * cap * d + 3 * cap * cap) > _VMEM_BUDGET:
        cap //= 2
    if n <= cap:
        return -(-n // 8) * 8
    return cap


def _gram_kernel(x_ref, z_ref, out_ref, *, gamma: float):
    # operands may arrive bf16 (halved HBM read traffic — the kernel is
    # bandwidth bound); norms and all compute stay f32 in VMEM
    x = x_ref[:].astype(jnp.float32)  # (TN, d)
    z = z_ref[:].astype(jnp.float32)  # (TM, d)
    xn = jnp.sum(x * x, axis=1, keepdims=True)  # (TN, 1)
    zn = jnp.sum(z * z, axis=1)[None, :]  # (1, TM)
    # contract d without materializing zᵀ (dot_general, f32 accumulation)
    cross = jax.lax.dot_general(
        x, z, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    sq = jnp.maximum(xn - 2.0 * cross + zn, 0.0)
    out_ref[:] = jnp.exp(-gamma * sq)


@functools.partial(jax.jit, static_argnames=("gamma", "interpret", "mxu"))
def gram_block_pallas(
    x, z, gamma: float, interpret: bool = False, mxu: str = "f32"
):
    """K(x, z) = exp(−γ‖x−z‖²) as one fused Pallas kernel.

    ``x``: (n, d); ``z``: (m, d) → (n, m) f32.  ``gamma`` is static
    (one fit = one γ = one compile).  Matches ``_gram_block_xla`` /
    ``GaussianKernelGenerator`` to f32 rounding; padding tiles compute
    garbage that is sliced away before return."""
    n, d = x.shape
    m = z.shape[0]
    tn = _gram_tile(n, d)
    tm = _gram_tile(m, d)
    n_tiles = -(-n // tn)
    m_tiles = -(-m // tm)
    if n_tiles * tn != n:
        x = jnp.pad(x, ((0, n_tiles * tn - n), (0, 0)))
    if m_tiles * tm != m:
        z = jnp.pad(z, ((0, m_tiles * tm - m), (0, 0)))

    fdt = _precision().fdtype(mxu)
    out = pl.pallas_call(
        functools.partial(_gram_kernel, gamma=float(gamma)),
        grid=(n_tiles, m_tiles),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel")
        ),
        in_specs=[
            pl.BlockSpec((tn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((tm, d), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((tn, tm), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n_tiles * tn, m_tiles * tm), jnp.float32),
        interpret=interpret,
    )(x.astype(fdt), z.astype(fdt))
    return out[:n, :m]


def _gram_block_xla(x, z, gamma, solver_grade: bool = True):
    """The CPU/fallback chain — EXACTLY the ``GaussianKernelGenerator``
    graph, by construction: it IS the generator (imported lazily; the
    models module imports this one only inside functions, so there is
    no cycle).  Routing through the dispatcher off-TPU is bit-identical
    to calling the generator directly (pinned by test), and a future
    generator change cannot silently diverge the fallback."""
    from keystone_tpu.models.kernel_ridge import GaussianKernelGenerator

    return GaussianKernelGenerator(gamma, solver_grade=solver_grade)(x, z)


def gram_pallas_enabled(d: int = None) -> bool:
    """Should gram blocks route to the Pallas kernel?  True only on a
    TPU-capable target (``pallas_supported``), and only while the
    untiled feature dim fits the VMEM budget.

    The ``gram_pallas`` gate resolves through the planner precedence
    (``keystone_tpu.planner.registry``): ``KEYSTONE_GRAM_PALLAS=0`` is
    the documented env override; with the env unset, an installed
    ``PhysicalPlan`` that sampled the XLA chain as cheaper routes there;
    with neither, the historical default (Pallas wherever it runs)."""
    if os.environ.get("KEYSTONE_GRAM_PALLAS", "1") == "0":
        return False
    if os.environ.get("KEYSTONE_GRAM_PALLAS") is None:
        try:
            from keystone_tpu.planner import registry as _plans

            if _plans.planned_gate("gram_pallas") == "xla":
                return False
        except Exception:
            pass
    if d is not None and d > GRAM_MAX_D:
        return False
    return pallas_supported()


def gram_block(
    x,
    z,
    gamma,
    solver_grade: bool = True,
    mxu: str = "f32",
    use_pallas=None,
    interpret: bool = False,
):
    """One kernel column/tile block, routed to the fused Pallas kernel
    on capable backends and to the bit-identical XLA chain elsewhere.

    ``use_pallas=None`` resolves via :func:`gram_pallas_enabled`;
    callers inside jitted solver steps resolve it ONCE per fit and pass
    it static.  ``solver_grade`` keeps the XLA chain's contraction on
    ``sdot`` (true-f32 MXU passes) — the Pallas path is f32-accumulated
    regardless, and its operand stream width follows ``mxu`` (kept
    ``'f32'`` by every solver caller)."""
    if use_pallas is None:
        use_pallas = gram_pallas_enabled(int(x.shape[-1]))
    if use_pallas:
        return gram_block_pallas(
            x, z, float(gamma), interpret=interpret, mxu=mxu
        )
    return _gram_block_xla(x, z, gamma, solver_grade=solver_grade)


# ------------------------------------------------- polynomial / linear tier
def _poly_gram_kernel(x_ref, z_ref, out_ref, *, alpha: float, c: float, degree: int):
    # same VMEM discipline as the Gaussian kernel: operands may stream
    # bf16, the contraction accumulates f32, and the affine + integer
    # power epilogue never leaves VMEM
    x = x_ref[:].astype(jnp.float32)  # (TN, d)
    z = z_ref[:].astype(jnp.float32)  # (TM, d)
    cross = jax.lax.dot_general(
        x, z, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    out_ref[:] = (alpha * cross + c) ** degree


@functools.partial(
    jax.jit, static_argnames=("alpha", "c", "degree", "interpret", "mxu")
)
def poly_block_pallas(
    x, z, alpha: float, c: float, degree: int, interpret: bool = False,
    mxu: str = "f32",
):
    """K(x, z) = (α·x·zᵀ + c)^degree as one fused Pallas kernel —
    the polynomial (and, at α=1, c=0, degree=1, linear) twin of
    :func:`gram_block_pallas`; identical tiling/VMEM budget, identical
    padding discipline (padding tiles compute garbage, sliced away)."""
    n, d = x.shape
    m = z.shape[0]
    tn = _gram_tile(n, d)
    tm = _gram_tile(m, d)
    n_tiles = -(-n // tn)
    m_tiles = -(-m // tm)
    if n_tiles * tn != n:
        x = jnp.pad(x, ((0, n_tiles * tn - n), (0, 0)))
    if m_tiles * tm != m:
        z = jnp.pad(z, ((0, m_tiles * tm - m), (0, 0)))
    fdt = _precision().fdtype(mxu)
    out = pl.pallas_call(
        functools.partial(
            _poly_gram_kernel,
            alpha=float(alpha),
            c=float(c),
            degree=int(degree),
        ),
        grid=(n_tiles, m_tiles),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel")
        ),
        in_specs=[
            pl.BlockSpec((tn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((tm, d), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((tn, tm), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n_tiles * tn, m_tiles * tm), jnp.float32),
        interpret=interpret,
    )(x.astype(fdt), z.astype(fdt))
    return out[:n, :m]


def _poly_block_xla(x, z, alpha, c, degree, solver_grade: bool = True):
    """The CPU/fallback chain — EXACTLY the ``PolynomialKernelGenerator``
    graph, by construction (the ``_gram_block_xla`` discipline: the
    fallback IS the generator, so it can never silently diverge)."""
    from keystone_tpu.models.kernel_ridge import PolynomialKernelGenerator

    return PolynomialKernelGenerator(
        degree=int(degree), alpha=alpha, c=c, solver_grade=solver_grade
    )(x, z)


def _linear_block_xla(x, z, solver_grade: bool = True):
    """Bit-identical fallback = the ``LinearKernelGenerator`` itself."""
    from keystone_tpu.models.kernel_ridge import LinearKernelGenerator

    return LinearKernelGenerator(solver_grade=solver_grade)(x, z)


def poly_gram_block(
    x,
    z,
    alpha: float = 1.0,
    c: float = 1.0,
    degree: int = 2,
    solver_grade: bool = True,
    mxu: str = "f32",
    use_pallas=None,
    interpret: bool = False,
):
    """Polynomial-kernel gram block through the same Pallas/XLA gating
    as :func:`gram_block` (``gram_pallas_enabled`` +
    ``KEYSTONE_GRAM_PALLAS=0`` escape hatch + ``GRAM_MAX_D`` bound)."""
    if use_pallas is None:
        use_pallas = gram_pallas_enabled(int(x.shape[-1]))
    if use_pallas:
        return poly_block_pallas(
            x, z, float(alpha), float(c), int(degree),
            interpret=interpret, mxu=mxu,
        )
    return _poly_block_xla(x, z, alpha, c, degree, solver_grade=solver_grade)


def linear_gram_block(
    x,
    z,
    solver_grade: bool = True,
    mxu: str = "f32",
    use_pallas=None,
    interpret: bool = False,
):
    """Linear-kernel gram block: rides the polynomial megakernel at
    (α=1, c=0, degree=1) on Pallas targets; the XLA fallback is the
    ``LinearKernelGenerator`` chain, bit-identical."""
    if use_pallas is None:
        use_pallas = gram_pallas_enabled(int(x.shape[-1]))
    if use_pallas:
        return poly_block_pallas(
            x, z, 1.0, 0.0, 1, interpret=interpret, mxu=mxu
        )
    return _linear_block_xla(x, z, solver_grade=solver_grade)


def gram_block_for(kernel_gen, x, z, mxu: str = "f32", use_pallas=None,
                   interpret: bool = False):
    """Route a kernel GENERATOR instance through the matching
    dispatcher — the single entry ``BlockKernelMatrix`` uses, so every
    first-class generator (Gaussian, polynomial, linear) shares the
    Pallas/XLA gating and duck-typed generators stay untouched.
    Returns None for generators with no dispatcher route (the caller
    falls back to calling the generator directly)."""
    from keystone_tpu.models.kernel_ridge import (
        GaussianKernelGenerator,
        LinearKernelGenerator,
        PolynomialKernelGenerator,
    )

    sg = getattr(kernel_gen, "solver_grade", True)
    if isinstance(kernel_gen, GaussianKernelGenerator):
        return gram_block(
            x, z, float(kernel_gen.gamma), solver_grade=sg, mxu=mxu,
            use_pallas=use_pallas, interpret=interpret,
        )
    if isinstance(kernel_gen, PolynomialKernelGenerator):
        return poly_gram_block(
            x, z, alpha=float(kernel_gen.alpha), c=float(kernel_gen.c),
            degree=int(kernel_gen.degree), solver_grade=sg, mxu=mxu,
            use_pallas=use_pallas, interpret=interpret,
        )
    if isinstance(kernel_gen, LinearKernelGenerator):
        return linear_gram_block(
            x, z, solver_grade=sg, mxu=mxu, use_pallas=use_pallas,
            interpret=interpret,
        )
    return None
