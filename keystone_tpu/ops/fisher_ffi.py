"""Fisher-vector encode as a C++ XLA custom call (host/CPU).

Reference: the production FV encode in the reference is EncEval, a C++
library working in double precision on the host, reached over JNI
(utils/external/EncEval.scala; SURVEY.md §2.8 "JNI shim layer →
equivalent = XLA custom-call/FFI registration (C++)").  This module is
that equivalent: ``native/keystone_ffi.cpp`` registered through the XLA
FFI, accumulating in f64 regardless of I/O dtype.

Use it (a) as the precision reference in parity tests for the f32 TPU
paths (ops/fisher.py einsums, ops/fisher_pallas.py kernel) and (b) as a
CPU-backend encode.  TPU execution keeps the pure-XLA/Pallas paths — the
custom call is registered for platform="cpu" only, mirroring how EncEval
ran on the executors' host CPUs.
"""

from __future__ import annotations

import logging
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

_SO_PATH = os.path.join(
    os.path.dirname(__file__), "..", "native", "libkeystone_ffi.so"
)
_lock = threading.Lock()
# registered per target group, so a stale prebuilt .so missing the newer
# EM symbols still serves the fisher-encode targets it does have
_registered: dict[str, bool] = {}

_TARGETS = {
    np.dtype(np.float32): "ks_fisher_encode_f32",
    np.dtype(np.float64): "ks_fisher_encode_f64",
}
_SYMBOLS = {
    np.dtype(np.float32): "KsFisherEncodeF32",
    np.dtype(np.float64): "KsFisherEncodeF64",
}
_EM_TARGETS = {
    np.dtype(np.float32): ("ks_gmm_em_f32", "KsGmmEmF32"),
    np.dtype(np.float64): ("ks_gmm_em_f64", "KsGmmEmF64"),
}
_GROUPS = {
    "fisher": [(_TARGETS[dt], _SYMBOLS[dt]) for dt in _TARGETS],
    "em": list(_EM_TARGETS.values()),
}
_lib = None
_lib_loaded = False


def ffi_available(group: str = "fisher") -> bool:
    """Load the custom-call library (build lazily) and register the given
    target group ("fisher" or "em")."""
    if group not in _GROUPS:
        raise ValueError(f"unknown FFI group {group!r}; valid: {sorted(_GROUPS)}")
    global _lib, _lib_loaded
    with _lock:
        if group in _registered:
            return _registered[group]
        if not _lib_loaded:
            from keystone_tpu.native import build_and_load

            _lib = build_and_load(_SO_PATH, make_target="ffi")
            _lib_loaded = True
        lib = _lib
        if lib is None:
            _registered[group] = False
            return False
        try:
            for target, symbol in _GROUPS[group]:
                jax.ffi.register_ffi_target(
                    target, jax.ffi.pycapsule(getattr(lib, symbol)), platform="cpu"
                )
            _registered[group] = True
        except (OSError, AttributeError) as e:
            logger.warning("could not register FFI targets (%s): %s", group, e)
            _registered[group] = False
    return _registered[group]


def _resolve_dtype(arr: np.ndarray, targets) -> np.dtype:
    """Pick the FFI I/O dtype for ``arr``: f32/f64 by input dtype, but fall
    back to f32 when x64 is disabled — device_put would canonicalize f64
    operands to f32 while the f64 target still declares F64 buffers, and
    the call would be rejected at runtime.  (Accumulation is f64 inside
    the kernels either way.)"""
    dt = np.dtype(arr.dtype)
    if dt not in targets:
        dt = np.dtype(np.float32)
    if dt == np.float64 and not jax.config.jax_enable_x64:
        dt = np.dtype(np.float32)
    return dt


def fisher_encode_ffi(xs, mask, w, mu, var):
    """xs: (n, T, d); mask: (n, T); GMM (w (K,), mu/var (K, d)) → (n, 2KD).

    Same contract as ops/fisher.py § _fisher_encode, computed by the C++
    double-accumulation host kernel.  CPU backend only — raises
    RuntimeError when the library can't be built/loaded.
    """
    if not ffi_available():
        raise RuntimeError(
            "keystone FFI library unavailable (g++ or jaxlib FFI headers missing)"
        )
    xs = np.asarray(xs)
    dt = _resolve_dtype(xs, _TARGETS)
    xs = xs.astype(dt)
    n, t, d = xs.shape
    mu = np.asarray(mu, dt)
    k = mu.shape[0]
    # the targets are registered for platform="cpu" only (mirroring
    # EncEval running on host CPUs); pin placement so a TPU/GPU default
    # backend doesn't lower the call for a platform that lacks it
    cpu = jax.devices("cpu")[0]
    call = jax.ffi.ffi_call(
        _TARGETS[dt],
        jax.ShapeDtypeStruct((n, 2 * k * d), dt),
    )
    with jax.default_device(cpu):
        return call(
            jax.device_put(xs, cpu),
            jax.device_put(np.asarray(mask, dt), cpu),
            jax.device_put(np.asarray(w, dt), cpu),
            jax.device_put(mu, cpu),
            jax.device_put(np.asarray(var, dt), cpu),
        )


def gmm_em_ffi(x, mask, w0, mu0, var0, iters: int = 25, min_var: float = 1e-6):
    """Run ``iters`` EM steps from the given initial GMM, in C++ with f64
    accumulators (the EncEval-EM equivalent; models/gmm.py § _gmm_fit is
    the jitted TPU path).  Initialization stays in Python — the seeded
    k-means++ there can't be reproduced in C++ — so parity tests feed both
    paths the same init.  Returns (weights (K,), means (K, d), variances
    (K, d)).  CPU backend only."""
    if not ffi_available("em"):
        raise RuntimeError(
            "keystone FFI library unavailable (g++ or jaxlib FFI headers missing,"
            " or a stale library without the EM symbols)"
        )
    x = np.asarray(x)
    dt = _resolve_dtype(x, _EM_TARGETS)
    x = x.astype(dt)
    n, d = x.shape
    mu0 = np.asarray(mu0, dt)
    k = mu0.shape[0]
    target, _ = _EM_TARGETS[dt]
    cpu = jax.devices("cpu")[0]
    call = jax.ffi.ffi_call(
        target,
        (
            jax.ShapeDtypeStruct((k,), dt),
            jax.ShapeDtypeStruct((k, d), dt),
            jax.ShapeDtypeStruct((k, d), dt),
        ),
    )
    with jax.default_device(cpu):
        return call(
            jax.device_put(x, cpu),
            jax.device_put(np.asarray(mask, dt), cpu),
            jax.device_put(np.asarray(w0, dt), cpu),
            jax.device_put(mu0, cpu),
            jax.device_put(np.asarray(var0, dt), cpu),
            iters=np.int64(iters),
            min_var=np.float64(min_var),
        )
