"""Local Color Statistics descriptors.

Reference: nodes/images/LCSExtractor.scala — the second branch of the
ImageNet FV pipeline: per keypoint on a dense grid, the patch around it is
divided into ``grid × grid`` subpatches and the descriptor concatenates
each subpatch's per-channel mean and standard deviation
(dim = 2 · C · grid²; 96 for RGB with the default 4×4 grid).

TPU form: subpatch means/E[x²] are box-filter convolutions
(reduce_window sums), gathered at the keypoint grid — one jitted program
for the whole batch.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from keystone_tpu.workflow.transformer import Transformer
from keystone_tpu.utils import precision

_GRID = 4


class LCSExtractor(Transformer):
    """Input: (n, H, W, C) images.  Output: ((n, K, 2·C·16), mask)."""

    fusable = False

    def __init__(self, step: int = 4, subpatch_size: int = 6):
        self.step = int(step)
        self.subpatch_size = int(subpatch_size)

    def params(self):
        return (self.step, self.subpatch_size)

    def apply_batch(self, xs, mask=None):
        xs = jnp.asarray(xs, jnp.float32)
        if xs.ndim == 3:
            xs = xs[..., None]
        out = _lcs(xs, self.step, self.subpatch_size, mxu=precision.apply_mode())
        return out, jnp.ones(out.shape[:2], jnp.float32)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0][0]


def _lcs_grid(extent: int, step: int, sub: int) -> np.ndarray:
    margin = 2 * sub  # patch = 4x4 subpatches of size sub
    lo, hi = margin, extent - margin
    if hi <= lo:
        return np.zeros((0,), np.int32)
    return np.arange(lo, hi, step, dtype=np.int32)


def _box_matrix(extent: int, sub: int) -> np.ndarray:
    """(extent−sub+1, extent) banded ones operator ≡ the VALID stride-1
    1-D box sum along one axis: row y sums x[y : y+sub].  The matmul
    twin of the reduce_window box filter, same trick as
    ops/filters._blur_matrix."""
    out = np.zeros((extent - sub + 1, extent), np.float32)
    for y in range(out.shape[0]):
        out[y, y : y + sub] = 1.0
    return out


@partial(jax.jit, static_argnames=("step", "sub", "mxu"))
def _lcs(xs, step, sub, mxu: str = "f32"):
    n, h, w, c = xs.shape
    area = float(sub * sub)
    dims = (1, sub, sub, 1)
    ones = (1, 1, 1, 1)
    # box sums of x and x² with stride 1, VALID: index (y, x) = sum of
    # the sub×sub box whose top-left corner is (y, x)
    if mxu == "bf16_apply":
        # apply policy (utils/precision.py): the separable box sums as
        # banded-ones MXU einsums with bf16 inputs / f32 accumulation —
        # the same linear-map-as-matmul rework (and the same physical
        # form, filters.separable_apply) as the banded blur.  Inert
        # modes keep the reduce_window form below bit-identical.
        from keystone_tpu.ops.filters import separable_apply

        bh = jnp.asarray(_box_matrix(h, sub))
        bw = jnp.asarray(_box_matrix(w, sub))
        s1 = separable_apply(bh, bw, xs, mxu=mxu)
        s2 = separable_apply(bh, bw, xs * xs, mxu=mxu)
    else:
        s1 = lax.reduce_window(xs, 0.0, lax.add, dims, ones, "VALID")
        s2 = lax.reduce_window(xs * xs, 0.0, lax.add, dims, ones, "VALID")
    mean = s1 / area
    var = jnp.maximum(s2 / area - mean * mean, 0.0)
    std = jnp.sqrt(var)
    feat = jnp.concatenate([mean, std], axis=-1)  # (n, h', w', 2C)

    ys = jnp.asarray(_lcs_grid(h, step, sub))
    xs_ = jnp.asarray(_lcs_grid(w, step, sub))
    # subpatch top-left corners relative to keypoint: (-2,-1,0,1)*sub
    offs = ((jnp.arange(_GRID) - _GRID // 2) * sub).astype(jnp.int32)
    yy = (ys[:, None] + offs[None, :]).reshape(-1)
    xx = (xs_[:, None] + offs[None, :]).reshape(-1)
    g = feat[:, yy, :, :][:, :, xx, :]  # (n, Ky*4, Kx*4, 2C)
    ky, kx = ys.shape[0], xs_.shape[0]
    g = g.reshape(n, ky, _GRID, kx, _GRID, 2 * c)
    return jnp.transpose(g, (0, 1, 3, 2, 4, 5)).reshape(
        n, ky * kx, _GRID * _GRID * 2 * c
    )
