"""Statistical feature ops (reference src/main/scala/nodes/stats/).

All device ops are natively batched (apply_batch on the sharded (n, d)
array) and fusable, so chains like RandomSign → PaddedFFT → Rectifier
compile into one XLA stage.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.models.common import constrain
from keystone_tpu.parallel.mesh import DATA_AXIS
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.estimator import Estimator
from keystone_tpu.workflow.transformer import Transformer


class CosineRandomFeatures(Transformer):
    """Random Fourier features: cos(x·Wᵀ + b)
    (nodes/stats/CosineRandomFeatures.scala — TIMIT's featurizer).

    W rows ~ Gaussian(0, γ) for the RBF kernel or Cauchy(0, γ) for the
    Laplacian kernel; b ~ Uniform[0, 2π].
    """

    # TIMIT gathers many instances of this class with identical shapes —
    # traced parameters make them share ONE compiled program per shape
    # (Transformer.traced_attrs)
    traced_attrs = ("w", "b")

    def __init__(self, w: jnp.ndarray, b: jnp.ndarray):
        self.w = w  # (num_out, num_in)
        self.b = b  # (num_out,)

    @classmethod
    def init(
        cls,
        num_input_features: int,
        num_output_features: int,
        gamma: float = 1.0,
        seed: int = 0,
        distribution: str = "gaussian",
    ) -> "CosineRandomFeatures":
        kw, kb = jax.random.split(jax.random.PRNGKey(seed))
        shape = (num_output_features, num_input_features)
        if distribution == "gaussian":
            w = gamma * jax.random.normal(kw, shape, jnp.float32)
        elif distribution == "cauchy":
            w = gamma * jax.random.cauchy(kw, shape, jnp.float32)
        else:
            raise ValueError(f"unknown distribution {distribution!r}")
        b = jax.random.uniform(kb, (num_output_features,), jnp.float32, 0.0, 2 * np.pi)
        return cls(w, b)

    def params(self):
        from keystone_tpu.utils.hashing import cached_fingerprint

        return (self.w.shape, cached_fingerprint(self, "_fp", self.w, self.b))

    def apply_batch(self, xs, mask=None):
        # Deliberately NOT under the bf16 matmul policy: the phase xWᵀ is
        # unbounded, so bf16's ~0.4% relative rounding becomes an absolute
        # phase error that wraps through cos with O(1) feature error
        # (measured: 0.4 rad at |phase|≈100).  Random-feature quality
        # depends on phase fidelity; keep f32.
        return jnp.cos(xs @ self.w.T + self.b)

    def apply_one(self, x):
        return jnp.cos(self.w @ x + self.b)


class RandomSignNode(Transformer):
    """Elementwise Rademacher sign flip (nodes/stats/RandomSignNode.scala);
    paired with PaddedFFT for fastfood-style random features."""

    traced_attrs = ("signs",)  # MNIST gathers N sign-flip branches

    def __init__(self, signs: jnp.ndarray):
        self.signs = signs

    @classmethod
    def init(cls, num_features: int, seed: int = 0) -> "RandomSignNode":
        bits = jax.random.bernoulli(jax.random.PRNGKey(seed), 0.5, (num_features,))
        return cls(bits.astype(jnp.float32) * 2.0 - 1.0)

    def params(self):
        from keystone_tpu.utils.hashing import cached_fingerprint

        return (self.signs.shape[0], cached_fingerprint(self, "_fp", self.signs))

    def apply_batch(self, xs, mask=None):
        return xs * self.signs

    def apply_one(self, x):
        return x * self.signs


class PaddedFFT(Transformer):
    """Zero-pad to the next power of two and take a real FFT
    (nodes/stats/PaddedFFT.scala — MNIST's featurizer).

    Output = [Re(rfft), Im(rfft)] of the positive-frequency half (the
    reference emits the complex spectrum's components as a real vector;
    concatenation keeps full information with static shapes).  The FFT is
    unitary (norm="ortho") so feature magnitudes stay at the input's
    scale — important for the f32 normal-equation solvers downstream
    (the f64-everywhere reference didn't need this).
    """

    def params(self):
        return ()

    def apply_batch(self, xs, mask=None):
        d = xs.shape[-1]
        padded = 1 << (d - 1).bit_length()
        xs = jnp.pad(xs, [(0, 0)] * (xs.ndim - 1) + [(0, padded - d)])
        spec = jnp.fft.rfft(xs, axis=-1, norm="ortho")
        return jnp.concatenate([jnp.real(spec), jnp.imag(spec)], axis=-1)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


class LinearRectifier(Transformer):
    """max(x − α, maxVal) (nodes/stats/LinearRectifier.scala)."""

    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = float(max_val)
        self.alpha = float(alpha)

    def params(self):
        return (self.max_val, self.alpha)

    def apply_batch(self, xs, mask=None):
        return jnp.maximum(xs - self.alpha, self.max_val)

    def apply_one(self, x):
        return jnp.maximum(x - self.alpha, self.max_val)


class SignedHellingerMapper(Transformer):
    """sign(x)·√|x| (nodes/stats/SignedHellingerMapper.scala) — the
    power-normalization step after Fisher-vector encoding."""

    def params(self):
        return ()

    def apply_batch(self, xs, mask=None):
        out = jnp.sign(xs) * jnp.sqrt(jnp.abs(xs))
        return (out, mask) if mask is not None else out

    def apply_one(self, x):
        return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


class NormalizeRows(Transformer):
    """L2 row normalization (nodes/stats/NormalizeRows.scala)."""

    def __init__(self, eps: float = 1e-12):
        self.eps = float(eps)

    def params(self):
        return (self.eps,)

    def apply_batch(self, xs, mask=None):
        norm = jnp.sqrt(jnp.sum(xs * xs, axis=-1, keepdims=True))
        out = xs / jnp.maximum(norm, self.eps)
        return (out, mask) if mask is not None else out

    def apply_one(self, x):
        return x / jnp.maximum(jnp.sqrt(jnp.sum(x * x)), self.eps)


class StandardScalerModel(Transformer):
    traced_attrs = ("mean", "std")

    def __init__(self, mean: jnp.ndarray, std: Optional[jnp.ndarray] = None):
        self.mean = mean
        self.std = std

    def apply_batch(self, xs, mask=None):
        out = xs - self.mean
        if self.std is not None:
            out = out / self.std
        return out

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


class StandardScaler(Estimator):
    """Column mean/std via sharded moment sums — the treeAggregate
    col-stats of nodes/stats/StandardScaler.scala."""

    def __init__(self, normalize_std: bool = True, eps: float = 1e-8):
        self.normalize_std = normalize_std
        self.eps = float(eps)

    def params(self):
        return (self.normalize_std, self.eps)

    def fit_dataset(self, data: Dataset) -> StandardScalerModel:
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(data, StreamDataset):
            return self.fit_stream(data.batches)
        return self._fit(data.array, data.n)

    def fit_arrays(self, x) -> StandardScalerModel:
        x = jnp.asarray(x, jnp.float32)
        return self._fit(x, x.shape[0])

    def _fit(self, x, n):
        mean, std = _moments(x, jnp.float32(n))
        if not self.normalize_std:
            return StandardScalerModel(mean, None)
        return StandardScalerModel(mean, jnp.maximum(std, self.eps))

    def fit_stream(self, batches) -> StandardScalerModel:
        """Out-of-core moments from a stream of (n_i, d) host batches
        (companion of LinearMapEstimator.fit_stream; same contract:
        a callable returning a fresh iterator, or a re-iterable).

        Two passes: means, then Σ(x − mean)² of EXPLICITLY centered
        batches — the one-pass ``Σx² − n·mean²`` shortcut cancels
        catastrophically in f32 for large-mean/small-spread columns
        (std collapses to eps and scaled features explode).  Sums are
        Kahan-compensated across batches."""
        from keystone_tpu.models.common import stage_stream_batch

        get = batches if callable(batches) else lambda: iter(batches)
        sums = None
        n = 0
        for b in get():
            x, bn, row_ok = stage_stream_batch(b)
            n += bn
            sums = _acc_col_sums(sums, x)
        if n == 0:
            raise ValueError("empty batch stream")
        mean = sums[0] / n
        sq = None
        n2 = 0
        for b in get():
            x, bn, row_ok = stage_stream_batch(b)
            n2 += bn
            sq = _acc_centered_sq(sq, x, mean, row_ok)
        if n2 != n:
            raise ValueError(
                f"batch stream is not re-iterable: first pass saw {n} rows, "
                f"second pass {n2}. Pass a CALLABLE returning a fresh "
                "iterator (or a re-iterable like a list)."
            )
        var = sq[0] / max(n - 1.0, 1.0)  # unbiased, like _moments
        if not self.normalize_std:
            return StandardScalerModel(mean, None)
        return StandardScalerModel(mean, jnp.maximum(jnp.sqrt(var), self.eps))


@jax.jit
def _acc_col_sums(carry, x):
    """carry = (s1, c1): Kahan-compensated Σx columns."""
    from keystone_tpu.models.common import kahan_add

    b1 = jnp.sum(x, axis=0)
    if carry is None:
        return b1, jnp.zeros_like(b1)
    s1, c1 = carry
    return kahan_add(s1, c1, b1)


@jax.jit
def _acc_centered_sq(carry, x, mean, row_ok):
    """carry = (s2, c2): Kahan-compensated Σ(x − mean)² columns; the mask
    keeps shard-padding rows (which would center to −mean) at zero."""
    from keystone_tpu.models.common import kahan_add

    xc = (x - mean) * row_ok
    b2 = jnp.sum(xc * xc, axis=0)
    if carry is None:
        return b2, jnp.zeros_like(b2)
    s2, c2 = carry
    return kahan_add(s2, c2, b2)


@jax.jit
def _moments(x, n):
    x = constrain(x.astype(jnp.float32), DATA_AXIS)
    s1 = constrain(jnp.sum(x, axis=0))
    mean = s1 / n
    # EXPLICIT centering before the square: the Σx² − n·mean² shortcut
    # cancels catastrophically in f32 for large-mean/small-spread columns
    # (hypothesis found 2% std error at mean≈30; worse cases collapse to
    # 0).  Padding rows are zero, so they must be masked after centering.
    row_ok = (jnp.arange(x.shape[0]) < n).astype(jnp.float32)[:, None]
    xc = (x - mean) * row_ok
    s2c = constrain(jnp.sum(xc * xc, axis=0))
    # unbiased, like Breeze's stddev (n-1 denominator)
    var = s2c / jnp.maximum(n - 1.0, 1.0)
    return mean, jnp.sqrt(var)


class Sampler(Transformer):
    """Row subsampling with a fixed seed (nodes/stats/Sampler.scala);
    used to cut datasets down for PCA/GMM fitting."""

    is_host = False
    fusable = False

    def __init__(self, size: int, seed: int = 0):
        self.size = int(size)
        self.seed = int(seed)

    def params(self):
        return (self.size, self.seed)

    def apply_dataset(self, ds: Dataset) -> Dataset:
        k = min(self.size, ds.n)
        idx = np.random.default_rng(self.seed).choice(ds.n, size=k, replace=False)
        return Dataset(np.asarray(ds.array)[np.sort(idx)])

    def apply_one(self, x):
        return x


class ColumnSampler(Transformer):
    """Sample ``num_samples`` descriptors per item from ragged descriptor
    sets (nodes/stats/ColumnSampler.scala — the reference samples columns
    of per-image descriptor matrices before PCA/GMM fitting).

    Input: Dataset with array (n, max_k, d) + mask (n, max_k).
    Output: flat dense Dataset (n·num_samples, d), sampling only valid
    descriptors (with replacement when an item has fewer than requested).
    """

    fusable = False

    def __init__(self, num_samples: int, seed: int = 0):
        self.num_samples = int(num_samples)
        self.seed = int(seed)

    def params(self):
        # "fold_in-v1" versions the per-item key derivation (fold_in of
        # the global index, batching-invariant); bumping it invalidates
        # saved-state/CSE matches from the pre-fold_in derivation, whose
        # output differs for the same (num_samples, seed)
        return (self.num_samples, self.seed, "fold_in-v1")

    def apply_dataset(self, ds: Dataset) -> Dataset:
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(ds, StreamDataset):
            if ds.is_host:
                raise TypeError(
                    "ColumnSampler stream path needs device descriptor "
                    "batches, but this StreamDataset carries host "
                    "objects. Featurize to arrays first."
                )
            # Out-of-core path: sample each descriptor batch as it
            # streams past and keep only the (small) samples.  Keys are
            # derived from the GLOBAL item index, so the sample is
            # identical to the in-memory path regardless of batching.
            import numpy as np

            outs = []
            offset = 0
            key = jax.random.PRNGKey(self.seed)
            for arr, mask in ds.device_batches():
                if arr.ndim != 3:
                    raise ValueError(
                        "ColumnSampler expects (n, max_k, d) descriptor sets"
                    )
                m = arr.shape[0]
                out = _sample_descriptors(
                    arr,
                    mask
                    if mask is not None
                    else jnp.ones(arr.shape[:2], jnp.float32),
                    self.num_samples,
                    key,
                    offset=offset,
                )
                outs.append(np.asarray(out.reshape(m * self.num_samples, -1)))
                offset += m
            if offset != ds.n:
                raise ValueError(
                    f"descriptor stream produced {offset} items, expected {ds.n}"
                )
            return Dataset(np.concatenate(outs, axis=0))
        arr = ds.array
        if arr.ndim != 3:
            raise ValueError("ColumnSampler expects (n, max_k, d) descriptor sets")
        n = ds.n
        from keystone_tpu.workflow.transformer import _apply_chunk_rows

        chunk = _apply_chunk_rows()
        if chunk and arr.shape[0] > chunk:
            # fixed-shape row chunks with GLOBAL-index keys (exactly the
            # stream path's offset sampling, so output is bit-identical
            # to the whole-array program) — keeps the compiled program's
            # shape independent of n (see Transformer._apply_dataset_chunked)
            from keystone_tpu.workflow.transformer import iter_row_chunks

            mask_full = (
                ds.mask
                if ds.mask is not None
                else jnp.ones(arr.shape[:2], jnp.float32)
            )
            key = jax.random.PRNGKey(self.seed)
            parts = [
                _sample_descriptors(a, m, self.num_samples, key, offset=i)
                for a, m, i in iter_row_chunks(arr, mask_full, chunk)
            ]
            out = jnp.concatenate(parts, axis=0)
            flat = out[:n].reshape(n * self.num_samples, arr.shape[-1])
        else:
            # sample + slice-to-true-rows + flatten as ONE program: the
            # eager slice/reshape at (n, max_k, d) scale compiled two
            # extra (0.1-1.4 s) programs per sampler per process
            # (BASELINE.md r5 fit-floor split)
            flat = _sample_descriptors_flat(
                arr, ds.mask, self.num_samples, self.seed, n_true=n
            )
        return Dataset(flat)

    def apply_one(self, x):
        raise TypeError("ColumnSampler operates on datasets")


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("k", "n_true"))
def _sample_descriptors_flat(arr, mask, k, seed, n_true):
    """In-memory sampler fast path: mask default, PRNG key derivation,
    sampling, true-row slice, and the flat reshape fused into one jit
    program (the eager PRNGKey alone was 2 compiled programs/fit)."""
    key = jax.random.PRNGKey(seed)
    if mask is None:
        mask = jnp.ones(arr.shape[:2], jnp.float32)
    out = _sample_descriptors(arr, mask, k, key)
    return out[:n_true].reshape(n_true * k, arr.shape[-1])


@_partial(jax.jit, static_argnames=("k",))
def _sample_descriptors(arr, mask, k, key, offset=0):
    n, max_k, d = arr.shape
    # Per-item keys fold in the GLOBAL item index (offset for stream
    # batches), so sampling is batching-invariant: the streaming and
    # in-memory paths draw identical descriptors for the same seed.
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(n, dtype=jnp.int32) + jnp.int32(offset)
    )

    def per_item(a, m, kk):
        logits = jnp.where(m > 0, 0.0, -jnp.inf)
        idx = jax.random.categorical(kk, logits, shape=(k,))
        return a[idx]

    return jax.vmap(per_item)(arr, mask, keys)
