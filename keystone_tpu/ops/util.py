"""Utility nodes (reference src/main/scala/nodes/util/)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.transformer import Transformer


class ClassLabelIndicators(Transformer):
    """int label(s) → ±1 indicator vector
    (nodes/util/ClassLabelIndicators.scala) — the regression targets for
    least-squares classifiers."""

    def __init__(self, num_classes: int):
        self.num_classes = int(num_classes)

    def params(self):
        return (self.num_classes,)

    def apply_batch(self, xs, mask=None):
        onehot = jax.nn.one_hot(xs.astype(jnp.int32), self.num_classes)
        return onehot * 2.0 - 1.0

    def apply_one(self, x):
        return jax.nn.one_hot(jnp.asarray(x, jnp.int32), self.num_classes) * 2.0 - 1.0


class MaxClassifier(Transformer):
    """argmax prediction head (nodes/util/MaxClassifier.scala)."""

    def params(self):
        return ()

    def apply_batch(self, xs, mask=None):
        return jnp.argmax(xs, axis=-1)

    def apply_one(self, x):
        return jnp.argmax(x)


class TopKClassifier(Transformer):
    """top-k class indices, best first (nodes/util/TopKClassifier.scala);
    feeds the ImageNet top-5 evaluator."""

    def __init__(self, k: int):
        self.k = int(k)

    def params(self):
        return (self.k,)

    def apply_batch(self, xs, mask=None):
        _, idx = jax.lax.top_k(xs, min(self.k, xs.shape[-1]))
        return idx

    def apply_one(self, x):
        return jax.lax.top_k(x, min(self.k, x.shape[-1]))[1]


class VectorSplitter(Transformer):
    """(n, d) → (n, num_blocks, block_size) feature blocks
    (nodes/util/VectorSplitter.scala).  The block solvers do this
    internally; the node exists for explicit pipeline use."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)

    def params(self):
        return (self.block_size,)

    def apply_batch(self, xs, mask=None):
        n, d = xs.shape
        nb = -(-d // self.block_size)
        if nb * self.block_size != d:
            xs = jnp.pad(xs, ((0, 0), (0, nb * self.block_size - d)))
        return xs.reshape(n, nb, self.block_size)

    def apply_one(self, x):
        return self.apply_batch(x[None])[0]


class VectorCombiner(Transformer):
    """Inverse of VectorSplitter: (n, nb, bs) → (n, nb·bs)."""

    def params(self):
        return ()

    def apply_batch(self, xs, mask=None):
        return xs.reshape(xs.shape[0], -1)

    def apply_one(self, x):
        return x.reshape(-1)


class Densify(Transformer):
    """scipy.sparse rows → dense device array
    (nodes/util/Densify.scala — physical representation cast chosen by the
    optimizer's node-choice rule; on TPU dense is the only MXU-friendly
    form, so this is the ingest boundary for sparse text features)."""

    is_host = True
    fusable = False

    def params(self):
        return ()

    def apply_one(self, x):
        if hasattr(x, "toarray"):
            return np.asarray(x.toarray()).ravel().astype(np.float32)
        return np.asarray(x, np.float32)

    def apply_dataset(self, ds: Dataset) -> Dataset:
        items = ds.items
        if len(items) and hasattr(items[0], "toarray"):
            import scipy.sparse as sp

            stacked = sp.vstack(items).toarray().astype(np.float32)
            return Dataset(stacked)
        return Dataset(np.stack([self.apply_one(x) for x in items]).astype(np.float32))


class Sparsify(Transformer):
    """Dense rows → scipy CSR (nodes/util/Sparsify.scala); host-side."""

    is_host = True
    fusable = False

    def params(self):
        return ()

    def apply_dataset(self, ds: Dataset) -> Dataset:
        import scipy.sparse as sp

        mat = sp.csr_matrix(np.asarray(ds.numpy()))
        return ds.with_items([mat[i] for i in range(mat.shape[0])])

    def apply_one(self, x):
        import scipy.sparse as sp

        return sp.csr_matrix(np.asarray(x))


class FloatToDouble(Transformer):
    """dtype cast (nodes/util/FloatToDouble.scala).  TPUs compute in
    f32/bf16; this is a host-boundary cast for numpy interop."""

    def params(self):
        return ()

    def apply_batch(self, xs, mask=None):
        return xs.astype(jnp.float32)

    def apply_one(self, x):
        return jnp.asarray(x, jnp.float32)
