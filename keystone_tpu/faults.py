"""Process-wide deterministic fault injection.

The reference inherited its failure modes *and* their remedies from
Spark: partial writes, flaky storage, and worker death were absorbed by
lineage recompute and task retry (SURVEY.md §5).  The TPU rebuild
replaces those remedies with stage retry + durable checkpoints — which
means the failure modes themselves must be injectable on demand, or the
recovery paths rot untested.  This module is the injection side of that
contract; ``keystone_tpu.utils.durable`` is the survival side.

Named **sites** are threaded through the codebase::

    blockstore.read     FeatureBlockStore.read_block
    blockstore.write    FeatureBlockStore.append_rows (per block file)
    ckpt.save           durable.save_npz (write + publish phases)
    ckpt.load           durable.load_npz (per candidate file)
    stream.batch        loaders.stream.batched / resilient sources
    multihost.init      parallel.multihost.initialize
    executor.stage      GraphExecutor stage execution (inside retry scope)
    serve.enqueue       serve.PipelineService.submit (admission path);
                        multi-tenant services pass ctx ``tenant=NAME``,
                        so ``serve.enqueue:ctx.tenant=a:raise`` refuses
                        ONE tenant's admissions (blast-radius drills)
    serve.batch         serve micro-batch flush (batcher worker thread);
                        multi-tenant flushes ALSO fire once per co-
                        flushed tenant with ctx ``tenant=NAME`` — a
                        tenant-targeted fault fails that tenant's
                        riders only, co-tenants deliver
    serve.rollout       guarded rollout episode (serve/rollout.py) —
                        fires before the canary generation stages, so
                        ``raise`` fails the episode with the old
                        generation untouched (the ``serve.swap``
                        contract for guarded swaps)
    serve.worker        serve replica worker loop, per popped flush —
                        ``raise`` CRASHES the worker thread (the
                        in-hand flush is requeued for the supervisor's
                        restart), ``hang`` wedges it; this is how chaos
                        plans kill a live worker, not just one flush
    serve.net.connect   remote worker dialing the router (serve/net.py)
    serve.net.send      one outbound stream frame, either side; ctx
                        ``link=NAME`` names the worker the frame is
                        to/from, ``role=router|worker`` names the side
    serve.net.recv      one inbound stream frame, either side (same ctx)

A **plan** activates faults at sites, either via the ``inject`` context
manager (tests) or the ``KEYSTONE_FAULTS`` environment variable — the
env route is what lets the multi-process kill workers
(tests/faulttol_worker.py) run under injected faults without plumbing::

    KEYSTONE_FAULTS="ckpt.save:after=3:raise;blockstore.read:p=0.2:seed=7"

Plan grammar: ``site:token:token;site:token...`` where tokens are

- triggers: ``after=N`` (skip the first N matching calls), ``every=N``
  (then fire every Nth), ``p=F`` + ``seed=S`` (fire with probability F
  from a dedicated deterministic RNG), ``times=N`` (stop after N fires);
- actions: ``raise`` (default — raise :class:`FaultInjected`, an
  ``OSError`` so every transient-I/O retry path treats it as
  retryable), ``corrupt`` (flip bytes in the site's file), ``truncate``
  (halve the site's file), ``exit`` / ``exit=CODE`` (``os._exit`` — the
  kill-worker action), the **latency actions** ``delay=SECONDS``
  (stall the operation, then let it proceed) and ``hang`` (stall far
  past any deadline — ``KEYSTONE_HANG_SECONDS``, default 3600 s), and
  the **wire action** ``drop`` (alias ``partition``) — valid only at
  the ``serve.net.*`` sites, where the transport silently discards the
  frame (the peer sees pure silence, exactly what a network partition
  looks like).  ``drop`` never raises: :func:`fault_point` RETURNS the
  advisory action string and the transport honors it, so a severed
  link is detected by lease expiry, not by an exception the breaker
  could classify.  At ``serve.net.*`` sites ``corrupt`` is likewise
  advisory (there is no file): the sender flips bytes in the outbound
  frame and the receiver's CRC check condemns the connection;
- context matches: ``ctx.<key>=<value>`` restricts the spec to calls
  whose site context carries that value (string-compared), e.g.
  ``serve.replica:ctx.replica=0:delay=0.05`` stalls replica 0's
  flushes only — the straggler leg of ``tools/serve_bench.py`` and
  single-replica chaos plans ride this.  Non-matching calls do not
  advance the spec's triggers (``after=N`` counts matching calls).
  Latency actions are valid at every site; the stalls ride
  ``utils.guard.interruptible_sleep``, so a watchdog
  (``guard.run_with_deadline``) that gives up on the hung operation
  also unparks the injected sleep — the deadline/watchdog/breaker
  layer can be chaos-tested without hour-long test runs.

Everything is deterministic given the plan string and the call
sequence: probabilistic specs draw from a private ``random.Random(seed)``
so the same plan replayed over the same calls injects at the same call
indices (locked in by tests/test_faults.py).
"""

from __future__ import annotations

import logging
import os
import random
import threading
from collections import Counter
from contextlib import contextmanager
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

ENV_VAR = "KEYSTONE_FAULTS"

#: the sites wired through the codebase; plans naming anything else are
#: rejected at parse time (a typo'd site would otherwise never fire).
SITES = {
    "blockstore.read",
    "blockstore.write",
    "ckpt.save",
    "ckpt.load",
    "stream.batch",
    "multihost.init",
    "executor.stage",
    "serve.enqueue",
    "serve.batch",
    "serve.replica",
    "serve.swap",
    "serve.rollout",
    "serve.worker",
    "serve.artifact_load",
    "serve.net.connect",
    "serve.net.send",
    "serve.net.recv",
    "kernel.sweep",
    "plan.sample",
}

_ACTIONS = ("raise", "corrupt", "truncate", "exit", "delay", "hang", "drop")

#: sites where file actions (corrupt) and the drop action are ADVISORY:
#: fault_point returns the action name and the transport applies it to
#: the in-flight frame (there is no file to damage and nothing local to
#: raise — a partition is silence, not an exception)
_WIRE_SITE_PREFIX = "serve.net."

# file-damaging actions only make sense once the file is durably
# published; failure actions fire while the operation is in flight.
# Two-phase sites (ckpt.save) pass phase="write" / phase="publish";
# single-phase sites pass no phase and accept every action.
_ACTION_PHASE = {"corrupt": "publish", "truncate": "publish"}


class FaultInjected(OSError):
    """An injected transient fault.  Subclasses ``OSError`` on purpose:
    every retry path that absorbs flaky storage/transport I/O absorbs
    injected faults identically — a plan with ``times=1`` at a retried
    site must be *survived*, and that is the behavior chaos tests pin."""

    def __init__(self, site: str, message: Optional[str] = None):
        super().__init__(message or f"injected fault at {site!r}")
        self.site = site


class FaultPlanError(ValueError):
    """A malformed ``KEYSTONE_FAULTS`` / ``inject`` plan string."""


class UnknownFaultSiteError(FaultPlanError):
    """A plan names a site that matches no registered site — a typo'd
    site would never fire and report nothing outside ``tools/chaos.py``'s
    exit-2 path, so it is rejected up front (parse time for plan
    strings, :func:`inject` time for hand-built :class:`FaultPlan`
    objects).  Carries the offending names and the registered set."""

    def __init__(self, unknown, known=None):
        self.unknown = sorted(unknown)
        self.known = sorted(known if known is not None else SITES)
        names = ", ".join(repr(s) for s in self.unknown)
        super().__init__(
            f"unknown fault site(s) {names}; registered sites: {self.known}"
        )


def validate_plan(plan: "FaultPlan") -> "FaultPlan":
    """Check every spec's site against the registered-site set; raises
    :class:`UnknownFaultSiteError` listing the offenders.  Plan strings
    are validated at parse time already — this covers plans built
    directly from :class:`SiteSpec` objects (and is what the pre-flight
    analyzer's robustness pass calls)."""
    unknown = {s.site for s in plan.specs if s.site not in SITES}
    if unknown:
        raise UnknownFaultSiteError(unknown)
    return plan


class SiteSpec:
    """One parsed ``site:tokens`` clause plus its firing state."""

    def __init__(
        self,
        site: str,
        action: str = "raise",
        after: int = 0,
        every: int = 1,
        p: float = 1.0,
        seed: int = 0,
        times: Optional[int] = None,
        exit_code: int = 42,
        delay_seconds: float = 0.0,
        match: Optional[Dict[str, str]] = None,
    ):
        self.site = site
        self.action = action
        self.after = int(after)
        self.every = max(1, int(every))
        self.p = float(p)
        self.seed = int(seed)
        self.times = None if times is None else int(times)
        self.exit_code = int(exit_code)
        self.delay_seconds = float(delay_seconds)
        #: ctx.<key>=<value> clauses: the spec applies only to calls
        #: whose fault_point context matches every entry (str-compared)
        self.match = dict(match) if match else None
        self.reset()

    def matches(self, ctx: Dict) -> bool:
        if not self.match:
            return True
        return all(str(ctx.get(k)) == v for k, v in self.match.items())

    def reset(self) -> None:
        self.calls = 0
        self.fired = 0
        self._pending = False
        self._rng = random.Random(self.seed)

    def _advance(self) -> bool:
        """Consume one *operation* against the triggers."""
        self.calls += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.calls <= self.after:
            return False
        if (self.calls - self.after - 1) % self.every != 0:
            return False
        if self.p < 1.0 and self._rng.random() >= self.p:
            return False
        self.fired += 1
        return True

    def should_fire(self, phase: Optional[str]) -> bool:
        """Decide whether this call fires the fault.  Triggers advance
        once per *operation*: two-phase sites evaluate them on the
        ``write`` call, and a publish-phase action (corrupt/truncate)
        carries that decision over to the matching ``publish`` call, so
        ``after=N`` counts saves, not phases."""
        want = _ACTION_PHASE.get(self.action)  # None or "publish"
        if phase is None:
            return self._advance()
        if phase == "write":
            fire = self._advance()
            if want == "publish":
                self._pending = fire
                return False
            return fire
        if phase == "publish" and want == "publish":
            fire, self._pending = self._pending, False
            return fire
        return False


class FaultPlan:
    """An ordered set of :class:`SiteSpec`, activated as a unit."""

    def __init__(self, specs: List[SiteSpec], source: str = ""):
        self.specs = specs
        self.source = source

    def for_site(self, site: str) -> List[SiteSpec]:
        return [s for s in self.specs if s.site == site]

    def reset(self) -> None:
        for s in self.specs:
            s.reset()


def parse_plan(text: str) -> FaultPlan:
    """Parse the ``KEYSTONE_FAULTS`` grammar into a :class:`FaultPlan`."""
    specs: List[SiteSpec] = []
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        tokens = [t.strip() for t in clause.split(":")]
        site = tokens[0]
        if site not in SITES:
            raise UnknownFaultSiteError({site})
        kwargs: Dict = {}
        for tok in tokens[1:]:
            if not tok:
                continue
            key, _, val = tok.partition("=")
            if key in _ACTIONS and not val and key != "delay":
                kwargs["action"] = key
            elif key == "partition" and not val:
                # chaos-drill vocabulary: a partition IS dropped frames
                kwargs["action"] = "drop"
            elif key == "exit":
                kwargs["action"] = "exit"
                kwargs["exit_code"] = int(val)
            elif key == "delay":
                try:
                    kwargs["delay_seconds"] = float(val)
                except ValueError:
                    raise FaultPlanError(
                        f"delay needs seconds (delay=0.5), got {tok!r} in "
                        f"clause {clause!r}"
                    )
                kwargs["action"] = "delay"
            elif key == "after":
                kwargs["after"] = int(val)
            elif key == "every":
                kwargs["every"] = int(val)
            elif key == "times":
                kwargs["times"] = int(val)
            elif key == "p":
                kwargs["p"] = float(val)
            elif key == "seed":
                kwargs["seed"] = int(val)
            elif key.startswith("ctx."):
                if not val:
                    raise FaultPlanError(
                        f"context match needs a value (ctx.replica=0), "
                        f"got {tok!r} in clause {clause!r}"
                    )
                kwargs.setdefault("match", {})[key[4:]] = val
            else:
                raise FaultPlanError(
                    f"bad fault token {tok!r} in clause {clause!r}"
                )
        if kwargs.get("action") == "drop" and not site.startswith(
            _WIRE_SITE_PREFIX
        ):
            raise FaultPlanError(
                f"drop/partition is a wire action; it is honored only "
                f"at {_WIRE_SITE_PREFIX}* sites, not {site!r} (the site "
                f"would silently ignore it)"
            )
        specs.append(SiteSpec(site, **kwargs))
    return FaultPlan(specs, source=text)


# --------------------------------------------------------------- runtime

_LOCK = threading.Lock()
_STACK: List[FaultPlan] = []  # inject() plans, innermost last
_ENV_PLAN: Optional[FaultPlan] = None
_ENV_TEXT: Optional[str] = None  # the string _ENV_PLAN was parsed from

CALLS: Counter = Counter()  # site -> fault_point calls (operations)
INJECTED: Counter = Counter()  # site -> faults actually applied


def _env_plan() -> Optional[FaultPlan]:
    """The plan from ``KEYSTONE_FAULTS``, reparsed whenever the env value
    changes — so monkeypatched tests and freshly-spawned workers both
    pick it up without an explicit install call."""
    global _ENV_PLAN, _ENV_TEXT
    text = os.environ.get(ENV_VAR)
    if text != _ENV_TEXT:
        _ENV_TEXT = text
        _ENV_PLAN = parse_plan(text) if text else None
        if _ENV_PLAN is not None:
            logger.info("fault plan active from %s: %s", ENV_VAR, text)
    return _ENV_PLAN


def active_plans() -> List[FaultPlan]:
    plans = list(_STACK)
    env = _env_plan()
    if env is not None:
        plans.append(env)
    return plans


@contextmanager
def inject(plan):
    """Activate a fault plan for a ``with`` block (tests).  ``plan`` is a
    plan string or a :class:`FaultPlan`; trigger counters start fresh on
    entry so the block is a deterministic replay unit."""
    p = parse_plan(plan) if isinstance(plan, str) else plan
    # hand-built FaultPlan objects bypass parse_plan's site check;
    # validate here so a typo'd site fails loudly instead of never firing
    validate_plan(p)
    p.reset()
    with _LOCK:
        _STACK.append(p)
    try:
        yield p
    finally:
        with _LOCK:
            _STACK.remove(p)


def reset_stats() -> None:
    with _LOCK:
        CALLS.clear()
        INJECTED.clear()


def stats() -> Dict[str, Dict[str, int]]:
    """Per-site ``{"calls": n, "injected": m}`` since the last reset."""
    with _LOCK:
        sites = set(CALLS) | set(INJECTED)
        return {
            s: {"calls": CALLS[s], "injected": INJECTED[s]} for s in sites
        }


def _corrupt_file(path: str) -> None:
    """Flip a byte run in the middle of ``path`` (content damage the
    length/np.load checks cannot see — only a checksum catches it)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        chunk = f.read(16) or b"\0"
        f.seek(size // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))


def _truncate_file(path: str) -> None:
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)


def fault_point(site: str, path: Optional[str] = None, phase: Optional[str] = None, **ctx) -> Optional[str]:
    """The injection hook threaded through the codebase.

    No active plan ⇒ a counter bump and an immediate return (the hot
    paths pay one dict lookup).  With a matching spec it raises
    :class:`FaultInjected`, damages the file at ``path``, or exits the
    process, per the spec's action.  File actions with no ``path`` fall
    back to raising, so a plan never silently does nothing — EXCEPT at
    the ``serve.net.*`` sites, where ``drop`` and ``corrupt`` are
    advisory: the fired action name is RETURNED and the transport
    applies it to the in-flight frame (discard it / flip its bytes).
    Every other path returns ``None``; existing call sites ignore the
    return value unchanged.
    """
    from keystone_tpu.obs import metrics

    with _LOCK:
        if phase != "publish":  # two-phase sites count once per operation
            CALLS[site] += 1
        plans = list(_STACK)
    if phase != "publish":
        # outside _LOCK: the registry has its own lock, and the mirror
        # needs nothing from this module's critical section
        metrics.inc("faults.calls", site=site)
    env = _env_plan()
    if env is not None:
        plans.append(env)
    if not plans:
        return None
    advisory: Optional[str] = None
    for plan in reversed(plans):  # innermost inject() wins
        for spec in plan.for_site(site):
            if not spec.matches(ctx):
                continue  # triggers advance on MATCHING calls only
            with _LOCK:
                fire = spec.should_fire(phase)
                if fire:
                    INJECTED[site] += 1
            if not fire:
                continue
            # mirrored into the unified metrics registry so chaos
            # reports and run ledgers read fault outcomes from the same
            # place as every other subsystem (and survive reset_stats)
            metrics.inc("faults.injected", site=site)
            logger.warning(
                "fault injected at %s (action=%s%s)",
                site,
                spec.action,
                f", path={path}" if path else "",
            )
            if spec.action == "exit":
                os._exit(spec.exit_code)
            if spec.action == "drop":
                # a partition is silence: hand the verdict back to the
                # transport (which skips the send / discards the recv)
                # and keep scanning — a co-active raise still wins
                advisory = "drop"
                continue
            if spec.action == "corrupt" and site.startswith(
                _WIRE_SITE_PREFIX
            ):
                advisory = advisory or "corrupt"
                continue
            if spec.action in ("delay", "hang"):
                # latency, not failure: stall the operation in flight,
                # then let it proceed.  The sleep is cancel-aware
                # (guard.interruptible_sleep) so a watchdog that gave up
                # on this operation also unparks the injected stall.
                from keystone_tpu.utils import guard

                seconds = (
                    spec.delay_seconds
                    if spec.action == "delay"
                    else guard.hang_seconds()
                )
                guard.interruptible_sleep(seconds)
                continue
            if spec.action == "corrupt" and path and os.path.exists(path):
                _corrupt_file(path)
                continue  # damage is silent: the *load* must detect it
            if spec.action == "truncate" and path and os.path.exists(path):
                _truncate_file(path)
                continue
            raise FaultInjected(site)
    return advisory
