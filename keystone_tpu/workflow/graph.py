"""Pipeline DAG representation.

Mirrors the semantics of the reference's immutable dataflow graph
(workflow/Graph.scala § Graph, NodeId/SourceId/SinkId and
workflow/Operator.scala § Operator kinds), rebuilt for the TPU execution
model: node outputs are sharded device arrays (or fitted transformers)
rather than RDDs, and linear chains of device ops are later fused into
single jit stages by the optimizer.

A graph has:
  - sources:       open inputs (bound to data when a pipeline is applied)
  - operators:     NodeId -> Operator
  - dependencies:  NodeId -> tuple of (NodeId | SourceId)
  - sink_dependencies: SinkId -> (NodeId | SourceId)

All editing methods return a new Graph (persistent-structure style), which
is what makes optimizer rules safe to compose.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, Optional, Tuple, Union


@dataclasses.dataclass(frozen=True, order=True)
class NodeId:
    id: int

    def __repr__(self):
        return f"n{self.id}"


@dataclasses.dataclass(frozen=True, order=True)
class SourceId:
    id: int

    def __repr__(self):
        return f"src{self.id}"


@dataclasses.dataclass(frozen=True, order=True)
class SinkId:
    id: int

    def __repr__(self):
        return f"sink{self.id}"


GraphId = Union[NodeId, SourceId]


class Operator:
    """A physical node kind (workflow/Operator.scala)."""

    def label(self) -> str:
        return type(self).__name__

    def signature(self):
        """Hashable identity for CSE merging; ``None`` disables merging."""
        return None


class DatasetOperator(Operator):
    """A literal dataset (workflow/DatasetOperator.scala)."""

    def __init__(self, dataset):
        self.dataset = dataset

    def label(self):
        return "Dataset"

    def signature(self):
        name = getattr(self.dataset, "name", None)
        return ("dataset", name if name is not None else id(self.dataset))


class DatumOperator(Operator):
    """A literal single datum (workflow/DatumOperator.scala)."""

    def __init__(self, datum):
        self.datum = datum

    def label(self):
        return "Datum"

    def signature(self):
        return ("datum", id(self.datum))


class TransformerOperator(Operator):
    """Apply a Transformer (workflow/TransformerOperator.scala)."""

    def __init__(self, transformer):
        self.transformer = transformer

    def label(self):
        return self.transformer.label

    def signature(self):
        sig = self.transformer.signature()
        return None if sig is None else ("transform", sig)


class EstimatorOperator(Operator):
    """Fit an Estimator on its dependencies; yields a Transformer
    (workflow/EstimatorOperator.scala)."""

    def __init__(self, estimator):
        self.estimator = estimator

    def label(self):
        return f"fit[{self.estimator.label}]"

    def signature(self):
        sig = self.estimator.signature()
        return None if sig is None else ("fit", sig)


class DelegatingOperator(Operator):
    """Apply the transformer produced by dependency 0 to dependencies 1..n
    (workflow/DelegatingOperator.scala)."""

    def label(self):
        return "apply"

    def signature(self):
        return ("delegate",)


class GatherOperator(Operator):
    """Concatenate the feature outputs of N branch dependencies
    (workflow/Pipeline.scala § Pipeline.gather / GatherTransformer).

    The reference gathers branch outputs into a Seq per datum which
    pipelines immediately concatenate; here gather concatenates along the
    trailing (feature) axis directly."""

    def label(self):
        return "Gather"

    def signature(self):
        return ("gather",)


class Graph:
    def __init__(
        self,
        sources: Tuple[SourceId, ...] = (),
        operators: Optional[Dict[NodeId, Operator]] = None,
        dependencies: Optional[Dict[NodeId, Tuple[GraphId, ...]]] = None,
        sink_dependencies: Optional[Dict[SinkId, GraphId]] = None,
    ):
        self.sources = tuple(sources)
        self.operators = dict(operators or {})
        self.dependencies = dict(dependencies or {})
        self.sink_dependencies = dict(sink_dependencies or {})

    # ---------------------------------------------------------------- ids
    def _next_id(self) -> int:
        used = [i.id for i in self.operators]
        used += [s.id for s in self.sources]
        used += [s.id for s in self.sink_dependencies]
        return max(used, default=-1) + 1

    @property
    def nodes(self) -> Tuple[NodeId, ...]:
        return tuple(self.operators.keys())

    @property
    def sinks(self) -> Tuple[SinkId, ...]:
        return tuple(self.sink_dependencies.keys())

    # ------------------------------------------------------------ editing
    def add_source(self) -> Tuple["Graph", SourceId]:
        sid = SourceId(self._next_id())
        g = Graph(
            self.sources + (sid,), self.operators, self.dependencies, self.sink_dependencies
        )
        return g, sid

    def add_node(self, op: Operator, deps: Tuple[GraphId, ...]) -> Tuple["Graph", NodeId]:
        nid = NodeId(self._next_id())
        ops = dict(self.operators)
        ops[nid] = op
        dep = dict(self.dependencies)
        dep[nid] = tuple(deps)
        return Graph(self.sources, ops, dep, self.sink_dependencies), nid

    def add_sink(self, dep: GraphId) -> Tuple["Graph", SinkId]:
        kid = SinkId(self._next_id())
        sinks = dict(self.sink_dependencies)
        sinks[kid] = dep
        return Graph(self.sources, self.operators, self.dependencies, sinks), kid

    def set_operator(self, node: NodeId, op: Operator) -> "Graph":
        ops = dict(self.operators)
        ops[node] = op
        return Graph(self.sources, ops, self.dependencies, self.sink_dependencies)

    def set_dependencies(self, node: NodeId, deps: Tuple[GraphId, ...]) -> "Graph":
        dep = dict(self.dependencies)
        dep[node] = tuple(deps)
        return Graph(self.sources, self.operators, dep, self.sink_dependencies)

    def replace_dependency(self, old: GraphId, new: GraphId) -> "Graph":
        """Point every edge into ``old`` at ``new`` instead."""
        dep = {
            n: tuple(new if d == old else d for d in ds)
            for n, ds in self.dependencies.items()
        }
        sinks = {k: (new if d == old else d) for k, d in self.sink_dependencies.items()}
        return Graph(self.sources, self.operators, dep, sinks)

    def remove_node(self, node: NodeId) -> "Graph":
        ops = {n: o for n, o in self.operators.items() if n != node}
        dep = {n: d for n, d in self.dependencies.items() if n != node}
        return Graph(self.sources, ops, dep, self.sink_dependencies)

    def remove_source(self, source: SourceId) -> "Graph":
        return Graph(
            tuple(s for s in self.sources if s != source),
            self.operators,
            self.dependencies,
            self.sink_dependencies,
        )

    def remove_sink(self, sink: SinkId) -> "Graph":
        sinks = {k: d for k, d in self.sink_dependencies.items() if k != sink}
        return Graph(self.sources, self.operators, self.dependencies, sinks)

    def replace_source_with_node(self, source: SourceId, op: Operator) -> Tuple["Graph", NodeId]:
        """Bind a source to a literal operator (how pipeline.apply(data) works)."""
        g, nid = self.add_node(op, ())
        g = g.replace_dependency(source, nid)
        return g.remove_source(source), nid

    # ---------------------------------------------------------- combining
    def union(self, other: "Graph") -> Tuple["Graph", Dict]:
        """Disjoint union; returns (combined, mapping from other's ids to new ids)."""
        counter = itertools.count(self._next_id())
        mapping: Dict = {}

        def remap(i):
            if i not in mapping:
                newid = next(counter)
                mapping[i] = type(i)(newid)
            return mapping[i]

        sources = self.sources + tuple(remap(s) for s in other.sources)
        ops = dict(self.operators)
        deps = dict(self.dependencies)
        for n, op in other.operators.items():
            ops[remap(n)] = op
        for n, ds in other.dependencies.items():
            deps[remap(n)] = tuple(remap(d) for d in ds)
        sinks = dict(self.sink_dependencies)
        for k, d in other.sink_dependencies.items():
            sinks[remap(k)] = remap(d)
        return Graph(sources, ops, deps, sinks), mapping

    def connect(self, sink: SinkId, source: SourceId) -> "Graph":
        """Splice: feed this graph's ``sink`` value into ``source``'s consumers."""
        dep = self.sink_dependencies[sink]
        g = self.remove_sink(sink)
        g = g.replace_dependency(source, dep)
        return g.remove_source(source)

    # ---------------------------------------------------------- analysis
    def dependents(self, target: GraphId) -> Tuple[GraphId, ...]:
        out = [n for n, ds in self.dependencies.items() if target in ds]
        out += [k for k, d in self.sink_dependencies.items() if d == target]
        return tuple(out)

    def ancestors(self, target: GraphId) -> Tuple[GraphId, ...]:
        seen = []

        def walk(i):
            if isinstance(i, NodeId):
                for d in self.dependencies[i]:
                    if d not in seen:
                        seen.append(d)
                        walk(d)

        walk(target)
        return tuple(seen)

    def topological_nodes(self) -> Tuple[NodeId, ...]:
        order, seen = [], set()

        def visit(i):
            if i in seen or not isinstance(i, NodeId):
                return
            seen.add(i)
            for d in self.dependencies[i]:
                visit(d)
            order.append(i)

        for k in sorted(self.sink_dependencies, key=lambda s: s.id):
            visit(self.sink_dependencies[k])
        for n in sorted(self.operators, key=lambda n: n.id):
            visit(n)
        return tuple(order)

    def prefix_signature(self, target: GraphId, _memo=None) -> Optional[tuple]:
        """Structural hash of the subgraph rooted at ``target``.

        Two nodes with equal prefix signatures compute the same value —
        the merge criterion of the CSE rule
        (workflow/EquivalentNodeMergeRule.scala).
        """
        if _memo is None:
            _memo = {}
        if target in _memo:
            return _memo[target]
        if isinstance(target, SourceId):
            result = ("source", target.id)
        else:
            sig = self.operators[target].signature()
            if sig is None:
                result = ("unique", target.id)
            else:
                deps = tuple(
                    self.prefix_signature(d, _memo) for d in self.dependencies[target]
                )
                if any(d is None for d in deps):
                    result = ("unique", target.id)
                else:
                    result = ("node", sig, deps)
        _memo[target] = result
        return result

    def __repr__(self):
        lines = [f"Graph(sources={list(self.sources)})"]
        for n in self.topological_nodes():
            deps = ", ".join(map(repr, self.dependencies[n]))
            lines.append(f"  {n!r} = {self.operators[n].label()}({deps})")
        for k, d in self.sink_dependencies.items():
            lines.append(f"  {k!r} <- {d!r}")
        return "\n".join(lines)
