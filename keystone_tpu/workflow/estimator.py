"""Estimator / LabelEstimator.

Reference: workflow/Estimator.scala § Estimator[A,B] (``fit(RDD[A]):
Transformer[A,B]``; ``withData`` splices it into a pipeline DAG) and
workflow/LabelEstimator.scala § LabelEstimator[A,B,L] (supervised
``fit(data, labels)``).

Concrete estimators implement ``fit_dataset`` (or the array-level
``fit_arrays``), returning a fitted Transformer.  The heavy lifting —
sharded Gramians, psum, on-device solves — happens inside the concrete
solvers in keystone_tpu.models.
"""

from __future__ import annotations

from typing import Optional

from keystone_tpu.workflow.dataset import Dataset, as_dataset
from keystone_tpu.workflow.transformer import Chainable, Transformer


class Estimator(Chainable):
    @property
    def label(self) -> str:
        return type(self).__name__

    def params(self):
        return None

    def signature(self):
        p = self.params()
        return None if p is None else (type(self).__name__, p)

    # -------------------------------------------------------------- fit
    def fit_arrays(self, x) -> Transformer:
        raise NotImplementedError(type(self).__name__)

    def fit_dataset(self, data: Dataset) -> Transformer:
        return self.fit_arrays(data.array if not data.is_host else data.items)

    def fit(self, data) -> Transformer:
        return self.fit_dataset(as_dataset(data))

    # -------------------------------------------------------------- DSL
    def with_data(self, data, labels=None):
        """Splice this estimator into a pipeline: returns a Pipeline whose
        transform is 'the transformer obtained by fitting me on ``data``'
        (workflow/Estimator.scala § withData)."""
        from keystone_tpu.workflow.pipeline import Pipeline

        return Pipeline.from_estimator(self, data, labels)

    # Optimizer hook: physical-operator choice (workflow/NodeOptimizationRule).
    def choose_physical(self, sample: Optional[Dataset]) -> "Estimator":
        """Return the best physical implementation of this logical estimator
        given a data sample (dims/sparsity).  Default: self."""
        return self

    def __repr__(self):
        return self.label


class LabelEstimator(Estimator):
    def fit_arrays(self, x, y=None) -> Transformer:
        raise NotImplementedError(type(self).__name__)

    def fit_dataset(self, data: Dataset, labels: Optional[Dataset] = None) -> Transformer:
        if labels is None:
            raise ValueError(f"{self.label}.fit requires labels")
        return self.fit_arrays(
            data.array if not data.is_host else data.items,
            labels.array if not labels.is_host else labels.items,
        )

    def fit(self, data, labels=None) -> Transformer:
        if labels is None:
            raise ValueError(f"{self.label}.fit requires labels")
        return self.fit_dataset(as_dataset(data), as_dataset(labels))
