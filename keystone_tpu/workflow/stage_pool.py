"""Process-wide shared stage pool: cross-pipeline stage-result cache.

KeystoneML's headline optimization (ICDE 2017 §4) is common-subexpression
elimination plus cost-based cache placement — but both ran *per
pipeline*.  A multi-tenant serving fleet runs many pipelines over the
same featurization prefix (one SIFT/FV/Nyström front end feeding N
heads), and per-pipeline CSE recomputes that prefix once per tenant per
request batch.  This module inverts stage-result cache ownership: the
per-run :class:`~keystone_tpu.workflow.executor.GraphExecutor` memo
stays (it is the per-walk working set), but results of stages marked
shareable by the cross-pipeline pass (``workflow/cross.py``) are read
through and published into ONE process-wide pool, so co-served tenants
compute each shared prefix once per flush.

Keying is content-addressed, riding the existing ``signature()``
machinery end to end::

    entry key = (normalized prefix signature, flush token)

- the **prefix signature** is the structural hash of the stage and its
  whole input subgraph (``Graph.prefix_signature`` semantics with
  sources normalized), i.e. *what* is computed — two tenants' SIFT
  prefixes share it exactly when CSE would have merged them inside one
  pipeline;
- the **flush token** identifies *which data* the stage ran over — the
  multi-tenant batcher stamps one token per combined flush, so entries
  can never leak across different request batches (and a hedged/healed
  re-run of the same flush shares the token and therefore the work).

Lifecycle: :meth:`SharedStagePool.begin_flush` declares the flush's
per-signature consumer counts (how many co-flushed tenants contain the
stage); each hit decrements the entry's remaining-consumer refcount and
the entry is freed at zero (HBM is returned as soon as the last tenant
has read it, not at flush end); :meth:`SharedStagePool.end_flush` drops
whatever is left.  Publishing past the byte budget evicts — unpinned
first, least-recently-used first — and an evicted-but-needed entry is
simply a miss: the consumer recomputes (counted, never wrong).
``pin``/``auto_pin`` implement the ProfilingAutoCacheRule placement
discipline at pool granularity: the signatures whose byte estimates
earn their residency under the budget are evicted last.

Safety is the PR-6 signature-collision pass: the cross-pipeline planner
runs it over the UNION of co-served graphs and refuses to mark any
stage whose signature collides (equal signature, observably different
state) — a refused stage is counted (``serve.pool_refusals``) and runs
per-tenant, never shared, never wrong.

Thread-safety: one lock around the entry map; stage *computation* runs
outside it (tenant walks of one flush are sequential on the replica
worker, and distinct flushes never share a token, so there is no
same-key compute race to arbitrate).
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from typing import Dict, Optional, Tuple

from keystone_tpu.obs import metrics

#: in-process pool lookup by token: replica clones are pickle
#: round-trips WITHIN one process (serve/fleet), and a private pool
#: holds a lock — unpicklable — so the applier serializes the token and
#: the clone re-resolves the same pool here.  Weak values: a retired
#: pool dies with its service (cross-process unpickles miss and fall
#: back to the default pool).
_POOL_REGISTRY: "weakref.WeakValueDictionary[int, SharedStagePool]" = (
    weakref.WeakValueDictionary()
)
_POOL_TOKENS = itertools.count(1)


def pool_by_token(token) -> Optional["SharedStagePool"]:
    """The live pool registered under ``token`` (None: unknown/dead —
    the caller falls back to :func:`default_pool`)."""
    if token is None:
        return None
    return _POOL_REGISTRY.get(token)

#: the pool key: (normalized prefix signature, flush token)
PoolKey = Tuple[tuple, object]


def expr_nbytes(expr) -> int:
    """Byte estimate of one pooled stage result (the eviction unit):
    the device array's real footprint for dataset results, 0 for
    host/stream results (they hold no HBM worth accounting)."""
    ds = getattr(expr, "dataset", None)
    if ds is None:
        return 0
    try:
        if ds.is_host:
            return 0
        arr = ds.array
        return int(arr.size) * int(arr.dtype.itemsize)
    except Exception:
        return 0


class _Entry:
    __slots__ = ("value", "nbytes", "remaining", "last_use", "sig")

    def __init__(self, value, nbytes: int, remaining: int, sig):
        self.value = value
        self.nbytes = int(nbytes)
        self.remaining = int(remaining)
        self.last_use = time.monotonic()
        self.sig = sig


class SharedStagePool:
    """Bounded, refcounted, process-wide stage-result cache.

    ``budget_bytes``: one HBM budget for every resident entry (default:
    ``workflow.profiling.pool_budget_bytes()`` — a fraction of the real
    device limit, leaving the serve batches and model weights their
    room).  ``name`` labels the pool's gauges."""

    def __init__(self, budget_bytes: Optional[int] = None, name: str = "serve"):
        if budget_bytes is None:
            from keystone_tpu.workflow.profiling import pool_budget_bytes

            budget_bytes = pool_budget_bytes()
        self.budget_bytes = int(budget_bytes)
        self.name = name
        #: in-process identity for clone re-resolution (pool_by_token)
        self.token = next(_POOL_TOKENS)
        _POOL_REGISTRY[self.token] = self
        self._lock = threading.Lock()
        self._entries: Dict[PoolKey, _Entry] = {}
        self._bytes = 0
        #: signatures pinned by the placement decision: evicted last
        self._pinned: set = set()
        #: token -> {sig: consumer count} declared by begin_flush
        self._flushes: Dict[object, Dict[tuple, int]] = {}
        #: observed output bytes per signature (feeds auto_pin)
        self.sig_bytes: Dict[tuple, int] = {}
        #: per-signature registered tenant counts (live tenants whose
        #: graph contains the signature) — refcounts ACROSS tenants, as
        #: opposed to the per-flush remaining-consumer counts
        self._sig_tenants: Dict[tuple, set] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ------------------------------------------------------- registration
    def register_tenant(self, tenant: str, sigs) -> None:
        """Declare a live tenant's shareable signatures (service
        construction).  An entry whose signature has no registered
        tenant left is first in line for eviction."""
        with self._lock:
            for s in sigs:
                self._sig_tenants.setdefault(s, set()).add(tenant)

    def unregister_tenant(self, tenant: str) -> None:
        with self._lock:
            for s, owners in list(self._sig_tenants.items()):
                owners.discard(tenant)
                if not owners:
                    del self._sig_tenants[s]

    def sig_refcount(self, sig) -> int:
        """How many registered tenants share ``sig`` right now."""
        with self._lock:
            return len(self._sig_tenants.get(sig, ()))

    # ------------------------------------------------------------ pinning
    def pin(self, sig) -> None:
        with self._lock:
            self._pinned.add(sig)

    def auto_pin(self, budget_fraction: float = 0.5) -> int:
        """Greedy pin placement under a fraction of the pool budget —
        the AutoCacheRule discipline at pool granularity: signatures
        ranked by compute saved per byte pinned, approximated as
        (consumers − 1) / observed bytes, admitted until the pin budget
        is spent.  Needs observed byte estimates (a primed flush or the
        first live one).  Returns how many signatures were pinned."""
        with self._lock:
            budget = self.budget_bytes * max(0.0, min(1.0, budget_fraction))
            ranked = sorted(
                (
                    (s, b)
                    for s, b in self.sig_bytes.items()
                    if len(self._sig_tenants.get(s, ())) >= 2
                ),
                key=lambda sb: -(
                    (len(self._sig_tenants.get(sb[0], ())) - 1)
                    / max(sb[1], 1)
                ),
            )
            self._pinned.clear()
            spent = 0
            for s, b in ranked:
                if spent + b > budget:
                    continue
                spent += b
                self._pinned.add(s)
            return len(self._pinned)

    # ------------------------------------------------------ flush lifecycle
    def begin_flush(self, token, sig_consumers: Dict[tuple, int]) -> None:
        """Declare one combined flush: ``sig_consumers`` maps each
        shareable signature to the number of co-flushed tenants whose
        graph contains it (the per-entry refcount ceiling)."""
        with self._lock:
            self._flushes[token] = dict(sig_consumers)

    def end_flush(self, token) -> None:
        """Drop the flush's declaration and any leftover entries (a
        consumer pruned deeper in the walk never read them)."""
        with self._lock:
            self._flushes.pop(token, None)
            for key in [k for k in self._entries if k[1] == token]:
                self._drop(key)
            metrics.set_gauge("serve.pool_bytes", float(self._bytes))

    # ----------------------------------------------------------- get / put
    def get(self, key: PoolKey):
        """``(hit, value)`` — a hit decrements the entry's remaining
        consumer count and frees it at zero."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                metrics.inc("serve.pool_misses")
                metrics.set_gauge("serve.pool_hit_rate", self._hit_rate())
                return False, None
            self.hits += 1
            e.last_use = time.monotonic()
            e.remaining -= 1
            value = e.value
            if e.remaining <= 0:
                self._drop(key)
            metrics.inc("serve.pool_hits")
            metrics.set_gauge("serve.pool_bytes", float(self._bytes))
            metrics.set_gauge("serve.pool_hit_rate", self._hit_rate())
            return True, value

    def _hit_rate(self) -> float:
        """Lifetime hit fraction (must hold the lock) — the
        ``serve.pool_hit_rate`` gauge the autoscaler reads as a
        capacity lever: a high rate means co-tenant flushes amortize
        their shared prefix, so occupancy overstates marginal cost."""
        n = self.hits + self.misses
        return (self.hits / n) if n else 0.0

    def hit_rate(self) -> float:
        with self._lock:
            return self._hit_rate()

    def put(self, key: PoolKey, value, nbytes: Optional[int] = None) -> bool:
        """Publish one computed stage result.  Returns False (and stores
        nothing) when the flush declared no further consumer for the
        signature, or when the entry alone exceeds the whole budget."""
        sig, token = key
        if nbytes is None:
            nbytes = expr_nbytes(value)
        with self._lock:
            self.sig_bytes[sig] = int(nbytes)
            consumers = self._flushes.get(token, {}).get(sig, 1)
            remaining = consumers - 1  # the producer is a consumer too
            if remaining <= 0:
                return False
            if nbytes > self.budget_bytes:
                # one entry bigger than the whole budget: never resident
                self.evictions += 1
                metrics.inc("serve.pool_evictions")
                return False
            self._evict_until(self.budget_bytes - int(nbytes))
            self._entries[key] = _Entry(value, nbytes, remaining, sig)
            self._bytes += int(nbytes)
            metrics.set_gauge("serve.pool_bytes", float(self._bytes))
            return True

    # ----------------------------------------------------------- internals
    def _drop(self, key: PoolKey) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._bytes -= e.nbytes

    def _evict_until(self, budget: int) -> None:
        """Must hold the lock.  Evict until resident bytes fit
        ``budget``: entries whose signature has no registered tenant
        first, then unpinned LRU, then pinned LRU (only when nothing
        else is left — pinned is a priority, not an exemption)."""
        if self._bytes <= budget:
            return
        order = sorted(
            self._entries.items(),
            key=lambda kv: (
                len(self._sig_tenants.get(kv[1].sig, ())) > 0,
                kv[1].sig in self._pinned,
                kv[1].last_use,
            ),
        )
        for key, e in order:
            if self._bytes <= budget:
                return
            self._drop(key)
            self.evictions += 1
            metrics.inc("serve.pool_evictions")

    # -------------------------------------------------------------- status
    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "resident_bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self._hit_rate(), 4),
                "evictions": self.evictions,
                "pinned_sigs": len(self._pinned),
                "registered_sigs": len(self._sig_tenants),
            }


#: the process-wide default pool (the "one HBM budget" of the design);
#: services may construct private pools (tests do)
_DEFAULT: Optional[SharedStagePool] = None
_DEFAULT_LOCK = threading.Lock()


def default_pool() -> SharedStagePool:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SharedStagePool()
        return _DEFAULT
