"""Coarse-grained failure recovery for pipeline fits.

SURVEY.md §5 "Failure detection/elastic recovery": the reference
delegated everything to Spark — lineage recompute of lost partitions,
task retry, speculative execution.  The TPU-era decomposition here:

- **stage retry** (executor.GraphExecutor ``node_retries``): stages are
  pure functions of memoized inputs, so a transiently-failed stage
  (preempted device, flaky interconnect) is simply re-run — the lineage-
  recompute analogue at node granularity.
- **process-level restart + resume** (this module): when a whole
  process dies (host failure, killed Gloo peer), the surviving state is
  what was durably saved — pipeline-prefix materializations
  (workflow/state.py, reloaded by SavedStateLoadRule) and per-epoch
  solver checkpoints (``fit_checkpointed`` /
  ``fit_store(checkpoint_dir=...)``).  ``fit_with_recovery`` wraps the
  build-fit cycle so a restarted attempt resumes from both instead of
  recomputing.  In a multi-process job every process must restart
  together (collectives are SPMD); the fault-injection test
  (tests/test_faulttol.py) exercises exactly that: kill one of two Gloo
  processes mid-fit, relaunch, assert the fit resumes from the epoch
  checkpoint and matches an uninterrupted run.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


def scan_state_dir(state_dir: str) -> Dict[str, List[str]]:
    """Classify the ``.npz`` durable-state files under ``state_dir``
    (recursively: solver checkpoint dirs nest) as valid / corrupt.

    Validity is the durable layer's contract (utils/durable): the
    checksum sidecar matches when present, and the npz parses.  Files
    without a sidecar only fail on unreadability (legacy state keeps
    loading).  Returns ``{"valid": [...], "corrupt": [...]}``.
    """
    import numpy as np

    from keystone_tpu.utils import durable

    out: Dict[str, List[str]] = {"valid": [], "corrupt": []}
    for root, _dirs, files in os.walk(state_dir):
        for name in files:
            if not (name.endswith(".npz") or ".npz." in name):
                continue
            if ".tmp." in name or name.endswith(durable.CHECKSUM_SUFFIX):
                continue
            if name.endswith(".corrupt"):
                continue
            path = os.path.join(root, name)
            try:
                durable.verify_checksum(path)
                with np.load(path, allow_pickle=False) as z:
                    z.files  # force the header parse
                out["valid"].append(path)
            except Exception:
                out["corrupt"].append(path)
    return out


def purge_invalid_state(state_dir: str) -> List[str]:
    """Quarantine corrupt durable-state files (renamed ``*.corrupt``) so
    resume scans stop tripping over them; rotated last-good copies
    (``<file>.1`` …) are left for the solvers' fallback loads.  Returns
    the quarantined paths.  Called between ``fit_with_recovery``
    attempts — a restart after a torn write starts from a clean scan."""
    from keystone_tpu.utils import durable

    quarantined = []
    for path in scan_state_dir(state_dir)["corrupt"]:
        dest = durable.quarantine(path)
        if dest is not None:
            quarantined.append(dest)
    return quarantined


def fit_with_recovery(
    build_fn: Callable,
    state_dir: Optional[str] = None,
    max_restarts: int = 2,
) -> Tuple[object, int]:
    """Fit with in-process restart + saved-state resume.

    ``build_fn() -> Pipeline`` builds the UNFITTED pipeline (training
    data loading belongs inside it).  Each attempt fits; on failure the
    pipeline is rebuilt and refitted.  With ``state_dir`` set,
    previously-saved prefix materializations reload via
    SavedStateLoadRule (PipelineEnv wiring), and solvers configured with
    a ``checkpoint_dir`` resume from their last completed epoch — so a
    retry resumes rather than recomputes.

    Returns ``(fitted, attempts_used)``.  Raises the last error once
    ``max_restarts`` is exhausted.
    """
    import jax

    from keystone_tpu.workflow.pipeline import PipelineEnv

    if max_restarts > 0 and jax.process_count() > 1:
        # collectives are SPMD: a locally-restarted attempt would rerun
        # collectives its peers never see and hang the job.  Multi-process
        # restart is job-level (relaunch every process; the saved state
        # and solver checkpoints make the relaunch resume) — fail fast
        # here instead of deadlocking.
        logger.warning(
            "fit_with_recovery: in-process retry disabled under "
            "multi-process execution (%d processes); restart the job to "
            "recover",
            jax.process_count(),
        )
        max_restarts = 0

    prev_state_dir = PipelineEnv.state_dir
    if state_dir is not None:
        PipelineEnv.state_dir = state_dir
    try:
        from keystone_tpu.utils.durable import backoff_delays

        delays = iter(
            backoff_delays(max_restarts, base_delay=0.1, max_delay=2.0)
        )
        last_err: Optional[BaseException] = None
        for attempt in range(max_restarts + 1):
            try:
                # multi-process: verify every peer is alive and healthy
                # BEFORE launching a collective fit — a dead host fails
                # this barrier (DeadlineExceeded / SickHostError) in
                # bounded time instead of deadlocking the first
                # all-reduce.  Inert single-process and with no
                # KEYSTONE_HEALTH_TIMEOUT configured.
                from keystone_tpu.parallel import multihost

                multihost.maybe_health_barrier("fit_with_recovery.attempt")
                fitted = build_fn().fit()
                # force materialization so failures surface HERE, inside
                # the retry scope, not at first use of the fitted model
                fitted.block_until_ready()
                return fitted, attempt
            except Exception as e:
                last_err = e
                # per-attempt fault stats land in the run ledger BEFORE
                # any reset/restart, so chaos reports keep the full
                # per-restart history instead of only the final window
                from keystone_tpu import faults
                from keystone_tpu.obs import ledger

                ledger.event(
                    "faults.stats",
                    attempt=attempt,
                    error=f"{type(e).__name__}: {e}"[:200],
                    stats=faults.stats(),
                )
                if attempt >= max_restarts:
                    raise
                logger.warning(
                    "fit attempt %d failed (%s); restarting (%d left)",
                    attempt,
                    e,
                    max_restarts - attempt,
                )
                if state_dir is not None:
                    # quarantine corrupt durable state before the resume
                    # scan: the restart must load last-good checkpoints,
                    # not re-crash on the same torn file
                    purge_invalid_state(state_dir)
                # jittered backoff: restarting fleets must decorrelate
                time.sleep(next(delays, 2.0))
        raise last_err  # unreachable; keeps type checkers calm
    finally:
        PipelineEnv.state_dir = prev_state_dir
