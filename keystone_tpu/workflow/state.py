"""Pipeline-level checkpoint/resume: saved materialized prefixes.

Reference: workflow/SavedStateLoadRule.scala + ExtractSaveablePrefixes —
materialized node outputs are saved under a state dir and reloaded by an
optimizer rule on later runs, so re-running a pipeline skips the expensive
featurization prefix (SURVEY.md §5 "Checkpoint/resume").

Keys are the node's structural prefix signature.  Signatures embed Python
``id()`` for unhashable params (datasets, weight arrays), which is not
stable across processes — so cross-run reuse requires *named* datasets
(``Dataset(..., name="train-images")``); unnamed roots simply never match
and recompute, which is safe.
"""

from __future__ import annotations

import hashlib
import logging
import os
from typing import Optional

import numpy as np

from keystone_tpu.workflow import graph as G
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.optimizer import Rule

logger = logging.getLogger(__name__)


def _contains_object_id(sig) -> bool:
    """True if any leaf looks like a CPython id() (memory address) —
    unstable across processes, so unusable as a persistent key.  Real
    params (dims, seeds, floats) are far below the 2^40 address range."""
    if isinstance(sig, (tuple, list)):
        return any(_contains_object_id(s) for s in sig)
    return isinstance(sig, int) and sig >= (1 << 40)


def _signature_key(sig) -> Optional[str]:
    """Stable hash of a prefix signature; None when it contains id()s."""
    if sig is None or _contains_object_id(sig):
        return None
    try:
        text = repr(sig)
    except Exception:
        return None
    if "unique" in text:
        return None
    return hashlib.sha256(text.encode()).hexdigest()[:24]


class SavedStateLoadRule(Rule):
    """Replace subgraphs whose prefix signature has a saved materialization
    with a dataset literal loaded from the state dir."""

    name = "SavedStateLoad"

    def __init__(self, state_dir: str):
        self.state_dir = state_dir

    def apply(self, graph: G.Graph) -> G.Graph:
        if not os.path.isdir(self.state_dir):
            return graph
        # deepest-first: replacing a shallow prefix would rewrite deeper
        # prefixes' signatures and orphan their saved results
        for n in reversed(list(graph.topological_nodes())):
            if n not in graph.operators:
                continue  # removed by an earlier replacement
            op = graph.operators[n]
            if not isinstance(op, (G.TransformerOperator, G.GatherOperator)):
                continue
            key = _signature_key(graph.prefix_signature(n, {}))
            if key is None:
                continue
            path = os.path.join(self.state_dir, key + ".npz")
            orbax_path = os.path.join(self.state_dir, key + ".orbax")
            loaded = None
            # newest save wins: save_pipeline_state removes the sibling
            # format, so at most one exists; if a corrupt one remains,
            # fall through to the other rather than giving up
            if os.path.isdir(orbax_path):
                try:
                    loaded = load_dataset_orbax(orbax_path)
                except Exception as e:
                    logger.warning("orbax reload failed for %s: %s", key, e)
            if loaded is None and os.path.exists(path):
                try:
                    loaded = load_dataset(path)
                except Exception as e:
                    logger.warning("state reload failed for %s: %s", key, e)
            if loaded is None:
                continue
            logger.info("reloaded saved prefix %s for %s", key, op.label())
            graph, new_node = graph.add_node(G.DatasetOperator(loaded), ())
            graph = graph.replace_dependency(n, new_node)
            # drop the now-orphaned prefix
            graph = graph.remove_node(n)
        return _prune_orphans(graph)


def save_dataset(ds: Dataset, path: str) -> None:
    from keystone_tpu.utils import durable

    payload = {"array": np.asarray(ds.array), "n": np.asarray(ds.n)}
    if ds.mask is not None:
        payload["mask"] = np.asarray(ds.mask)
    # atomic + checksummed (utils/durable): a crash mid-save never leaves
    # a half-written prefix for a later run to trip over, and bit rot is
    # detected at load instead of silently reviving wrong features
    durable.save_npz(path, payload, keep=1)


def load_dataset(path: str) -> Dataset:
    from keystone_tpu.utils import durable

    loaded = durable.load_npz(path)
    if loaded is None:
        raise durable.CorruptStateError(f"no valid saved dataset at {path}")
    z, _ = loaded
    arr = z["array"]
    n = int(z["n"])
    mask = z["mask"] if "mask" in z else None
    d = Dataset(arr, n=n, shard=True)
    if mask is not None:
        import jax.numpy as jnp

        d.mask = jnp.asarray(mask)
    return d


_ORBAX_CKPTR = None


def _orbax_checkpointer():
    """Process-wide StandardCheckpointer: one async checkpointer reused
    for every node save/restore (per-call instances leak their background
    resources across a multi-node pipeline)."""
    global _ORBAX_CKPTR
    if _ORBAX_CKPTR is None:
        import orbax.checkpoint as ocp

        _ORBAX_CKPTR = ocp.StandardCheckpointer()
    return _ORBAX_CKPTR


def save_dataset_orbax(ds: Dataset, path: str) -> None:
    """Tensorstore-backed save via orbax (SURVEY §5 "stage-output
    checkpointing (tensorstore)"): sharded device arrays write per-shard
    without a host gather — the multi-host-scale path; npz is the
    single-host default."""
    payload = {"array": ds.array, "n": np.asarray(ds.n)}
    if ds.mask is not None:
        payload["mask"] = ds.mask
    ckptr = _orbax_checkpointer()
    ckptr.save(os.path.abspath(path), payload, force=True)
    ckptr.wait_until_finished()


def load_dataset_orbax(path: str) -> Dataset:
    """Restore DIRECTLY to the mesh's data sharding: the abstract target
    carries NamedShardings, so each host/device reads only its shards —
    no full-array host materialization on restore (matching the save
    path's no-gather property)."""
    import jax

    from keystone_tpu.parallel.mesh import DATA_AXIS, current_mesh, data_sharding

    ckptr = _orbax_checkpointer()
    path = os.path.abspath(path)
    meta = ckptr.metadata(path).item_metadata
    mesh = current_mesh()
    # The saved arrays were padded for the SAVING mesh; if the current
    # 'data' axis doesn't divide that padded leading dim (saved on 8
    # devices, restored on 16), a sharded restore would raise.  Restore to
    # host instead and re-shard through Dataset (which re-pads) — the
    # saved prefix stays usable across mesh-shape changes.
    dsize = int(mesh.shape[DATA_AXIS])
    sharded = all(
        key == "n" or (len(m.shape) > 0 and m.shape[0] % dsize == 0)
        for key, m in meta.items()
    )
    target = {}
    for key, m in meta.items():
        shape, dtype = tuple(m.shape), m.dtype
        if key == "n" or not sharded:
            target[key] = np.zeros(shape, dtype)  # host
        else:  # 'array' / 'mask': leading axis over 'data'
            target[key] = jax.ShapeDtypeStruct(
                shape, dtype, sharding=data_sharding(mesh, max(1, len(shape)))
            )
    restored = ckptr.restore(path, target)
    if not sharded:
        logger.warning(
            "saved prefix %s was padded for a different mesh (leading dim "
            "%s vs data axis %d); restoring replicated and re-sharding",
            path,
            {k: m.shape for k, m in meta.items() if k != "n"},
            dsize,
        )
        d = Dataset(restored["array"], n=int(restored["n"]), shard=True)
        if restored.get("mask") is not None:
            # shard_batch re-pads the mask's leading dim exactly as it did
            # the array's, keeping ragged (array, mask) rows aligned
            from keystone_tpu.parallel import shard_batch

            d.mask = shard_batch(restored["mask"])
        return d
    d = Dataset.__new__(Dataset)
    d._host = None
    d._array = restored["array"]
    d.n = int(restored["n"])
    d.mask = restored.get("mask")
    d.name = None
    return d


def save_pipeline_state(
    pipeline_dataset, state_dir: str, backend: str = "npz"
) -> int:
    """Materialize and save every saveable (stable-signature, device-array)
    node output of a lazy result — ExtractSaveablePrefixes.  Returns the
    number of saved prefixes.  ``backend="orbax"`` writes tensorstore
    checkpoints (per-shard, no host gather — use at multi-host scale)."""
    from keystone_tpu.workflow.executor import DatasetExpr, GraphExecutor

    if backend not in ("npz", "orbax"):
        raise ValueError(f"unknown state backend {backend!r}: npz | orbax")
    os.makedirs(state_dir, exist_ok=True)
    g = pipeline_dataset.graph
    ex = GraphExecutor(g)
    memo: dict = {}
    saved = 0
    for n in g.topological_nodes():
        op = g.operators[n]
        if not isinstance(op, (G.TransformerOperator, G.GatherOperator)):
            continue
        key = _signature_key(g.prefix_signature(n, memo))
        if key is None:
            continue
        expr = ex.execute(n)
        if isinstance(expr, DatasetExpr) and not expr.dataset.is_host:
            npz_path = os.path.join(state_dir, key + ".npz")
            orbax_path = os.path.join(state_dir, key + ".orbax")
            if backend == "orbax":
                save_dataset_orbax(expr.dataset, orbax_path)
                if os.path.exists(npz_path):  # newest save must win reload
                    os.remove(npz_path)
                from keystone_tpu.utils import durable

                side = durable.checksum_path(npz_path)
                if os.path.exists(side):
                    os.remove(side)
            else:
                save_dataset(expr.dataset, npz_path)
                if os.path.isdir(orbax_path):
                    import shutil

                    shutil.rmtree(orbax_path)
            saved += 1
    return saved


def _prune_orphans(graph: G.Graph) -> G.Graph:
    """Remove nodes not reachable from any sink (after prefix replacement)."""
    keep = set()
    for k in graph.sink_dependencies.values():
        keep.add(k)
        keep.update(graph.ancestors(k))
    for n in list(graph.operators):
        if n not in keep:
            graph = graph.remove_node(n)
    return graph


# Reference-named alias: workflow/ExtractSaveablePrefixes.scala — the pass
# that walks a pipeline result and persists every stable-signature prefix.
ExtractSaveablePrefixes = save_pipeline_state
