"""Pipeline DSL: chain/gather composition, lazy results, fit.

Reference: workflow/Pipeline.scala § Pipeline[A,B], PipelineDataset,
PipelineDatum — pipelines are DAGs with one open source and one sink;
``andThen`` chains, ``Pipeline.gather`` merges branches, applying a
pipeline to data yields a *lazy* result wrapper, and ``fit()`` resolves
every estimator into its fitted transformer (the reference's
PipelineModel), triggering optimization + execution.

Typical usage (cf. pipelines/images/mnist/MnistRandomFFT.scala):

    featurizer = Pipeline.gather([
        RandomSignNode.init(d, key) | PaddedFFT() | LinearRectifier(0.0)
        for key in keys
    ])
    predictor = (featurizer
                 .and_then(LinearMapEstimator(lam), train_x, train_labels)
                 .and_then(MaxClassifier()))
    test_pred = predictor(test_x).get()
"""

from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence, Union

from keystone_tpu.workflow import graph as G
from keystone_tpu.workflow.dataset import Dataset, StreamDataset, as_dataset
from keystone_tpu.workflow.estimator import Estimator, LabelEstimator
from keystone_tpu.workflow.executor import (
    DatasetExpr,
    DatumExpr,
    GraphExecutor,
    TransformerExpr,
)
from keystone_tpu.workflow.transformer import Chainable, Transformer


class PipelineEnv:
    """Process-global pipeline environment (workflow/PipelineEnv.scala):
    the optimizer instance and the state directory for saved pipelines.

    Setting ``state_dir`` prepends a SavedStateLoadRule batch to the
    default optimizer, so previously-materialized prefixes reload
    automatically (the reference's saved-state flow)."""

    optimizer = None  # lazily constructed default
    state_dir: Optional[str] = None
    #: stage-retry budget for every executor the framework creates
    #: (GraphExecutor node_retries — SURVEY §5 task-retry analogue).
    #: None = read KEYSTONE_STAGE_RETRIES at use time (lazy: a malformed
    #: env value must not crash module import, and post-import env
    #: changes should take effect); set an int here to override.
    node_retries: Optional[int] = None

    @classmethod
    def stage_retries(cls) -> int:
        if cls.node_retries is not None:
            return max(0, int(cls.node_retries))
        raw = os.environ.get("KEYSTONE_STAGE_RETRIES", "0")
        try:
            return max(0, int(raw))
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "KEYSTONE_STAGE_RETRIES=%r is not an integer; using 0", raw
            )
            return 0
    _built_for_state_dir: Optional[str] = None
    _auto_built = None  # the instance get_optimizer constructed itself
    _auto_built_sig = ()  # identity of its rule batches at build time

    @classmethod
    def set_optimizer(cls, optimizer) -> None:
        """Install a custom optimizer; it is never overwritten by the
        state_dir wiring (compose SavedStateLoadRule yourself if needed)."""
        cls.optimizer = optimizer
        cls._auto_built = None
        cls._auto_built_sig = ()

    @classmethod
    def get_optimizer(cls):
        # anything not built by this method — via set_optimizer, direct
        # assignment to the public attribute, or in-place extension of
        # the auto-built default's rule batches — is user-owned: honor it
        if cls.optimizer is not None and (
            cls.optimizer is not cls._auto_built
            or len(cls.optimizer.batches) != len(cls._auto_built_sig)
            or any(
                b is not s
                for b, s in zip(cls.optimizer.batches, cls._auto_built_sig)
            )
        ):
            return cls.optimizer
        if cls.optimizer is None or cls._built_for_state_dir != cls.state_dir:
            from keystone_tpu.workflow.optimizer import (
                Once,
                RuleBatch,
                default_optimizer,
            )

            opt = default_optimizer()
            if cls.state_dir:
                from keystone_tpu.workflow.state import SavedStateLoadRule

                opt.batches.insert(
                    0,
                    RuleBatch(
                        "saved-state", Once(), [SavedStateLoadRule(cls.state_dir)]
                    ),
                )
            cls.optimizer = opt
            cls._auto_built = opt
            cls._auto_built_sig = tuple(opt.batches)
            cls._built_for_state_dir = cls.state_dir
        return cls.optimizer


def _validate_requested(validate) -> bool:
    """The ONE pre-flight gate shared by ``fit`` and ``freeze``:
    explicit flag wins, ``None`` reads ``KEYSTONE_VALIDATE``.  Kept
    module-local (not ``analysis.validation_enabled``) so the off path
    costs one env lookup and never imports the analysis package — the
    inert-path guarantee the solver byte-identity pins ride on."""
    if validate is not None:
        return bool(validate)
    return os.environ.get("KEYSTONE_VALIDATE", "0") == "1"


class Pipeline(Chainable):
    """A DAG with one open source and one sink."""

    def __init__(self, graph: G.Graph, source: G.SourceId, sink: G.SinkId):
        self.graph = graph
        self.source = source
        self.sink = sink

    # ------------------------------------------------------- constructors
    @staticmethod
    def of(x) -> "Pipeline":
        if isinstance(x, Pipeline):
            return x
        if isinstance(x, Transformer):
            return Pipeline.from_transformer(x)
        raise TypeError(f"cannot lift {x!r} into a Pipeline")

    @staticmethod
    def from_transformer(t: Transformer) -> "Pipeline":
        g = G.Graph()
        g, src = g.add_source()
        g, node = g.add_node(G.TransformerOperator(t), (src,))
        g, sink = g.add_sink(node)
        return Pipeline(g, src, sink)

    @staticmethod
    def from_estimator(est: Estimator, data, labels=None) -> "Pipeline":
        """``est.withData(data[, labels])``: a pipeline whose transform is
        the transformer obtained by fitting ``est`` on ``data``."""
        g = G.Graph()
        g, data_dep = _splice_input(g, data)
        deps = [data_dep]
        if labels is not None:
            g, labels_dep = _splice_input(g, labels)
            deps.append(labels_dep)
        elif isinstance(est, LabelEstimator):
            raise ValueError(f"{est.label} requires labels")
        g, est_node = g.add_node(G.EstimatorOperator(est), tuple(deps))
        g, src = g.add_source()
        g, apply_node = g.add_node(G.DelegatingOperator(), (est_node, src))
        g, sink = g.add_sink(apply_node)
        return Pipeline(g, src, sink)

    @staticmethod
    def gather(branches: Sequence[Union["Pipeline", Transformer]]) -> "Pipeline":
        """Merge N branches over a shared input; output = concatenated
        features (workflow/Pipeline.scala § gather).  The CSE rule merges
        any common branch prefixes so shared featurization runs once."""
        branches = [Pipeline.of(b) for b in branches]
        if not branches:
            raise ValueError("gather of zero branches")
        g = G.Graph()
        g, src = g.add_source()
        outs = []
        for b in branches:
            g, mapping = g.union(b.graph)
            b_src = mapping[b.source]
            g = g.replace_dependency(b_src, src)
            g = g.remove_source(b_src)
            out_dep = g.sink_dependencies[mapping[b.sink]]
            g = g.remove_sink(mapping[b.sink])
            outs.append(out_dep)
        g, gather_node = g.add_node(G.GatherOperator(), tuple(outs))
        g, sink = g.add_sink(gather_node)
        return Pipeline(g, src, sink)

    # ------------------------------------------------------- composition
    def then_pipeline(self, other: "Pipeline") -> "Pipeline":
        g, mapping = self.graph.union(other.graph)
        g = g.connect(self.sink, mapping[other.source])
        return Pipeline(g, self.source, mapping[other.sink])

    def and_then(self, nxt, data=None, labels=None) -> "Pipeline":
        """Chain a transformer/pipeline, or an estimator fit on this
        pipeline's output over ``data`` (workflow/Pipeline.scala § andThen)."""
        if isinstance(nxt, Estimator):
            if data is None:
                raise ValueError(f"and_then({nxt.label}) requires training data")
            featurized = self(data)  # lazy: shares this pipeline's prefix
            est_pipe = Pipeline.from_estimator(nxt, featurized, labels)
            return self.then_pipeline(est_pipe)
        return self.then_pipeline(Pipeline.of(nxt))

    # -------------------------------------------------------- application
    def __call__(self, data):
        if isinstance(data, PipelineDataset):
            g, mapping = data.graph.union(self.graph)
            out_dep = g.sink_dependencies[data.sink]
            g = g.remove_sink(data.sink)
            new_src = mapping[self.source]
            g = g.replace_dependency(new_src, out_dep)
            g = g.remove_source(new_src)
            return PipelineDataset(g, mapping[self.sink])
        if isinstance(data, (Dataset,)) or _is_batchlike(data):
            ds = as_dataset(data)
            g, _ = self.graph.replace_source_with_node(
                self.source, G.DatasetOperator(ds)
            )
            return PipelineDataset(g, self.sink)
        g, _ = self.graph.replace_source_with_node(self.source, G.DatumOperator(data))
        return PipelineDatum(g, self.sink)

    def apply(self, data):
        return self(data)

    def apply_datum(self, x) -> "PipelineDatum":
        """Apply to one datum (arrays are otherwise treated as batches)."""
        g, _ = self.graph.replace_source_with_node(self.source, G.DatumOperator(x))
        return PipelineDatum(g, self.sink)

    # --------------------------------------------------------------- fit
    def fit(self, deadline=None, validate=None) -> "FittedPipeline":
        """Optimize, execute every estimator fit, and return a pure
        transformer pipeline (the reference's ``Pipeline.fit():
        PipelineModel``).  Fits are memoized via the executor, so shared
        prefixes run once.

        ``validate``: run the pre-flight static analyzer
        (``keystone_tpu.analysis``) before any device work — abstract
        shape/dtype propagation over the bound estimator subgraphs,
        fault-plan/breaker/deadline configuration lint, and the
        CSE/cache-signature audit.  Error findings raise
        ``PipelineValidationError`` (the fit never starts); warnings
        log.  Default ``None`` reads ``KEYSTONE_VALIDATE`` (\"1\" = on);
        off, the cost is one env lookup and ``keystone_tpu.analysis``
        is never imported — the solver byte-identity pins ride on this
        inert path.

        ``deadline``: a wall-clock budget for the whole fit — seconds or
        a ``utils.guard.Deadline``.  The executor apportions it over the
        stages (see ``GraphExecutor``): a stage that overruns its share
        raises ``DeadlineExceeded`` inside the stage-retry scope, so
        hung stages are retried, degraded (``optional`` /
        ``with_fallback`` nodes), or fail the fit in bounded time
        instead of stalling it forever.  Default None: no watchdog, no
        threads — the pre-deadline behavior exactly.

        Observability: with ``KEYSTONE_OBS_DIR`` set (or a ledger
        attached via ``obs.ledger.start_run``) the whole fit runs inside
        a ``pipeline.fit`` span — per-stage executor spans, solver
        convergence events, and I/O counters land in the run's JSONL
        ledger, and a metrics snapshot is flushed at fit end so
        ``tools/obs_report.py`` can summarize a run even if the process
        later dies.  Unset, all hooks are inert."""
        if _validate_requested(validate):
            from keystone_tpu.analysis import validate_fit

            validate_fit(self, deadline=deadline)
        from keystone_tpu.obs import ledger as _ledger

        with _ledger.span("pipeline.fit"):
            fitted_pipe = self._fit_inner(deadline=deadline)
        led = _ledger.active()
        if led is not None:
            try:
                import jax

                jax.effects_barrier()  # flush in-flight solver callbacks
            except Exception:
                pass
            led.metrics_snapshot()
        return fitted_pipe

    def _fit_inner(self, deadline=None) -> "FittedPipeline":
        opt = PipelineEnv.get_optimizer()
        g = opt.execute(self.graph)
        g = _auto_out_of_core(g)
        # ONE executor (and one resolved Deadline) for every estimator
        # in the walk: memoized prefixes and the fit budget are shared
        ex = GraphExecutor(g, deadline=deadline)
        fitted: dict = {}
        for n in g.topological_nodes():
            if isinstance(g.operators[n], G.EstimatorOperator):
                expr = ex.execute(n)
                assert isinstance(expr, TransformerExpr)
                fitted[n] = expr.transformer
        for n, t in fitted.items():
            for dep in g.dependents(n):
                if isinstance(dep, G.NodeId) and isinstance(
                    g.operators[dep], G.DelegatingOperator
                ):
                    rest = tuple(d for d in g.dependencies[dep] if d != n)
                    g = g.set_operator(dep, G.TransformerOperator(t))
                    g = g.set_dependencies(dep, rest)
            g = g.remove_node(n)
        g = _prune_unreachable(g, self.sink, keep_sources=(self.source,))
        # Re-fuse: estimator substitution just turned DelegatingOperators
        # (unfusable while the transformer was unknown) into plain device
        # transformers, leaving linear chains the pre-fit fusion pass
        # could not touch.  One more pass means the SCORING path runs as
        # few jit programs as possible — each extra program costs a
        # per-process trace + compile-cache load, the dominant cost of a
        # cold scoring run (BASELINE.md r4 fit-overhead split).
        from keystone_tpu.workflow.optimizer import StageFusionRule

        g = StageFusionRule().apply(g)
        return FittedPipeline(g, self.source, self.sink)

    def freeze(self, validate=None, example=None, plan=None) -> "FrozenApplier":
        """Freeze this pipeline for repeated online application: run the
        whole-pipeline optimizer ONCE now, and return a
        :class:`FrozenApplier` that binds each incoming batch to the
        pre-optimized graph — the serving entry point
        (``keystone_tpu.serve`` builds its micro-batching service on
        this).  Requires an estimator-free pipeline (``fit()`` first).

        ``validate`` runs the pre-flight analyzer in apply mode before
        the serve path primes any bucket program: a statically-broken
        pipeline (mis-shaped stage given ``example``, signature
        collision, bad fault plan) is rejected with
        ``PipelineValidationError`` instead of failing request-by-
        request.  ``example`` (a per-item shape tuple, batch array, or
        Dataset) seeds shape propagation from the open source.  Default
        ``None`` reads ``KEYSTONE_VALIDATE``; off, the path is inert.

        ``plan`` opts into cost-based physical planning
        (``keystone_tpu.planner``): ``True`` samples candidate
        implementations on ``example`` batches and builds a
        :class:`~keystone_tpu.planner.plan.PhysicalPlan` here (installed
        before the optimizer runs, shipped in the applier and its
        artifacts); a ``PhysicalPlan`` instance installs as-is.  Default
        ``None``: no plan — the legacy path, byte-identical."""
        return FrozenApplier(self, validate=validate, example=example, plan=plan)

    def to_dot(
        self, name: str = "pipeline", timings=None, retries=None, findings=None
    ) -> str:
        """Graphviz DOT of this pipeline's DAG (Pipeline.toDOT analogue).
        ``timings``/``retries`` overlay measured per-node seconds and
        retry counts (see ``workflow/viz.py`` — ``ledger_overlay`` folds
        them out of a run ledger); ``findings`` overlays analyzer
        findings (red = error, yellow = warning — ``cli.py check
        --dot``)."""
        from keystone_tpu.workflow.viz import to_dot

        return to_dot(
            self.graph, name, timings=timings, retries=retries,
            findings=findings,
        )

    def __repr__(self):
        return f"Pipeline({self.graph!r})"


class FittedPipeline(Pipeline):
    """An estimator-free pipeline; picklable for save/load
    (the analogue of the reference's serialized PipelineModel +
    workflow/SavedStateLoadRule.scala)."""

    def fit(self, deadline=None, validate=None) -> "FittedPipeline":
        return self

    def _walk_fitted(self, visit=None) -> None:
        """Apply block_on_arrays over every fitted transformer's state —
        the ONE place that knows where fitted state lives (both sync
        paths ride it, so they cannot diverge)."""
        from keystone_tpu.workflow.executor import block_on_arrays

        seen: set = set()
        for op in self.graph.operators.values():
            t = getattr(op, "transformer", None)
            if t is not None:
                block_on_arrays(t, seen, visit=visit)

    def block_until_ready(self) -> "FittedPipeline":
        """Wait for every fitted transformer's device arrays to finish
        computing.  ``fit()`` dispatches solves asynchronously (XLA async
        execution); honest fit-time measurement and safe hand-off to
        other processes require this barrier."""
        self._walk_fitted()
        return self

    def read_back(self):
        """Device→host read of ONE element of every fitted device array;
        returns them as a flat float64 numpy vector.

        The hard sync ``block_until_ready`` cannot give on backends
        whose ``block_until_ready`` returns without draining the stream
        (the axon runtime): an actual transfer forces each array's
        computation — and everything it transitively depends on — to
        completion.  bench.py's fit leg ends with this (plus a
        finiteness check) instead of a probe score, which was charging
        ~5 one-row scoring-program traces (6–7 s/process, measured) to
        fit time.

        Limitation (ADVICE r4): non-numeric leaves that expose
        ``block_until_ready`` but cannot join the batched read fall back
        to ``block_until_ready`` alone, which on the axon backend does
        NOT drain the stream — such exotic leaves (none exist in-repo)
        are not force-synced by this method."""
        import jax.numpy as jnp
        import numpy as np

        leaves = []
        self._walk_fitted(visit=leaves.append)
        heads = []
        for a in leaves:
            try:  # one element per array, gathered ON DEVICE
                h = jnp.ravel(a)[:1]
                if jnp.issubdtype(h.dtype, jnp.floating):
                    # clamp IN THE NATIVE dtype so a finite wide value
                    # stays finite through the f32 transfer; true
                    # non-finites become nan (the caller's finiteness
                    # check must fire on those, and only those)
                    lim = float(jnp.finfo(jnp.float32).max)
                    h = jnp.where(
                        jnp.isfinite(h), jnp.clip(h, -lim, lim), jnp.nan
                    )
                heads.append(h.astype(jnp.float32))
            except TypeError:
                # non-numeric leaf exposing block_until_ready: it cannot
                # join the batched read, but it must still be forced
                a.block_until_ready()
        if not heads:
            # no numeric fitted state — there is nothing a read could
            # force, and returning empty would let a caller treat an
            # unsynced timing as synced
            raise RuntimeError(
                "read_back: fitted pipeline holds no readable device arrays"
            )
        # ONE device→host transfer for the lot: each read rides a
        # host↔device round trip, and a fitted pipeline holds dozens of
        # arrays — per-array np.asarray would pay dozens of RTTs
        return np.asarray(jnp.concatenate(heads), np.float64)

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump(self, f)

    @staticmethod
    def _load_raw(path: str):
        """Unpickle ``path`` → (fitted, saved_config_or_None); accepts the
        bare-pipeline and the fit_or_load {config, pipeline} formats."""
        with open(path, "rb") as f:
            obj = pickle.load(f)
        saved_cfg = None
        if isinstance(obj, dict) and "pipeline" in obj:
            saved_cfg, obj = obj.get("config"), obj["pipeline"]
        if not isinstance(obj, FittedPipeline):
            raise TypeError(f"{path} does not contain a FittedPipeline")
        return obj, saved_cfg

    @staticmethod
    def load(path: str) -> "FittedPipeline":
        return FittedPipeline._load_raw(path)[0]

    @staticmethod
    def fit_or_load(path, build_fn, config=None):
        """Load the fitted pipeline saved at ``path``, or build+fit+save.

        ``build_fn`` is called ONLY when fitting is needed — training-data
        loading belongs inside it, so scoring runs with a saved model skip
        it entirely.  ``config`` (any ==-comparable value, e.g. the app's
        Config dataclass) is persisted alongside the pipeline; loading
        with a config that doesn't match what the model was fitted with
        raises instead of silently reporting stale results.

        Returns ``(fitted, loaded)`` — ``loaded`` is True when the model
        came from disk.
        """
        import os

        if path and os.path.exists(path):
            obj, saved_cfg = FittedPipeline._load_raw(path)
            if config is not None and saved_cfg is None:
                # Legacy bare-pickle save() format: no config was persisted,
                # so the staleness check cannot run — exactly the mismatch
                # it exists to catch. Warn instead of silently accepting.
                import logging

                logging.getLogger(__name__).warning(
                    "saved model at %s has no persisted config (legacy "
                    "save() format); cannot verify it matches the current "
                    "config — re-fit (delete the file) to enable the "
                    "staleness check",
                    path,
                )
            if config is not None and saved_cfg is not None and saved_cfg != config:
                raise ValueError(
                    f"saved model at {path} was fitted with a different "
                    f"config ({saved_cfg!r}); refusing to score with "
                    "mismatched parameters — delete the file or pass a "
                    "matching config"
                )
            return obj, True
        fitted = build_fn().fit().block_until_ready()
        if path:
            with open(path, "wb") as f:
                pickle.dump({"config": config, "pipeline": fitted}, f)
        return fitted, False


class FrozenApplier:
    """A fitted pipeline optimized once and applied many times — the
    online-serving apply path (``keystone_tpu.serve``).

    ``Pipeline(...)``/``PipelineDataset.get()`` re-run the whole-pipeline
    optimizer on every application, which is the right trade for one
    big offline batch and the wrong one for a stream of small requests:
    the optimizer walk is pure host-side overhead once the graph is
    fitted and frozen.  Freezing runs the optimizer ONCE over the
    unbound graph; each call then binds the batch to the pre-optimized
    graph (persistent graphs make the bind a cheap copy) and runs a
    fresh :class:`GraphExecutor` walk over it.

    Compiled-program reuse: the per-transformer jitted apply caches
    (``workflow/transformer.py``) key on the SAME transformer instances
    on every call, so as long as callers keep the input shape set finite
    — the serve batcher's padding-bucket discipline
    (:func:`~keystone_tpu.workflow.transformer.iter_row_chunks` pads
    every flush up to a fixed bucket size) — every request after the
    first per bucket runs entirely from cache-hot programs.

    ``deadline`` per call plumbs into the executor exactly like
    ``Pipeline.fit(deadline=…)``: stages run under apportioned
    watchdogs, and ``optional``/``with_fallback`` nodes degrade instead
    of failing the batch — graceful degradation applies on the serve
    path too.

    **AOT artifacts** — :meth:`export_artifacts` lowers the whole
    frozen apply at each padding-bucket shape to a serialized
    ``jax.export`` program (the fitted weights ride along as program
    constants), and :meth:`install_artifacts` registers the
    deserialized programs so calls at exactly those shapes skip the
    optimizer-bind + per-stage trace/lower entirely — the cold-start,
    hot-swap, and supervisor-heal paths stop paying compile time.
    With nothing installed the cost is one empty-dict check per call
    (the pre-artifact path, byte-identical)."""

    def __init__(self, pipeline: "Pipeline", validate=None, example=None,
                 plan=None):
        for op in pipeline.graph.operators.values():
            if isinstance(op, G.EstimatorOperator):
                raise TypeError(
                    f"cannot freeze a pipeline with unfitted estimator "
                    f"{op.label()!r}; call fit() first"
                )
        if _validate_requested(validate):
            from keystone_tpu.analysis import validate_freeze

            validate_freeze(pipeline, example=example)
        #: the cost-based PhysicalPlan (keystone_tpu.planner), or None.
        #: Built/installed BEFORE the optimizer executes so planning
        #: rules (fused-FV) consult it; plain data, so it pickles with
        #: the applier (replica clones) and rides export_artifacts.
        self.plan = None
        if plan is not None and plan is not False:
            from keystone_tpu import planner

            if plan is True:
                self.plan = planner.build_plan(pipeline, example=example)
            else:
                self.plan = plan
            planner.install_plan(self.plan, source="freeze")
        opt = PipelineEnv.get_optimizer()
        self.graph = opt.execute(pipeline.graph)
        self.source = pipeline.source
        self.sink = pipeline.sink
        #: the PRE-optimizer pipeline: the artifact signature hashes
        #: this (the pickled deploy payload) — the optimized graph is
        #: process-local (profiling-driven rules place by timings)
        self._frozen_from = pipeline
        #: installed AOT bucket programs: (shape, dtype str) -> callable.
        #: Unpicklable jitted callables — stripped by __getstate__.
        self._bucket_programs: dict = {}
        self._artifact_meta: dict = {}
        #: True when any stage declares optional/with_fallback: such
        #: pipelines keep the executor walk for deadline-carrying calls
        #: (a monolithic AOT program cannot degrade mid-run)
        self._degradable = any(
            getattr(getattr(op, "transformer", None), "optional", False)
            or getattr(getattr(op, "transformer", None), "fallback", None)
            is not None
            for op in self.graph.operators.values()
        )

    def __getstate__(self):
        state = dict(self.__dict__)
        # jitted callables are unpicklable; a cloned applier re-installs
        # from the bundle (ReplicaPool keeps it) or recompiles
        state["_bucket_programs"] = {}
        state["_artifact_meta"] = {}
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        # appliers pickled by older code lack the artifact fields
        self.__dict__.setdefault("_bucket_programs", {})
        self.__dict__.setdefault("_artifact_meta", {})
        self.__dict__.setdefault("_frozen_from", None)
        self.__dict__.setdefault("_degradable", True)
        self.__dict__.setdefault("plan", None)

    def __call__(self, data, deadline=None) -> Dataset:
        """Apply the frozen graph to one batch (a Dataset or batch-like
        array); returns the result Dataset.  ``deadline``: wall-clock
        budget for this batch, apportioned per stage by the executor.

        When an AOT bucket program is installed for the batch's exact
        shape/dtype (see :meth:`install_artifacts`), it runs instead of
        the executor walk — same math, one pre-lowered program.  A
        deadline-carrying call keeps the deadline contract: on a
        pipeline that declares degradation it takes the walk (per-stage
        watchdogs and substitutes need stage boundaries); otherwise the
        program runs under one whole-batch ``guard.run_with_deadline``
        watchdog, so an overrun still raises the typed
        ``DeadlineExceeded`` the walk would have.  A bucket program
        that fails at run time falls back to the walk for good and is
        counted (``serve.artifact_fallbacks``)."""
        ds = as_dataset(data)
        if (
            self._bucket_programs
            and not isinstance(ds, StreamDataset)
            and not ds.is_host
            and ds.mask is None
        ):
            # StreamDatasets are excluded BEFORE touching .array: an
            # out-of-core stream's .array materializes every batch, and
            # the walk streams them — shape-keyed programs can never
            # match a stream anyway
            if deadline is None or not self._degradable:
                key = (tuple(ds.array.shape), str(ds.array.dtype))
                fn = self._bucket_programs.get(key)
                if fn is not None:
                    from keystone_tpu.utils import guard

                    try:
                        if deadline is None:
                            out = fn(ds.array)
                        else:
                            # the walk apportions the budget per stage;
                            # a monolith gets it whole — an overrun is
                            # the same typed OSError either way
                            out = guard.run_with_deadline(
                                lambda: fn(ds.array),
                                guard.as_deadline(deadline),
                                site="serve.artifact",
                            )
                        return Dataset(out, n=ds.n, shard=False)
                    except guard.DeadlineExceeded:
                        # a genuine timeout, not a broken program: the
                        # caller's deadline contract fires; keep the
                        # program for the next flush
                        raise
                    except Exception as e:
                        # one failed program must not fail serving (or
                        # re-pay a doomed call per flush): drop it and
                        # walk — the compile tier takes over
                        self._bucket_programs.pop(key, None)
                        from keystone_tpu.obs import metrics

                        metrics.inc("serve.artifact_fallbacks")
                        import logging

                        logging.getLogger(__name__).warning(
                            "AOT bucket program %s failed (%s: %s); "
                            "falling back to the executor walk",
                            key,
                            type(e).__name__,
                            e,
                        )
        g, _ = self.graph.replace_source_with_node(
            self.source, G.DatasetOperator(ds)
        )
        ex = GraphExecutor(g, deadline=deadline)
        expr = ex.execute(g.sink_dependencies[self.sink])
        if not isinstance(expr, DatasetExpr):
            raise TypeError(
                f"frozen apply produced {type(expr).__name__}, expected dataset"
            )
        return expr.dataset

    # ------------------------------------------------------ AOT artifacts
    ARTIFACT_FORMAT = 1

    def fingerprint(self) -> str:
        """The pipeline signature hash artifacts are keyed by
        (``utils.hashing.pipeline_fingerprint`` of the pre-optimizer
        pipeline — structure + every fitted weight's bytes)."""
        if self._frozen_from is None:
            raise RuntimeError(
                "this FrozenApplier was pickled by an older version and "
                "lost its source pipeline; re-freeze to use artifacts"
            )
        from keystone_tpu.utils.hashing import pipeline_fingerprint

        return pipeline_fingerprint(self._frozen_from)

    def _bucket_callable(self):
        """The whole frozen apply as ONE traceable function of the
        padded batch — what gets lowered per bucket.  Host stages,
        data-dependent Python, and anything else untraceable raise at
        trace time; callers treat that as \"this pipeline has no
        artifact tier\" and ride the compile ladder."""
        graph, source, sink = self.graph, self.source, self.sink

        def run(x):
            ds = Dataset(x, n=x.shape[0], shard=False)
            g, _ = graph.replace_source_with_node(
                source, G.DatasetOperator(ds)
            )
            ex = GraphExecutor(g)
            expr = ex.execute(g.sink_dependencies[sink])
            if not isinstance(expr, DatasetExpr):
                raise TypeError(
                    f"frozen apply produced {type(expr).__name__}, "
                    "expected dataset"
                )
            return expr.dataset.array

        return run

    @staticmethod
    def _bucket_entry_key(rows: int) -> str:
        return f"b{int(rows):05d}"

    def export_artifacts(
        self, example=None, buckets=(8, 16, 32), item_shape=None, dtype=None
    ) -> dict:
        """Lower the frozen apply at every padding-bucket shape and
        serialize the programs with ``jax.export``; returns the artifact
        bundle ``{"manifest": {...}, "blobs": {entry: bytes}}`` the
        registry stores next to ``model.pkl``.

        Keyed by bucket shape/dtype, jax version, backend platform, and
        the pipeline's signature hash (:meth:`fingerprint`) — any skew
        at install time falls through to the compile ladder instead of
        replaying a stale program.  Fitted weights are embedded as
        program constants, so blobs scale with model size (they live
        next to the model blob, which carries the same bytes).

        ``example``: one datum (array) the per-item shape/dtype are read
        from; or pass ``item_shape``/``dtype`` explicitly."""
        import jax
        from jax import export as jexport

        import numpy as np

        if example is not None:
            ex = np.asarray(example)
            item_shape = tuple(ex.shape)
            dtype = ex.dtype
        if item_shape is None:
            raise ValueError(
                "export_artifacts needs the per-item shape: pass "
                "example=<one datum> or item_shape="
            )
        dtype = np.dtype(dtype if dtype is not None else np.float32)
        buckets = sorted({int(b) for b in buckets})
        if not buckets or min(buckets) < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        blobs: dict = {}
        entries: dict = {}
        platforms: set = set()
        fn = self._bucket_callable()
        for b in buckets:
            shape = (b,) + tuple(item_shape)
            exported = jexport.export(jax.jit(fn))(
                jax.ShapeDtypeStruct(shape, dtype)
            )
            platforms.update(exported.platforms)
            key = self._bucket_entry_key(b)
            blobs[key] = bytes(exported.serialize())
            entries[key] = {"rows": b, "file": f"{key}.hlo"}
        # the artifact ladder's remaining cold rung: a deserialized AOT
        # module still pays one BACKEND compile on first call.  With a
        # persistent compile cache active, run that compile NOW — on
        # the REHYDRATED program, so the cache key matches exactly what
        # a deploying host's install+first-call mints — and ship the
        # minted cache entries in the bundle.  seed_compile_cache()
        # installs them on the deploy host, whose first deploy then
        # skips even the backend compile.  Best-effort: no active
        # cache, no shipped entries.
        from keystone_tpu.utils.compile_cache import (
            collect_new_entries,
            snapshot_cache_entries,
        )

        before = snapshot_cache_entries()
        if before is not None:
            for b in buckets:
                key = self._bucket_entry_key(b)
                shape = (b,) + tuple(item_shape)
                try:
                    rehydrated = jexport.deserialize(bytearray(blobs[key]))
                    jax.jit(rehydrated.call).lower(
                        jax.ShapeDtypeStruct(shape, dtype)
                    ).compile()
                except Exception as e:
                    import logging

                    logging.getLogger(__name__).warning(
                        "cache pre-seed compile for bucket %d failed "
                        "(%s: %s); that rung ships without entries",
                        b,
                        type(e).__name__,
                        e,
                    )
            for i, (name, data) in enumerate(
                sorted(collect_new_entries(before).items())
            ):
                ckey = f"cache{i:03d}"
                blobs[ckey] = data
                entries[ckey] = {
                    "kind": "compile_cache",
                    "file": f"{ckey}.bin",
                    "name": name,
                }
        manifest = {
            "format": FrozenApplier.ARTIFACT_FORMAT,
            "jax_version": jax.__version__,
            "platforms": sorted(platforms),
            "signature": self.fingerprint(),
            "item_shape": list(item_shape),
            "dtype": str(dtype),
            "buckets": buckets,
            "entries": entries,
        }
        if getattr(self, "plan", None) is not None:
            # the PhysicalPlan ships INSIDE the manifest: it rides the
            # registry's blob-before-pointer publish (MANIFEST.json is
            # written last) and re-installs on every artifact install —
            # clone, worker spawn, swap, heal
            manifest["plan"] = self.plan.to_dict()
        return {"manifest": manifest, "blobs": blobs}

    def install_artifacts(
        self,
        bundle,
        device=None,
        signature=None,
        strict: bool = False,
        program_cache: Optional[dict] = None,
    ) -> int:
        """Deserialize an artifact bundle and register its bucket
        programs; returns how many were installed.

        The fallback ladder's first rung: ANY mismatch — format drift,
        jax version skew, wrong backend, signature drift, a corrupt
        blob — skips the offending artifact (counted as
        ``serve.artifact_fallbacks``) and leaves the compile tiers to
        serve, instead of failing the deploy.  ``strict=True`` raises
        instead (forensics).  ``device``: pin the programs' compilation
        to one device (the replica-fleet placement discipline);
        ``signature``: the expected pipeline hash, precomputed by the
        caller (default: :meth:`fingerprint`, which reads every fitted
        weight once).  ``program_cache``: a caller-owned dict keyed by
        (bundle signature, entry, device) of already-deserialized
        programs — the ReplicaPool shares one across replica builds and
        supervisor heals, so a replacement replica re-installs in
        microseconds instead of re-deserializing (compile time must
        not become recovery time); the programs are immutable pure
        functions, safe to share across worker generations."""
        import logging

        import jax
        from jax import export as jexport

        from keystone_tpu.obs import metrics

        log = logging.getLogger(__name__)

        def reject(why: str) -> int:
            if strict:
                raise ArtifactMismatch(why)
            metrics.inc("serve.artifact_fallbacks")
            log.warning("AOT artifacts rejected (%s); will compile", why)
            return 0

        manifest = (bundle or {}).get("manifest") or {}
        blobs = (bundle or {}).get("blobs") or {}
        if manifest.get("format") != FrozenApplier.ARTIFACT_FORMAT:
            return reject(f"unknown artifact format {manifest.get('format')!r}")
        if manifest.get("jax_version") != jax.__version__:
            return reject(
                f"jax version skew (artifact {manifest.get('jax_version')}, "
                f"running {jax.__version__})"
            )
        backend = jax.default_backend()
        if backend not in (manifest.get("platforms") or ()):
            return reject(
                f"backend skew (artifact {manifest.get('platforms')}, "
                f"running {backend!r})"
            )
        want = signature if signature is not None else self.fingerprint()
        if manifest.get("signature") != want:
            return reject(
                "pipeline signature drift (artifact "
                f"{manifest.get('signature')!r}, pipeline {want!r})"
            )
        plan_dict = manifest.get("plan")
        if plan_dict is not None:
            # past the reject ladder the bundle IS this pipeline's: its
            # plan is re-installed verbatim so a cloned replica / spawned
            # worker / swapped or healed fleet serves the planned
            # physical configuration, not whatever the env says here
            try:
                from keystone_tpu import planner

                self.plan = planner.PhysicalPlan.from_dict(plan_dict)
                planner.install_plan(self.plan, source="artifacts")
            except Exception as e:
                if strict:
                    raise ArtifactMismatch(f"plan failed to install: {e}")
                log.warning("shipped plan failed to install (%s)", e)
        item_shape = tuple(int(d) for d in manifest.get("item_shape") or ())
        dtype = str(manifest.get("dtype") or "float32")
        installed = 0
        for key, ent in (manifest.get("entries") or {}).items():
            if ent.get("kind") == "compile_cache" or "rows" not in ent:
                # shipped persistent-compile-cache entries ride the
                # bundle but are installed by seed_compile_cache(), not
                # registered as bucket programs
                continue
            cache_key = (manifest.get("signature"), key, device)
            call = (
                program_cache.get(cache_key)
                if program_cache is not None
                else None
            )
            if call is None:
                blob = blobs.get(key)
                if blob is None:
                    continue  # load-time skip already counted by the reader
                try:
                    exported = jexport.deserialize(bytearray(blob))
                    call = jax.jit(exported.call)
                except Exception as e:
                    if strict:
                        raise ArtifactMismatch(
                            f"artifact {key} failed to deserialize: {e}"
                        )
                    metrics.inc("serve.artifact_fallbacks")
                    log.warning(
                        "AOT artifact %s failed to deserialize (%s: %s); "
                        "that bucket will compile",
                        key,
                        type(e).__name__,
                        e,
                    )
                    continue
                if device is not None:
                    call = _pinned_to_device(call, device)
                if program_cache is not None:
                    program_cache[cache_key] = call
            shape = (int(ent["rows"]),) + item_shape
            self._bucket_programs[(shape, dtype)] = call
            self._artifact_meta[(shape, dtype)] = {
                "rows": int(ent["rows"]),
                "jax_version": manifest["jax_version"],
            }
            installed += 1
        return installed

    def has_bucket_program(self, shape, dtype) -> bool:
        import numpy as np

        return (tuple(shape), str(np.dtype(dtype))) in self._bucket_programs

    def installed_buckets(self) -> int:
        """How many AOT bucket programs this applier currently holds."""
        return len(self._bucket_programs)


class ArtifactMismatch(RuntimeError):
    """An AOT artifact bundle does not match this process/pipeline
    (format, jax version, backend, or pipeline signature) — raised only
    under ``install_artifacts(strict=True)``; the serving path counts
    the mismatch and falls through to the compile ladder instead."""


def _pinned_to_device(fn, device):
    """Wrap an AOT program so its (first-call) compilation and constants
    land on ``device`` — the replica fleet's one-replica-one-device
    placement discipline; without this every replica's artifact program
    would compute on the default device."""
    import jax

    def call(x):
        with jax.default_device(device):
            return fn(x)

    return call


class PreflightOOMError(RuntimeError):
    """``fit()`` refused to start: the predicted resident footprint
    exceeds the device's HBM limit and auto-spill is disabled
    (``KEYSTONE_AUTO_SPILL=0``).  The message carries the predicted
    bytes and the ``--stream`` pointer."""


def _auto_out_of_core(g):
    """No ``fit()`` may OOM the chip (VERDICT r4 item 2; the reference's
    AutoCacheRule owns memory decisions so the user doesn't —
    workflow/AutoCacheRule.scala).

    The profiled materialization pass already priced every shared output
    against the HBM budget; this pre-flight compares its estimate (plus
    the in-memory source bytes) against the device limit.  The estimate
    is a STRUCTURAL UNDER-count — unshared memoized outputs, the
    gathered solver features, solver state, and in-program transients
    (e.g. the FV γ tensor) ride on top of it.  Measured calibration
    (r5, this chip): the n=16384 north-star fit OOMs 16 GB HBM at a
    predicted 9.1 GB (≥1.8× under), while n=8192 (predicted 4.5 GB)
    completes in-memory — hence the 0.45 default fraction, which
    separates those two cases on a 16 GB device.  Over budget, the
    large device-array sources are
    converted to StreamDatasets over the same rows — downstream
    featurization then streams batch-by-batch and the solvers spill
    features to a FeatureBlockStore, the standard out-of-core path the
    ``--stream`` apps exercise (tests/test_stream_e2e.py asserts
    stream == in-memory bit-parity).  ``KEYSTONE_AUTO_SPILL=0`` refuses
    instead with the predicted footprint (PreflightOOMError)."""
    import logging

    import numpy as np

    from keystone_tpu.workflow import profiling
    from keystone_tpu.workflow.dataset import StreamDataset

    sources = []
    for n, op in g.operators.items():
        if isinstance(op, G.DatasetOperator):
            ds = as_dataset(op.dataset)
            if (
                not isinstance(ds, StreamDataset)
                and not ds.is_host
                and ds.mask is None
            ):
                sources.append((n, ds, ds.array.nbytes))
    source_bytes = sum(b for _, _, b in sources)
    shared_bytes = int(profiling.last_footprint.get("shared_bytes", 0))
    # consume-once: the estimate belongs to THIS fit's materialize pass;
    # a later fit whose pass takes the structural fallback must not
    # inherit it (profiling.py clears at pass start too)
    profiling.last_footprint.clear()
    predicted = source_bytes + shared_bytes
    frac = float(os.environ.get("KEYSTONE_OOC_FRACTION", "0.45"))
    limit = profiling.device_hbm_budget(fraction=frac)
    if predicted <= limit or not sources:
        return g
    if os.environ.get("KEYSTONE_AUTO_SPILL", "1") == "0":
        raise PreflightOOMError(
            f"fit() pre-flight: predicted resident footprint ~"
            f"{predicted / 1e9:.2f} GB (sources {source_bytes / 1e9:.2f} GB "
            f"+ shared featurized outputs {shared_bytes / 1e9:.2f} GB) "
            f"exceeds {frac:.0%} of device HBM ({limit / 1e9:.2f} GB). "
            "Load the training data as a stream (app flag --stream / "
            "--out-of-core, or build with a StreamDataset) so features "
            "spill to the disk block store, or re-enable auto-spill "
            "(unset KEYSTONE_AUTO_SPILL)."
        )
    # 512-row spill batches: the auto-spill stream pays a tunnel RTT per
    # batch per stage per sweep — 64-row batches made the n=16384 spill
    # fit RTT-bound (measured >35 min); 512 cuts the dispatch count 8×
    # while the largest per-batch transient (512×361×128 f32 SIFT
    # descriptors ≈ 94 MB) stays far under any HBM pressure
    batch = int(os.environ.get("KEYSTONE_SPILL_BATCH", "512"))
    biggest = max(b for _, _, b in sources)
    for n, ds, b in sources:
        # spill the batch-carrying sources; parameter-sized datasets
        # (labels, constants) stay resident — streaming them buys no
        # HBM and some estimators require in-memory labels
        if b < max(1 << 20, biggest // 8):
            continue
        arr = np.asarray(ds.array[: ds.n])  # one device→host read

        def batches(_arr=arr):
            for i in range(0, _arr.shape[0], batch):
                yield _arr[i : i + batch]

        stream = StreamDataset(batches, n=ds.n, name=ds.name)
        g = g.set_operator(n, G.DatasetOperator(stream))
        logging.getLogger(__name__).warning(
            "fit() pre-flight: predicted footprint %.2f GB exceeds %.2f GB "
            "HBM budget; source %s (%.2f GB) converted to a stream — "
            "features will spill to the disk block store "
            "(KEYSTONE_AUTO_SPILL=0 to refuse instead)",
            predicted / 1e9,
            limit / 1e9,
            ds.name or "dataset",
            b / 1e9,
        )
    return g


def fit_relevant_config(config, exclude=()):
    """App Config dataclass → dict of FIT-relevant fields for
    ``fit_or_load``'s staleness check.

    Eval-only knobs must not invalidate a saved model — fitting once and
    scoring new test sets later is the feature's purpose — so fields that
    only affect evaluation inputs are dropped: the model path itself,
    test-set paths, and view-patch size.  Anything that changes the
    FITTED ARTIFACT (featurizer params, solver params, train paths,
    ImageNet's augmented_eval — which persists a scorer instead of a
    classifier) stays.  ``exclude`` adds app-specific eval-only fields.
    """
    import dataclasses

    d = dataclasses.asdict(config)
    eval_only = {
        "model_path",
        "test_path",
        "test_features_path",
        "test_labels_path",
        "view_patch",
        # execution strategy, not model identity: streaming the same
        # data fits the same model (to fp tolerance), so a saved model
        # stays valid across in-memory/out-of-core runs
        "stream",
        "stream_batch_size",
    } | set(exclude)
    for k in eval_only:
        d.pop(k, None)
    return d


class PipelineDataset:
    """Lazy result of applying a pipeline to a dataset
    (workflow/Pipeline.scala § PipelineDataset).  ``get()`` triggers
    optimize + execute; the result is cached."""

    def __init__(self, graph: G.Graph, sink: G.SinkId):
        self.graph = graph
        self.sink = sink
        self._result: Optional[Dataset] = None

    def get(self, deadline=None) -> Dataset:
        """Trigger optimize + execute (cached).  ``deadline``: wall-clock
        budget for the apply, apportioned per stage by the executor —
        the scoring-path twin of ``Pipeline.fit(deadline=…)``."""
        if self._result is None:
            opt = PipelineEnv.get_optimizer()
            g = opt.execute(self.graph)
            ex = GraphExecutor(g, deadline=deadline)
            expr = ex.execute(g.sink_dependencies.get(self.sink, self.sink))
            if not isinstance(expr, DatasetExpr):
                raise TypeError(f"sink produced {type(expr).__name__}, expected dataset")
            self._result = expr.dataset
        return self._result

    def numpy(self):
        return self.get().numpy()


class PipelineDatum:
    """Lazy single-datum result (workflow/Pipeline.scala § PipelineDatum)."""

    def __init__(self, graph: G.Graph, sink: G.SinkId):
        self.graph = graph
        self.sink = sink
        self._result = None
        self._done = False

    def get(self, deadline=None):
        if not self._done:
            g = PipelineEnv.get_optimizer().execute(self.graph)
            ex = GraphExecutor(g, deadline=deadline)
            expr = ex.execute(g.sink_dependencies.get(self.sink, self.sink))
            if not isinstance(expr, DatumExpr):
                raise TypeError(f"sink produced {type(expr).__name__}, expected datum")
            self._result = expr.value
            self._done = True
        return self._result


# ----------------------------------------------------------------- helpers
def _splice_input(g: G.Graph, data):
    """Attach ``data`` (literal dataset or lazy PipelineDataset graph) to
    ``g``; returns (graph, dependency id of the data's value)."""
    if isinstance(data, PipelineDataset):
        g2, mapping = g.union(data.graph)
        dep = g2.sink_dependencies[mapping[data.sink]]
        g2 = g2.remove_sink(mapping[data.sink])
        return g2, dep
    ds = as_dataset(data)
    g2, node = g.add_node(G.DatasetOperator(ds), ())
    return g2, node


def _prune_unreachable(
    g: G.Graph, sink: G.SinkId, keep_sources: Sequence[G.SourceId]
) -> G.Graph:
    keep = set(keep_sources)
    keep.add(g.sink_dependencies[sink])
    keep.update(g.ancestors(g.sink_dependencies[sink]))
    for n in list(g.operators):
        if n not in keep:
            g = g.remove_node(n)
    for s in list(g.sources):
        if s not in keep:
            g = g.remove_source(s)
    for k in list(g.sink_dependencies):
        if k != sink:
            g = g.remove_sink(k)
    return g


def _is_batchlike(x) -> bool:
    import numpy as np

    return isinstance(x, (list, tuple)) or (hasattr(x, "ndim") and x.ndim >= 1)
