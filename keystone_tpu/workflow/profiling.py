"""Profiling-driven materialization (the AutoCacheRule proper).

Reference: workflow/AutoCacheRule.scala — estimates per-node output size
and compute time by running nodes on sampled partitions, then greedily
places caches under a cluster-memory budget.

TPU version: the budget is HBM (≈16 GB/chip — far tighter than a Spark
cluster's aggregate RAM, SURVEY.md §7 hard part e), and the decision is
materialize-vs-recompute: shared node outputs that fit keep an explicit
materialization barrier (Cacher); shared outputs that don't fit are
flagged no-memoize so the executor recomputes them per consumer instead
of pinning them in HBM.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, Optional

import numpy as np

from keystone_tpu.workflow import graph as G
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.optimizer import Rule, _truncate_datasets
from keystone_tpu.workflow.transformer import Cacher

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class NodeProfile:
    """Measured on a sample, extrapolated to the full dataset."""

    seconds: float
    output_bytes: int
    scale: float  # full_n / sample_n extrapolation factor

    @property
    def full_bytes(self) -> int:
        return int(self.output_bytes * self.scale)

    @property
    def full_seconds(self) -> float:
        return self.seconds * self.scale


def profile_graph(graph: G.Graph, sample_size: int = 64) -> Dict[G.NodeId, NodeProfile]:
    """Run every reachable transformer node on truncated dataset literals,
    recording wall time and output size (the reference's sampling pass)."""
    from keystone_tpu.workflow.executor import DatasetExpr, GraphExecutor

    full_n = max(
        (
            op.dataset.n if isinstance(op.dataset, Dataset) else len(op.dataset)
            for op in graph.operators.values()
            if isinstance(op, G.DatasetOperator)
        ),
        default=1,
    )
    truncated = _truncate_datasets(graph, sample_size)
    ex = GraphExecutor(truncated, profile=True)
    profiles: Dict[G.NodeId, NodeProfile] = {}
    for n in truncated.topological_nodes():
        op = truncated.operators[n]
        if not isinstance(op, (G.TransformerOperator, G.GatherOperator)):
            continue
        try:
            expr = ex.execute(n)
        except Exception as e:  # profiling is best-effort, like upstream
            logger.debug("profiling failed at %s: %s", op.label(), e)
            continue
        nbytes = 0
        sample_n = 1
        if isinstance(expr, DatasetExpr) and not expr.dataset.is_host:
            arr = expr.dataset.array
            nbytes = int(np.prod(arr.shape)) * arr.dtype.itemsize
            sample_n = max(expr.dataset.n, 1)
        profiles[n] = NodeProfile(
            seconds=ex.timings.get(n, 0.0),
            output_bytes=nbytes,
            scale=max(full_n / sample_n, 1.0),
        )
    return profiles


class ProfilingAutoCacheRule(Rule):
    """Greedy cache placement under an HBM byte budget."""

    name = "ProfilingAutoCache"

    def __init__(self, budget_bytes: int = 8 << 30, sample_size: int = 64):
        self.budget_bytes = int(budget_bytes)
        self.sample_size = int(sample_size)

    def apply(self, graph: G.Graph) -> G.Graph:
        profiles = profile_graph(graph, self.sample_size)
        shared = [
            n
            for n in graph.topological_nodes()
            if isinstance(graph.operators.get(n), (G.TransformerOperator, G.GatherOperator))
            and len([d for d in graph.dependents(n) if not isinstance(d, G.SinkId)]) > 1
        ]
        # most compute saved per byte pinned, first
        shared.sort(
            key=lambda n: (
                -(profiles[n].full_seconds / max(profiles[n].full_bytes, 1))
                if n in profiles
                else 0.0
            )
        )
        remaining = self.budget_bytes
        for n in shared:
            prof = profiles.get(n)
            cost = prof.full_bytes if prof else 0
            if cost <= remaining:
                remaining -= cost
                graph = _insert_cacher(graph, n)
            else:
                op = graph.operators[n]
                if isinstance(op, G.TransformerOperator):
                    logger.info(
                        "over HBM budget: %s (%.1f MB) will recompute per consumer",
                        op.label(),
                        cost / 1e6,
                    )
                    # never mutate shared Operator instances (graphs share
                    # them persistent-structure style): flag a fresh copy
                    flagged = G.TransformerOperator(op.transformer)
                    flagged.no_memoize = True
                    graph = graph.set_operator(n, flagged)
        return graph


def _insert_cacher(graph: G.Graph, n: G.NodeId) -> G.Graph:
    deps_on_n = [d for d in graph.dependents(n) if isinstance(d, G.NodeId)]
    already = any(
        isinstance(graph.operators.get(d), G.TransformerOperator)
        and isinstance(graph.operators[d].transformer, Cacher)
        for d in deps_on_n
    )
    if already:
        return graph
    graph, cache_node = graph.add_node(G.TransformerOperator(Cacher()), (n,))
    for d in deps_on_n:
        if d != cache_node:
            graph = graph.set_dependencies(
                d, tuple(cache_node if x == n else x for x in graph.dependencies[d])
            )
    return graph
