"""Profiling-driven materialization (the AutoCacheRule proper).

Reference: workflow/AutoCacheRule.scala — estimates per-node output size
and compute time by running nodes on sampled partitions, then greedily
places caches under a cluster-memory budget.

TPU version: the budget is HBM (≈16 GB/chip — far tighter than a Spark
cluster's aggregate RAM, SURVEY.md §7 hard part e), and the decision is
materialize-vs-recompute: shared node outputs that fit keep an explicit
materialization barrier (Cacher); shared outputs that don't fit are
flagged no-memoize so the executor recomputes them per consumer instead
of pinning them in HBM.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, Optional

import numpy as np

from keystone_tpu.workflow import graph as G
from keystone_tpu.workflow.dataset import Dataset
from keystone_tpu.workflow.optimizer import Rule, _truncate_datasets
from keystone_tpu.workflow.transformer import Cacher

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class NodeProfile:
    """Measured on a sample, extrapolated to the full dataset."""

    seconds: float
    output_bytes: int
    scale: float  # full_n / sample_n extrapolation factor
    hlo_seconds: Optional[float] = None  # full-scale roofline estimate

    @property
    def full_bytes(self) -> int:
        return int(self.output_bytes * self.scale)

    @property
    def full_seconds(self) -> float:
        # the static estimate, when available, is already at full scale and
        # immune to wall-clock noise / sub-sample fixed overheads
        if self.hlo_seconds is not None:
            return self.hlo_seconds
        return self.seconds * self.scale


# roofline peaks (f32 flops/s, HBM bytes/s) used to turn compiled HLO
# counters into a time estimate.  Only the *relative* ranking across nodes
# matters for cache placement, but the constants are real hardware numbers.
_ROOFLINE_PEAKS = {
    "tpu": (4.9e13, 8.1e11),  # TPU v5 lite: ~197 Tf/s bf16 → ~49 Tf/s f32; 819 GB/s
    "axon": (4.9e13, 8.1e11),
    "cpu": (5e10, 3e10),
}


def hlo_stage_cost(fn, *avals) -> Optional[dict]:
    """Compile ``fn`` for the given ShapeDtypeStructs and read XLA's cost
    analysis (SURVEY.md §5: "per-stage cost model from compiled HLO cost
    analysis instead of sampling runs").  Returns {'flops', 'bytes',
    'seconds_est'} or None when analysis is unavailable.

    Nothing executes and no buffers are allocated — this prices a stage at
    *full* batch size without paying for a full-size run."""
    import jax

    try:
        compiled = jax.jit(fn).lower(*avals).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0) or 0.0)
        byts = float(ca.get("bytes accessed", 0.0) or 0.0)
        if flops <= 0.0 and byts <= 0.0:
            return None
        platform = jax.devices()[0].platform
        peak_f, peak_b = _ROOFLINE_PEAKS.get(platform, _ROOFLINE_PEAKS["cpu"])
        return {
            "flops": flops,
            "bytes": byts,
            "seconds_est": max(flops / peak_f, byts / peak_b),
        }
    except Exception as e:  # cost analysis is best-effort
        logger.debug("hlo cost analysis failed: %s", e)
        return None


def profile_graph(
    graph: G.Graph,
    sample_size: int = 64,
    static_cost: bool = False,
    targets=None,
) -> Dict[G.NodeId, NodeProfile]:
    """Run every reachable transformer node on truncated dataset literals,
    recording wall time and output size (the reference's sampling pass).

    With ``static_cost=True``, additionally price each device transformer
    at FULL batch size from its compiled HLO (hlo_stage_cost) — sampled
    runs still provide shapes and output sizes, but the seconds estimate
    comes from XLA's own cost counters instead of extrapolated wall time.

    ``targets`` restricts profiling to a node subset (their sampled
    ancestors still execute, memoized, to produce inputs).  The cache rule
    passes the SHARED nodes here: they are the only ones whose profiles
    the placement decision reads, and pricing only them avoids compiling
    every stage at full batch size and avoids sampled execution of
    subgraphs (e.g. the solver's) that no shared output depends on —
    measured 4 shared of 23 profilable on the north-star fit, where the
    unrestricted pass was ~60% of total fit wall-clock."""
    from keystone_tpu.workflow.executor import DatasetExpr, GraphExecutor

    full_n = max(
        (
            op.dataset.n if isinstance(op.dataset, Dataset) else len(op.dataset)
            for op in graph.operators.values()
            if isinstance(op, G.DatasetOperator)
        ),
        default=1,
    )
    truncated = _truncate_datasets(graph, sample_size)
    ex = GraphExecutor(truncated, profile=True)
    profiles: Dict[G.NodeId, NodeProfile] = {}
    for n in truncated.topological_nodes():
        op = truncated.operators[n]
        if not isinstance(op, (G.TransformerOperator, G.GatherOperator)):
            continue
        if targets is not None and n not in targets:
            continue
        try:
            expr = ex.execute(n)
        except Exception as e:  # profiling is best-effort, like upstream
            logger.debug("profiling failed at %s: %s", op.label(), e)
            continue
        nbytes = 0
        sample_n = 1
        if isinstance(expr, DatasetExpr) and not expr.dataset.is_host:
            arr = expr.dataset.array
            nbytes = int(np.prod(arr.shape)) * arr.dtype.itemsize
            sample_n = max(expr.dataset.n, 1)
        hlo_seconds = None
        if static_cost:
            hlo_seconds = _static_node_seconds(truncated, ex, n, op, full_n)
        profiles[n] = NodeProfile(
            seconds=ex.timings.get(n, 0.0),
            output_bytes=nbytes,
            scale=max(full_n / sample_n, 1.0),
            hlo_seconds=hlo_seconds,
        )
    return profiles


def _static_node_seconds(graph: G.Graph, ex, n: G.NodeId, op, full_n: int):
    """Full-scale roofline estimate for one transformer node, from the
    sampled input's shape with the batch axis widened to full_n."""
    import jax

    if not isinstance(op, G.TransformerOperator):
        return None
    from keystone_tpu.workflow.executor import DatasetExpr

    deps = graph.dependencies.get(n, ())
    if len(deps) != 1:
        return None
    d = ex.results.get(deps[0])
    if not isinstance(d, DatasetExpr) or d.dataset.is_host:
        return None
    ds = d.dataset
    arr_aval = jax.ShapeDtypeStruct((full_n,) + tuple(ds.array.shape[1:]), ds.array.dtype)
    t = op.transformer
    if ds.mask is not None:
        mask_aval = jax.ShapeDtypeStruct(
            (full_n,) + tuple(ds.mask.shape[1:]), ds.mask.dtype
        )
        cost = hlo_stage_cost(lambda a, m: t.apply_batch(a, mask=m), arr_aval, mask_aval)
    else:
        cost = hlo_stage_cost(lambda a: t.apply_batch(a), arr_aval)
    return cost["seconds_est"] if cost else None


def device_hbm_budget(fraction: float = 0.5) -> int:
    """Cache budget from the REAL device's memory limit (bytes).

    Reads the backend's memory stats (HBM ``bytes_limit``); ``fraction``
    leaves headroom for solver state and XLA temporaries.  Falls back to
    8 GiB (half a v5-lite HBM) when the backend exposes no stats (CPU
    test meshes).  ``KEYSTONE_HBM_BUDGET_BYTES`` overrides the device
    limit (before ``fraction``) — the auto-out-of-core tests use it to
    provoke the over-budget path on small data."""
    import os

    import jax

    env = os.environ.get("KEYSTONE_HBM_BUDGET_BYTES", "").strip()
    if env:
        try:
            return int(int(env) * fraction)
        except ValueError:
            logger.warning("KEYSTONE_HBM_BUDGET_BYTES=%r is not an int", env)
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if limit:
            return int(limit * fraction)
    except Exception:
        pass
    # no stats (axon/CPU): assume a 16 GiB v5e-class device
    return int((16 << 30) * fraction)


def pool_budget_bytes(fraction: float = 0.25) -> int:
    """The shared stage pool's default HBM budget
    (``workflow/stage_pool.py``): a quarter of the device limit by
    default — the pool holds transient per-flush featurized outputs
    NEXT TO every tenant's resident model weights and the serve
    batches, so it gets a deliberately smaller slice than the fit-time
    cache budget.  ``KEYSTONE_POOL_BUDGET_BYTES`` overrides outright
    (the eviction tests provoke pressure on small data with it); with
    the env unset, an installed ``PhysicalPlan``'s pinned
    ``pool_budget_bytes`` knob applies (the planner precedence — a
    deploy host with different headroom serves what was planned); with
    neither, the device-derived default."""
    import os

    env = os.environ.get("KEYSTONE_POOL_BUDGET_BYTES", "").strip()
    if env:
        try:
            return int(env)
        except ValueError:
            logger.warning("KEYSTONE_POOL_BUDGET_BYTES=%r is not an int", env)
    try:
        from keystone_tpu.planner import registry as _plans

        planned = _plans.planned_knob("pool_budget_bytes")
    except Exception:
        planned = None
    if planned is not None:
        return int(planned)
    return device_hbm_budget(fraction=fraction)


#: Footprint estimate of the LAST ProfilingAutoCacheRule pass, read by
#: Pipeline.fit's auto-out-of-core decision (workflow/pipeline.py §
#: _auto_out_of_core).  A module global rather than a graph annotation:
#: rule batches rebuild Graph instances, so an annotation would not
#: survive the fusion pass that runs after materialization.
last_footprint: dict = {}


class ProfilingAutoCacheRule(Rule):
    """Greedy cache placement under an HBM byte budget.

    ``static_cost=True`` prices nodes from compiled-HLO counters at full
    batch size (jitter-free) instead of extrapolated sampled wall time."""

    name = "ProfilingAutoCache"

    def __init__(
        self,
        budget_bytes: int = 8 << 30,
        sample_size: int = 64,
        static_cost: bool = False,
    ):
        self.budget_bytes = int(budget_bytes)
        self.sample_size = int(sample_size)
        self.static_cost = bool(static_cost)

    def apply(self, graph: G.Graph) -> G.Graph:
        # a PREVIOUS fit's estimate must never leak into this fit's
        # auto-out-of-core decision (fallback/early-return paths would
        # otherwise leave it standing — review r5)
        last_footprint.clear()
        shared = [
            n
            for n in graph.topological_nodes()
            if isinstance(graph.operators.get(n), (G.TransformerOperator, G.GatherOperator))
            and len([d for d in graph.dependents(n) if not isinstance(d, G.SinkId)]) > 1
        ]
        if not shared:  # nothing to place — skip the sampling pass entirely
            return graph
        import os

        # debug/A-B knob: profile every node like the pre-r4 rule did
        # (measured ~60% of north-star fit wall-clock; BASELINE.md r4)
        profile_all = os.environ.get("KEYSTONE_CACHE_PROFILE_ALL", "") == "1"
        profiles = profile_graph(
            graph,
            self.sample_size,
            static_cost=self.static_cost,
            targets=None if profile_all else frozenset(shared),
        )
        seconds = _comparable_seconds(profiles)
        # most compute saved per byte pinned, first
        shared.sort(
            key=lambda n: (
                -(seconds[n] / max(profiles[n].full_bytes, 1))
                if n in profiles
                else 0.0
            )
        )
        remaining = self.budget_bytes
        shared_bytes = 0
        pinned_bytes = 0
        demotions = 0
        for n in shared:
            prof = profiles.get(n)
            cost = prof.full_bytes if prof else 0
            shared_bytes += cost
            if cost <= remaining:
                remaining -= cost
                pinned_bytes += cost
                graph = _insert_cacher(graph, n)
            else:
                op = graph.operators[n]
                if isinstance(op, G.TransformerOperator):
                    demotions += 1
                    logger.info(
                        "over HBM budget: %s (%.1f MB) will recompute per consumer",
                        op.label(),
                        cost / 1e6,
                    )
                    # never mutate shared Operator instances (graphs share
                    # them persistent-structure style): flag a fresh copy
                    flagged = G.TransformerOperator(op.transformer)
                    flagged.no_memoize = True
                    graph = graph.set_operator(n, flagged)
        # record the pass's byte estimates for the auto-out-of-core
        # decision (fit-time pre-flight in workflow/pipeline.py)
        last_footprint.clear()
        last_footprint.update(
            {
                "shared_bytes": int(shared_bytes),
                "budget_bytes": int(self.budget_bytes),
            }
        )
        from keystone_tpu.obs import ledger, metrics

        metrics.set_gauge("optimizer.pinned_bytes", float(pinned_bytes))
        if demotions:
            metrics.inc("optimizer.no_memoize_demotions", demotions)
        ledger.event(
            "optimizer.cache_placement",
            shared_nodes=len(shared),
            pinned_bytes=int(pinned_bytes),
            no_memoize_demotions=int(demotions),
            shared_bytes=int(shared_bytes),
            budget_bytes=int(self.budget_bytes),
        )
        return graph


def _comparable_seconds(profiles: Dict[G.NodeId, NodeProfile]) -> Dict[G.NodeId, float]:
    """Per-node cost in ONE unit.

    Roofline estimates (hlo_seconds) are idealized lower bounds, often far
    below wall time; ranking them directly against extrapolated wall times
    for nodes static pricing couldn't handle (gathers, host nodes) would
    systematically favor the wall-priced nodes.  Calibrate: median
    roofline/wall ratio over nodes that have both, applied to wall-only
    nodes, so every entry is in pseudo-roofline seconds."""
    ratios = [
        p.hlo_seconds / (p.seconds * p.scale)
        for p in profiles.values()
        if p.hlo_seconds is not None and p.seconds > 0
    ]
    calib = float(np.median(ratios)) if ratios else 1.0
    return {
        n: (
            p.hlo_seconds
            if p.hlo_seconds is not None
            else p.seconds * p.scale * calib
        )
        for n, p in profiles.items()
    }


def _insert_cacher(graph: G.Graph, n: G.NodeId) -> G.Graph:
    deps_on_n = [d for d in graph.dependents(n) if isinstance(d, G.NodeId)]
    already = any(
        isinstance(graph.operators.get(d), G.TransformerOperator)
        and isinstance(graph.operators[d].transformer, Cacher)
        for d in deps_on_n
    )
    if already:
        return graph
    graph, cache_node = graph.add_node(G.TransformerOperator(Cacher()), (n,))
    for d in deps_on_n:
        if d != cache_node:
            graph = graph.set_dependencies(
                d, tuple(cache_node if x == n else x for x in graph.dependencies[d])
            )
    return graph
