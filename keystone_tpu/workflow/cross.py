"""Cross-pipeline CSE: plan stage sharing between co-served pipelines.

The per-pipeline optimizer's ``EquivalentNodeMergeRule`` merges equal
prefixes WITHIN one graph; this pass is its across-graphs twin for the
multi-tenant serving fleet.  Given the frozen graphs of N co-served
tenants it:

1. computes a **normalized prefix signature** per node — the
   ``Graph.prefix_signature`` structural hash with every open source
   mapped to the same placeholder, so "SIFT over the request batch" is
   one value no matter which tenant's graph it sits in;
2. finds the signatures present in ≥ 2 tenants' graphs (the shared
   stages);
3. runs the PR-6 **signature-collision pass** over the disjoint UNION
   of all graphs as the admission gate: a stage whose transformer
   signature collides there (equal signature, observably different
   state — ``params()`` under-specifies) is refused sharing outright —
   counted, never shared, never wrong;
4. keeps only the sharing **frontier**: a shared node is marked iff in
   at least one graph some consumer of it is NOT shared (the deepest
   shared stages).  The executor consults the pool top-down, so a
   frontier hit prunes the whole prefix walk — marking interior nodes
   would only publish intermediates no other tenant reads.

The "rewrite to pool lookups" is the resulting per-tenant
``{node id → signature}`` map: the multi-tenant applier hands it to
each :class:`~keystone_tpu.workflow.executor.GraphExecutor`, whose walk
then reads marked nodes through the
:class:`~keystone_tpu.workflow.stage_pool.SharedStagePool` instead of
recomputing them.  Nothing is stamped on shared operator instances
(pipelines built from one featurizer object can share them across
graphs) and the plan is plain data — it pickles with the applier into
every replica clone.
"""

from __future__ import annotations

import dataclasses
from functools import reduce
from typing import Dict, Optional

from keystone_tpu.workflow import graph as G

#: every open source normalizes to this placeholder in prefix
#: signatures: co-served serve graphs all hang off "the request batch"
_SOURCE = ("source", 0)


def normalized_prefix_signature(
    g: G.Graph, target, memo: Optional[dict] = None
) -> Optional[tuple]:
    """``Graph.prefix_signature`` with sources normalized (and without
    the per-graph unique fallback: an unshareable node is simply None).
    None = the node (or something in its prefix) declares no stable
    signature — it can never key a cross-pipeline cache entry."""
    if memo is None:
        memo = {}
    if target in memo:
        return memo[target]
    if isinstance(target, G.SourceId):
        memo[target] = _SOURCE
        return _SOURCE
    op = g.operators[target]
    try:
        sig = op.signature()
    except Exception:
        sig = None  # a raising identity can never key a shared entry
    if sig is None:
        memo[target] = None
        return None
    # an over-HBM-budget node (no_memoize) must not be pinned into the
    # pool either: the cache rule already ruled its output unaffordable
    if getattr(op, "no_memoize", False):
        memo[target] = None
        return None
    deps = tuple(
        normalized_prefix_signature(g, d, memo) for d in g.dependencies[target]
    )
    if any(d is None for d in deps):
        memo[target] = None
        return None
    out = ("node", sig, deps)
    memo[target] = out
    return out


@dataclasses.dataclass
class SharingPlan:
    """The cross-pipeline pass's output (plain data; pickles with the
    multi-tenant applier into replica clones)."""

    #: tenant -> {NodeId: normalized prefix signature} for pooled nodes
    node_sigs: Dict[str, Dict[G.NodeId, tuple]]
    #: every pooled signature
    shared: frozenset
    #: signature -> number of tenant graphs containing it
    consumers: Dict[tuple, int]
    #: how many shared candidates the collision gate refused
    refused: int

    def sigs_for(self, tenants) -> Dict[tuple, int]:
        """Per-signature consumer counts restricted to one flush's
        tenants — the ``begin_flush`` declaration."""
        out: Dict[tuple, int] = {}
        for t in set(tenants):
            for sig in set(self.node_sigs.get(t, {}).values()):
                out[sig] = out.get(sig, 0) + 1
        return {s: n for s, n in out.items() if n >= 2}

    def shared_stage_count(self) -> int:
        return len(self.shared)


def plan_sharing(graphs: Dict[str, G.Graph]) -> SharingPlan:
    """Plan cross-pipeline stage sharing over co-served tenant graphs.

    Single-tenant (or no overlap) degenerates to an empty plan — the
    executor path is then byte-identical to the pre-pool walk (pinned
    by tests/test_multitenant.py)."""
    from keystone_tpu.obs import metrics

    per_node: Dict[str, Dict[G.NodeId, tuple]] = {}
    sig_tenants: Dict[tuple, set] = {}
    for tenant, g in graphs.items():
        memo: dict = {}
        sigs: Dict[G.NodeId, tuple] = {}
        for n in g.topological_nodes():
            op = g.operators[n]
            # pooled values are stage OUTPUTS a later stage consumes:
            # transformer applications and gathers; datasets/datums are
            # literals and estimator nodes never appear in frozen graphs
            if not isinstance(op, (G.TransformerOperator, G.GatherOperator)):
                continue
            s = normalized_prefix_signature(g, n, memo)
            if s is None or s == _SOURCE:
                continue
            sigs[n] = s
            sig_tenants.setdefault(s, set()).add(tenant)
        per_node[tenant] = sigs
    shared = {s for s, ts in sig_tenants.items() if len(ts) >= 2}
    if not shared:
        return SharingPlan({t: {} for t in graphs}, frozenset(), {}, 0)

    # ---- admission gate: the PR-6 collision pass over the UNION graph
    from keystone_tpu.analysis.signatures import collision_signatures

    union = reduce(lambda a, b: a.union(b)[0], graphs.values(), G.Graph())
    colliding = collision_signatures(union)
    refused = 0
    if colliding:
        admitted = set()
        for s in shared:
            # s = ("node", op.signature(), deps); op.signature() wraps
            # the object signature as ("transform"|"fit", obj_sig)
            obj_sig = s[1][1] if len(s[1]) == 2 else None
            if obj_sig in colliding or _prefix_tainted(s, colliding):
                refused += 1
            else:
                admitted.add(s)
        shared = admitted
    if refused:
        metrics.inc("serve.pool_refusals", refused)

    # ---- keep the sharing frontier only
    frontier: set = set()
    for tenant, g in graphs.items():
        sigs = per_node[tenant]
        for n, s in sigs.items():
            if s not in shared:
                continue
            deps_on_n = g.dependents(n)
            if not deps_on_n or any(
                isinstance(d, G.SinkId) or sigs.get(d) not in shared
                for d in deps_on_n
            ):
                frontier.add(s)
    node_sigs = {
        tenant: {n: s for n, s in sigs.items() if s in frontier}
        for tenant, sigs in per_node.items()
    }
    consumers = {
        s: len(sig_tenants[s]) for s in frontier if s in sig_tenants
    }
    return SharingPlan(node_sigs, frozenset(frontier), consumers, refused)


def _prefix_tainted(psig: tuple, colliding: set) -> bool:
    """Does any stage in the prefix signature carry a colliding object
    signature?  A safe frontier over a poisoned interior stage would
    still share the poisoned computation."""
    if not isinstance(psig, tuple) or not psig or psig[0] != "node":
        return False
    op_sig = psig[1]
    if (
        isinstance(op_sig, tuple)
        and len(op_sig) == 2
        and op_sig[1] in colliding
    ):
        return True
    return any(_prefix_tainted(d, colliding) for d in psig[2])
