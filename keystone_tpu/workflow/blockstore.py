"""Disk-backed feature-block store for out-of-core block solvers.

The reference fits d≈200k-dim Fisher-vector models by caching feature
blocks as RDDs (spilled to executor disk/memory) and re-reading them per
(epoch, block) during block coordinate descent
(nodes/learning/BlockLeastSquares.scala per SURVEY.md §3.2).  On TPU the
analogue is this store: features are written once, blockified on disk as
one ``.npy`` memmap per feature block, and re-streamed per sweep so HBM
only ever holds ONE (n × block_size) block plus the (n × k) residual —
the feature matrix itself can exceed device memory by an arbitrary
factor.

Layout of a store directory::

    meta.json                {"n": ..., "d": ..., "block_size": ..., "nb": ...}
    block_0000.npy           float32 (n, block_size)
    block_0001.npy           ...

The final block is zero-padded on columns to ``block_size`` (the
VectorSplitter convention, nodes/util/VectorSplitter.scala), which keeps
every device transfer and every compiled block-step identical in shape —
one XLA program serves all (epoch, block) steps.

``dtype="bfloat16"`` halves both the disk footprint and the
disk→host→device bytes per sweep — on this chip bf16 is a bandwidth
lever, not a compute lever (utils/precision.py), and the out-of-core
sweep is bandwidth-bound, so this is exactly where it pays.  Blocks are
stored as uint16 bit patterns (npy's parser chokes on the registered
bfloat16 descr) and read back as ml_dtypes.bfloat16; consumers cast to
f32 ON DEVICE so solver math is unchanged.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from typing import Iterable, Iterator, Optional, Sequence, Tuple

import numpy as np

from keystone_tpu.faults import fault_point
from keystone_tpu.obs import metrics

_META = "meta.json"
_DTYPES = ("float32", "bfloat16")


def _verify_blocks_enabled() -> bool:
    """Per-read checksum verification kill switch (KEYSTONE_VERIFY_BLOCKS
    =0).  BLAKE2b streams at memory-ish bandwidth, so verification is
    roughly a second disk pass per sweep — on by default because a
    silently-corrupt feature block poisons every subsequent epoch."""
    return os.environ.get("KEYSTONE_VERIFY_BLOCKS", "1") != "0"


def _bf16():
    import ml_dtypes

    return ml_dtypes.bfloat16


class _BlockStreamBase:
    """Shared disk→host→device streaming machinery for block stores.

    Subclasses provide :meth:`read_block`; both the column-blocked
    :class:`FeatureBlockStore` (BCD over feature blocks) and the
    row-blocked :class:`RowBlockStore` (the kernel tier's gram-block
    feed) ride the SAME prefetch thread + staged-transfer window, so
    the PR-7 flow-control guarantees — bounded in-flight host buffers,
    donation-safe yielded blocks, ``blockstore.stage_wait_seconds``
    metering — hold identically for every out-of-core sweep."""

    def read_block(self, b: int) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError(type(self).__name__)

    def iter_blocks(
        self, order: Sequence[int], prefetch: int = 2
    ) -> Iterator[Tuple[int, np.ndarray]]:
        """Yield ``(b, block)`` for each index in ``order``, reading ahead
        on a worker thread so disk IO overlaps the consumer's device work
        (the role the reference delegates to Spark's block manager)."""
        q: "queue.Queue" = queue.Queue(maxsize=max(1, int(prefetch)))
        sentinel = object()
        stop = threading.Event()
        err: list = []

        def put(item) -> bool:
            # bounded put that gives up when the consumer abandoned the
            # generator — otherwise the thread would park forever on a
            # full queue, pinning GB-scale host blocks
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            b_cur: Optional[int] = None
            try:
                for b in order:
                    b_cur = b
                    if stop.is_set() or not put((b, self.read_block(b))):
                        return
            except BaseException as e:
                # Tag the failing block index onto the error IN PLACE
                # (type preserved: retry_if / except-clauses downstream
                # dispatch on the exception class, so wrapping would
                # silently defeat them).  Without the tag, a sweep of
                # hundreds of blocks reports "checksum mismatch" with no
                # way to know WHICH block file to inspect.
                if b_cur is not None:
                    tag = f"block {b_cur}: "
                    if (
                        isinstance(e, OSError)
                        and e.errno is not None
                        and isinstance(e.strerror, str)
                    ):
                        # str(OSError) renders from errno/strerror, not
                        # args — and args must stay (errno, strerror)
                        # shaped for cross-process reconstruction, so
                        # the tag goes on the strerror field
                        e.strerror = tag + e.strerror
                    elif e.args and isinstance(e.args[0], str):
                        e.args = (tag + e.args[0],) + e.args[1:]
                    else:
                        # exotic arg shapes (fixed-arity/structured
                        # constructors): args mutation would break
                        # type(e)(*e.args) reconstruction — attach the
                        # index as an attribute only
                        e.block_index = b_cur
                err.append(e)
            finally:
                put(sentinel)

        t = threading.Thread(
            target=produce, daemon=True, name="blockstore-prefetch"
        )
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()
            # Join (bounded): when the consumer abandons the generator
            # mid-sweep (early break, exception, GC close), the producer
            # is parked on a full queue holding a GB-scale block; the
            # stop flag makes its bounded put give up within ~0.1 s, and
            # joining here makes the release PROMPT and deterministic
            # instead of leaving a parked daemon thread (and its pinned
            # block) to whenever the scheduler next runs it.  The
            # timeout covers a producer mid-read on a slow disk — a
            # leaked thread then still exits at the next put attempt.
            t.join(timeout=10.0)
            # drop any blocks still parked in the queue so their host
            # buffers free with the generator, not with the GC
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    def iter_device_blocks(
        self,
        order: Sequence[int],
        prefetch: int = 2,
        stage=None,
        window: int = 2,
    ) -> Iterator[Tuple[int, object]]:
        """Double-buffered device feed: yield ``(b, staged_block)`` with
        the host→device transfer of the NEXT block(s) already dispatched
        while the consumer computes on the current one.

        Three overlapped tiers: disk→host read-ahead rides
        :meth:`iter_blocks`'s producer thread (``prefetch`` deep);
        host→device staging is dispatched ``window`` blocks ahead of the
        consumer, so block *b+1*'s transfer overlaps block *b*'s
        compute; and the consumer's own device step is async-dispatched
        as usual.  ``stage(host_block) -> device value`` performs the
        put (default: ``jax.device_put`` + on-device f32 cast for bf16
        stores); a pytree return (tuple/list of arrays) is dispatched as
        ONE batched ``jax.device_put``-style transfer — callers staging
        multiple arrays per block should return them together rather
        than staging serially.

        Flow control WITHOUT host round-trips: before a block is
        yielded, ``jax.block_until_ready`` confirms its transfer landed
        (by then it was dispatched ``window`` iterations earlier, so the
        wait is usually zero).  That bounds in-flight staged host
        buffers to ``window`` blocks and guarantees every yielded block
        is safe for the consumer to DONATE to its compute step (a
        donated buffer cannot be waited on afterwards).  It bounds
        TRANSFERS only: transfers are not ordered behind compute, so a
        consumer whose per-block step is slower than the wire must also
        bound its own dispatch lead with a ready-wait on a recent step
        output (as ``_oc_bcd_fit`` does on the step's tick two behind) —
        otherwise yielded blocks pile up in HBM pinned by the queued
        executions that consume them.
        Time spent blocked in staging is recorded as the
        ``blockstore.stage_wait_seconds`` histogram — the obs ledger's
        ``transfer_seconds`` account.
        """
        import time

        import jax
        import jax.numpy as jnp
        from collections import deque

        if stage is None:

            def stage(blk):
                a = jax.device_put(blk)
                if a.dtype != jnp.float32:
                    a = a.astype(jnp.float32)
                return a

        window = max(1, int(window))
        staged: deque = deque()  # (b, value): transfer dispatched, not yielded

        def land(item):
            b, dev = item
            t0 = time.perf_counter()
            dev = jax.block_until_ready(dev)
            metrics.observe(
                "blockstore.stage_wait_seconds", time.perf_counter() - t0
            )
            return b, dev

        it = self.iter_blocks(order, prefetch=prefetch)
        try:
            for b, blk in it:
                t0 = time.perf_counter()
                dev = stage(blk)
                # the dispatch itself does real host work (layout copy +
                # DMA enqueue; on tunneled backends the RPC) — charge it
                # to the same transfer account as the landing wait
                metrics.observe(
                    "blockstore.stage_wait_seconds",
                    time.perf_counter() - t0,
                )
                staged.append((b, dev))
                if len(staged) > window:
                    yield land(staged.popleft())
            while staged:
                yield land(staged.popleft())
        finally:
            it.close()
            staged.clear()


class FeatureBlockStore(_BlockStreamBase):
    """Blockified (n, d) float32 feature matrix on disk.

    Create with :meth:`create` + :meth:`append_rows` (streaming writes),
    or the :meth:`from_array` / :meth:`from_batches` conveniences.
    """

    def __init__(self, directory: str):
        self.directory = directory
        with open(os.path.join(directory, _META)) as f:
            meta = json.load(f)
        self.n = int(meta["n"])
        self.d = int(meta["d"])
        self.block_size = int(meta["block_size"])
        self.num_blocks = int(meta["nb"])
        # stores written before the dtype option are float32
        self.dtype = str(meta.get("dtype", "float32"))

    @property
    def _disk_dtype(self):
        return np.uint16 if self.dtype == "bfloat16" else np.float32

    # ------------------------------------------------------------ create
    @classmethod
    def create(
        cls,
        directory: str,
        n: int,
        d: int,
        block_size: int,
        dtype: str = "float32",
    ):
        """Allocate an empty store; fill it with :meth:`append_rows`."""
        if dtype not in _DTYPES:
            raise ValueError(f"dtype must be one of {_DTYPES}, got {dtype!r}")
        os.makedirs(directory, exist_ok=True)
        nb = -(-d // block_size)
        meta = {
            "n": int(n),
            "d": int(d),
            "block_size": int(block_size),
            "nb": nb,
            "dtype": dtype,
        }
        with open(os.path.join(directory, _META), "w") as f:
            json.dump(meta, f)
        disk_dtype = np.uint16 if dtype == "bfloat16" else np.float32
        for b in range(nb):
            mm = np.lib.format.open_memmap(
                cls._block_path(directory, b),
                mode="w+",
                dtype=disk_dtype,
                shape=(n, block_size),
            )
            del mm  # flushed zero-initialized file
        store = cls(directory)
        store._cursor = 0
        # incremental payload digests, fed from the IN-MEMORY chunks as
        # they are written: finalize() compares them against what the
        # files actually contain, so corruption introduced by the write
        # path itself (torn write, bit flip between buffer and platter)
        # is caught at seal time — a sidecar hashed from the file alone
        # would faithfully checksum the damage
        import hashlib

        store._hashers = [
            hashlib.blake2b(digest_size=16) for _ in range(nb)
        ]
        return store

    @staticmethod
    def _block_path(directory: str, b: int) -> str:
        return os.path.join(directory, f"block_{b:04d}.npy")

    def append_rows(self, x: np.ndarray) -> None:
        """Write the next ``x.shape[0]`` rows of the (n, d) matrix."""
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[1] != self.d:
            raise ValueError(f"expected (m, {self.d}) rows, got {x.shape}")
        start = getattr(self, "_cursor", 0)
        stop = start + x.shape[0]
        if stop > self.n:
            raise ValueError(f"store holds {self.n} rows; write would reach {stop}")
        bs = self.block_size
        for b in range(self.num_blocks):
            mm = np.lib.format.open_memmap(
                self._block_path(self.directory, b), mode="r+"
            )
            chunk = x[:, b * bs : (b + 1) * bs]
            if chunk.shape[1] < bs:  # final ragged block: zero-pad columns
                chunk = np.pad(chunk, ((0, 0), (0, bs - chunk.shape[1])))
            if self.dtype == "bfloat16":
                chunk = chunk.astype(_bf16()).view(np.uint16)
            mm[start:stop] = chunk
            del mm
            hashers = getattr(self, "_hashers", None)
            if hashers is not None:
                hashers[b].update(np.ascontiguousarray(chunk).tobytes())
            fault_point(
                "blockstore.write", path=self._block_path(self.directory, b)
            )
            metrics.inc("blockstore.write_bytes", int(chunk.nbytes))
        metrics.inc("blockstore.writes")
        self._cursor = stop

    def finalize(self) -> None:
        """Seal a fully-written store: verify each block file's payload
        against the digest accumulated from the in-memory chunks during
        :meth:`append_rows` (write-path corruption — a torn or flipped
        write — surfaces HERE as :class:`CorruptStateError`, at spill
        time, instead of training on damaged features), then write a
        BLAKE2b sidecar per block so every later :meth:`read_block`
        verifies content integrity (truncation is caught even without
        sidecars via the size check).  ``from_array`` / ``from_batches``
        call this automatically; streaming ``append_rows`` writers call
        it once the last row lands."""
        import hashlib

        from keystone_tpu.utils import durable

        hashers = getattr(self, "_hashers", None)
        complete = getattr(self, "_cursor", None) == self.n
        for b in range(self.num_blocks):
            path = self._block_path(self.directory, b)
            if hashers is not None and complete:
                try:
                    raw = np.load(path, mmap_mode="r")
                    h = hashlib.blake2b(digest_size=16)
                    # stream row chunks off the memmap: the store exists
                    # because n×d does NOT fit in memory, so seal-time
                    # verification must stay O(chunk), not O(block)
                    row_bytes = max(1, raw.shape[1] * raw.itemsize)
                    step = max(1, (4 << 20) // row_bytes)
                    for s in range(0, raw.shape[0], step):
                        h.update(
                            np.ascontiguousarray(raw[s : s + step]).tobytes()
                        )
                    on_disk = h.hexdigest()
                except Exception as e:
                    raise durable.CorruptStateError(
                        f"unreadable block {path} at seal time: {e}"
                    )
                if on_disk != hashers[b].hexdigest():
                    raise durable.CorruptStateError(
                        f"write verification failed for block {path}: "
                        "on-disk payload does not match the bytes that "
                        "were written (torn or corrupted write)"
                    )
            durable.write_checksum(path)

    @classmethod
    def from_array(cls, directory: str, x, block_size: int, dtype: str = "float32"):
        x = np.asarray(x, np.float32)
        store = cls.create(directory, x.shape[0], x.shape[1], block_size, dtype=dtype)
        store.append_rows(x)
        store.finalize()
        return store

    @classmethod
    def from_batches(
        cls,
        directory: str,
        batches: Iterable[np.ndarray],
        n: int,
        block_size: int,
        dtype: str = "float32",
    ):
        """Build from a stream of (m_i, d) host batches (Σ m_i == n)."""
        store = None
        for batch in batches:
            batch = np.asarray(batch, np.float32)
            if store is None:
                store = cls.create(
                    directory, n, batch.shape[1], block_size, dtype=dtype
                )
            store.append_rows(batch)
        if store is None:
            raise ValueError("empty batch stream")
        if store._cursor != n:
            raise ValueError(
                f"batch stream produced {store._cursor} rows, expected {n}"
            )
        store.finalize()
        return store

    # -------------------------------------------------------------- read
    def read_block(self, b: int) -> np.ndarray:
        """One (n, block_size) block, as an in-memory host array.

        Hardened: transient read errors retry with backoff
        (utils/durable), a truncated file (partial write, torn spill)
        raises :class:`~keystone_tpu.utils.durable.CorruptStateError`
        before any bytes reach a solver, and sealed stores
        (:meth:`finalize`) additionally checksum-verify the content.

        bf16 stores return ml_dtypes.bfloat16 — consumers transfer the
        half-width bytes to device and cast to f32 THERE (halving the
        host→device wire cost, the scarce resource on this backend)."""
        from keystone_tpu.utils import durable

        path = self._block_path(self.directory, b)
        expected_bytes = (
            self.n * self.block_size * np.dtype(self._disk_dtype).itemsize
        )
        attempts = [0]

        def _read():
            attempts[0] += 1
            fault_point("blockstore.read", path=path)
            if os.path.getsize(path) < expected_bytes:
                raise durable.CorruptStateError(
                    f"truncated block {path}: {os.path.getsize(path)} bytes "
                    f"< {expected_bytes} of payload for shape "
                    f"({self.n}, {self.block_size})"
                )
            if _verify_blocks_enabled():
                durable.verify_checksum(path)  # no-op for unsealed stores
            try:
                raw = np.array(np.load(path, mmap_mode="r"))
            except ValueError as e:  # npy header inconsistent with size
                raise durable.CorruptStateError(f"corrupt block {path}: {e}")
            if raw.shape != (self.n, self.block_size):
                raise durable.CorruptStateError(
                    f"block {path} has shape {raw.shape}, expected "
                    f"({self.n}, {self.block_size})"
                )
            return raw

        raw = durable.with_retries(_read, description=f"block read {path}")
        metrics.inc("blockstore.reads")
        metrics.inc("blockstore.read_bytes", int(raw.nbytes))
        if attempts[0] > 1:
            metrics.inc("blockstore.read_retries", attempts[0] - 1)
        if self.dtype == "bfloat16":
            return raw.view(_bf16())
        return raw

    def nbytes(self) -> int:
        itemsize = 2 if self.dtype == "bfloat16" else 4
        return self.n * self.num_blocks * self.block_size * itemsize


_ROW_META = "row_meta.json"


class RowBlockStore(_BlockStreamBase):
    """Row-blocked (n, d) float32 matrix on disk — the kernel tier's
    out-of-core feed.

    Where :class:`FeatureBlockStore` splits the matrix by FEATURE
    columns (the BCD-over-feature-blocks layout), this store splits by
    EXAMPLE rows: block *b* is ``X[b·bs : (b+1)·bs]`` as one ``(bs, d)``
    npy file, zero-padded on rows in the final block so every device
    transfer and every compiled gram-block step shares one shape.  The
    kernel BCD sweep streams these row blocks to build ``K_{·b}``
    column blocks tile by tile via the ‖x−z‖² gemm expansion — the n×n
    kernel matrix never materializes anywhere.

    Streaming row batches append SEQUENTIALLY (each batch lands in a
    few consecutive block files), integrity rides the same machinery as
    the feature store: incremental write-path digests verified at
    :meth:`finalize`, BLAKE2b sidecars per block, retried +
    truncation-checked reads through the ``blockstore.read`` fault
    site.  ``dtype="bfloat16"`` halves disk + wire bytes; consumers
    cast to f32 on device (solver math unchanged).

    Layout::

        row_meta.json            {"n","d","block_size","nb","dtype"}
        rblock_0000.npy          (block_size, d) rows [0, bs)
        rblock_0001.npy          ...
    """

    def __init__(self, directory: str):
        self.directory = directory
        with open(os.path.join(directory, _ROW_META)) as f:
            meta = json.load(f)
        self.n = int(meta["n"])
        self.d = int(meta["d"])
        self.block_size = int(meta["block_size"])
        self.num_blocks = int(meta["nb"])
        self.dtype = str(meta.get("dtype", "float32"))

    @property
    def _disk_dtype(self):
        return np.uint16 if self.dtype == "bfloat16" else np.float32

    # ------------------------------------------------------------ create
    @classmethod
    def create(
        cls,
        directory: str,
        n: int,
        d: int,
        block_size: int,
        dtype: str = "float32",
    ):
        """Allocate an empty store; fill it with :meth:`append_rows`."""
        if dtype not in _DTYPES:
            raise ValueError(f"dtype must be one of {_DTYPES}, got {dtype!r}")
        os.makedirs(directory, exist_ok=True)
        nb = -(-n // block_size)
        meta = {
            "n": int(n),
            "d": int(d),
            "block_size": int(block_size),
            "nb": nb,
            "dtype": dtype,
        }
        with open(os.path.join(directory, _ROW_META), "w") as f:
            json.dump(meta, f)
        disk_dtype = np.uint16 if dtype == "bfloat16" else np.float32
        for b in range(nb):
            mm = np.lib.format.open_memmap(
                cls._block_path(directory, b),
                mode="w+",
                dtype=disk_dtype,
                shape=(block_size, d),
            )
            del mm  # flushed zero-initialized file
        store = cls(directory)
        store._cursor = 0
        # write-path digests fed from the in-memory chunks (see
        # FeatureBlockStore.create): finalize() compares them against
        # the files so a torn/flipped write surfaces at seal time
        import hashlib

        store._hashers = [hashlib.blake2b(digest_size=16) for _ in range(nb)]
        return store

    @staticmethod
    def _block_path(directory: str, b: int) -> str:
        return os.path.join(directory, f"rblock_{b:04d}.npy")

    def append_rows(self, x: np.ndarray) -> None:
        """Write the next ``x.shape[0]`` rows.  Sequential: a batch
        spans only the block files covering its row range."""
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[1] != self.d:
            raise ValueError(f"expected (m, {self.d}) rows, got {x.shape}")
        start = getattr(self, "_cursor", 0)
        stop = start + x.shape[0]
        if stop > self.n:
            raise ValueError(f"store holds {self.n} rows; write would reach {stop}")
        bs = self.block_size
        hashers = getattr(self, "_hashers", None)
        for b in range(start // bs, -(-stop // bs)):
            lo, hi = max(start, b * bs), min(stop, (b + 1) * bs)
            chunk = x[lo - start : hi - start]
            if self.dtype == "bfloat16":
                chunk = chunk.astype(_bf16()).view(np.uint16)
            mm = np.lib.format.open_memmap(
                self._block_path(self.directory, b), mode="r+"
            )
            mm[lo - b * bs : hi - b * bs] = chunk
            del mm
            if hashers is not None:
                hashers[b].update(np.ascontiguousarray(chunk).tobytes())
            fault_point(
                "blockstore.write", path=self._block_path(self.directory, b)
            )
            metrics.inc("blockstore.write_bytes", int(chunk.nbytes))
        metrics.inc("blockstore.writes")
        self._cursor = stop

    def finalize(self) -> None:
        """Seal a fully-written store: verify every block's WRITTEN rows
        against the write-path digest (the padding rows of the final
        block were zero-filled at create time and never appended, so
        only rows ``< n`` enter the comparison), then write the BLAKE2b
        sidecar covering the whole file for read-time verification."""
        import hashlib

        from keystone_tpu.utils import durable

        hashers = getattr(self, "_hashers", None)
        complete = getattr(self, "_cursor", None) == self.n
        bs = self.block_size
        for b in range(self.num_blocks):
            path = self._block_path(self.directory, b)
            if hashers is not None and complete:
                rows = min(bs, self.n - b * bs)
                try:
                    raw = np.load(path, mmap_mode="r")
                    h = hashlib.blake2b(digest_size=16)
                    row_bytes = max(1, raw.shape[1] * raw.itemsize)
                    step = max(1, (4 << 20) // row_bytes)
                    for s in range(0, rows, step):
                        h.update(
                            np.ascontiguousarray(
                                raw[s : min(s + step, rows)]
                            ).tobytes()
                        )
                    on_disk = h.hexdigest()
                except Exception as e:
                    raise durable.CorruptStateError(
                        f"unreadable block {path} at seal time: {e}"
                    )
                if on_disk != hashers[b].hexdigest():
                    raise durable.CorruptStateError(
                        f"write verification failed for block {path}: "
                        "on-disk payload does not match the bytes that "
                        "were written (torn or corrupted write)"
                    )
            durable.write_checksum(path)

    @classmethod
    def from_array(cls, directory: str, x, block_size: int, dtype: str = "float32"):
        x = np.asarray(x, np.float32)
        store = cls.create(directory, x.shape[0], x.shape[1], block_size, dtype=dtype)
        store.append_rows(x)
        store.finalize()
        return store

    @classmethod
    def from_batches(
        cls,
        directory: str,
        batches: Iterable[np.ndarray],
        n: int,
        block_size: int,
        dtype: str = "float32",
    ):
        """Build from a stream of (m_i, d) host batches (Σ m_i == n)."""
        store = None
        for batch in batches:
            batch = np.asarray(batch, np.float32)
            if store is None:
                store = cls.create(
                    directory, n, batch.shape[1], block_size, dtype=dtype
                )
            store.append_rows(batch)
        if store is None:
            raise ValueError("empty batch stream")
        if store._cursor != n:
            raise ValueError(
                f"batch stream produced {store._cursor} rows, expected {n}"
            )
        store.finalize()
        return store

    # -------------------------------------------------------------- read
    def read_block(self, b: int) -> np.ndarray:
        """One (block_size, d) row block as an in-memory host array,
        with the same hardening as FeatureBlockStore.read_block: retried
        reads, truncation detection, checksum verification, and the
        ``blockstore.read`` fault site."""
        from keystone_tpu.utils import durable

        path = self._block_path(self.directory, b)
        expected_bytes = (
            self.block_size * self.d * np.dtype(self._disk_dtype).itemsize
        )
        attempts = [0]

        def _read():
            attempts[0] += 1
            fault_point("blockstore.read", path=path)
            if os.path.getsize(path) < expected_bytes:
                raise durable.CorruptStateError(
                    f"truncated block {path}: {os.path.getsize(path)} bytes "
                    f"< {expected_bytes} of payload for shape "
                    f"({self.block_size}, {self.d})"
                )
            if _verify_blocks_enabled():
                durable.verify_checksum(path)  # no-op for unsealed stores
            try:
                raw = np.array(np.load(path, mmap_mode="r"))
            except ValueError as e:  # npy header inconsistent with size
                raise durable.CorruptStateError(f"corrupt block {path}: {e}")
            if raw.shape != (self.block_size, self.d):
                raise durable.CorruptStateError(
                    f"block {path} has shape {raw.shape}, expected "
                    f"({self.block_size}, {self.d})"
                )
            return raw

        raw = durable.with_retries(_read, description=f"block read {path}")
        metrics.inc("blockstore.reads")
        metrics.inc("blockstore.read_bytes", int(raw.nbytes))
        if attempts[0] > 1:
            metrics.inc("blockstore.read_retries", attempts[0] - 1)
        if self.dtype == "bfloat16":
            return raw.view(_bf16())
        return raw

    def nbytes(self) -> int:
        itemsize = 2 if self.dtype == "bfloat16" else 4
        return self.num_blocks * self.block_size * self.d * itemsize
