"""Workflow core: Transformer/Estimator/Pipeline DSL, DAG, executor,
whole-pipeline optimizer (reference src/main/scala/workflow/, SURVEY.md §2.1)."""

from keystone_tpu.workflow.dataset import (  # noqa: F401
    Dataset,
    StreamDataset,
    as_dataset,
)
from keystone_tpu.workflow.blockstore import FeatureBlockStore  # noqa: F401
from keystone_tpu.workflow.transformer import (  # noqa: F401
    Cacher,
    Identity,
    LambdaTransformer,
    Transformer,
    transformer,
)
from keystone_tpu.workflow.estimator import Estimator, LabelEstimator  # noqa: F401
from keystone_tpu.workflow.graph import (  # noqa: F401
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    GatherOperator,
    Graph,
    NodeId,
    Operator,
    SinkId,
    SourceId,
    TransformerOperator,
)
from keystone_tpu.workflow.executor import GraphExecutor  # noqa: F401
from keystone_tpu.workflow.recovery import (  # noqa: F401
    fit_with_recovery,
    purge_invalid_state,
    scan_state_dir,
)
from keystone_tpu.workflow.optimizer import (  # noqa: F401
    AutoMaterializeRule,
    EquivalentNodeMergeRule,
    FixedPoint,
    FusedTransformer,
    NodeChoiceRule,
    Once,
    Optimizer,
    PallasFvFusionRule,
    Rule,
    RuleBatch,
    StageFusionRule,
    default_optimizer,
)
from keystone_tpu.workflow.pipeline import (  # noqa: F401
    ArtifactMismatch,
    FittedPipeline,
    FrozenApplier,
    Pipeline,
    PipelineDataset,
    PipelineDatum,
    PipelineEnv,
    PreflightOOMError,
)
