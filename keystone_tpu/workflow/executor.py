"""Demand-driven, memoizing DAG executor.

Reference: workflow/GraphExecutor.scala § GraphExecutor — a topological
demand-driven walk that memoizes per-node results ("Expressions"); fit
nodes execute once and their fitted transformers are reused by all
dependents.

Results here are:
  - DatasetExpr: a sharded device-array Dataset (or host list)
  - DatumExpr: a single value
  - TransformerExpr: a fitted Transformer (output of estimator nodes)
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import time
from typing import Any, Dict, Optional

from keystone_tpu.workflow import graph as G
from keystone_tpu.workflow.dataset import Dataset, as_dataset
from keystone_tpu.workflow.estimator import Estimator, LabelEstimator
from keystone_tpu.workflow.transformer import Transformer

logger = logging.getLogger(__name__)

#: per-process monotonic discriminators for signatureless nodes'
#: breaker keys (see GraphExecutor._stage_breaker): stamped on the
#: transformer/operator object so the key is stable for the object's
#: lifetime and never recycled the way id() addresses are
_BREAKER_TOKENS = itertools.count()


@dataclasses.dataclass
class DatumExpr:
    value: Any


@dataclasses.dataclass
class DatasetExpr:
    dataset: Dataset


@dataclasses.dataclass
class TransformerExpr:
    transformer: Transformer


class GraphExecutor:
    def __init__(
        self,
        graph: G.Graph,
        profile: bool = False,
        node_retries: Optional[int] = None,
        deadline=None,
        stage_pool=None,
        pool_token=None,
        pool_sigs=None,
    ):
        """``node_retries``: re-run a failed stage up to this many times
        before propagating (SURVEY §5 "failure detection/elastic
        recovery" — the coarse analogue of Spark task retry: stages are
        pure functions of memoized inputs, so re-running one is always
        safe).  Default (None) resolves PipelineEnv.node_retries /
        KEYSTONE_STAGE_RETRIES, so EVERY executor the framework creates
        honors the knob without per-site plumbing.  Deterministic
        failures still propagate after the budget; process-level
        recovery is workflow/recovery.py.

        ``deadline``: a wall-clock budget (seconds, or a
        ``utils.guard.Deadline``) for THIS executor's whole walk —
        ``Pipeline.fit(deadline=…)`` and the lazy ``get(deadline=…)``
        results plumb through here.  Each stage attempt runs under a
        watchdog whose budget is the overall remaining time apportioned
        over the not-yet-executed nodes, further capped by the
        ``KEYSTONE_STAGE_DEADLINE`` per-stage env knob; an overrun
        raises ``DeadlineExceeded`` (an ``OSError``) INSIDE the retry
        scope, so a hung stage is retried — and, for nodes declaring
        ``optional=True`` / ``with_fallback``, degraded — like any
        transient fault.  With neither a deadline nor
        ``KEYSTONE_BREAKER_THRESHOLD`` configured the per-stage cost is
        one ``None`` check (no watchdog thread, no breaker lookup).

        ``stage_pool``/``pool_token``/``pool_sigs``: the cross-pipeline
        shared-stage tier (ISSUE 14 — the cache-ownership inversion).
        Per-run memoization stays in ``self.results`` exactly as
        before, but nodes listed in ``pool_sigs`` (``{NodeId:
        normalized prefix signature}``, planned by ``workflow/cross.py``)
        additionally read through and publish into the process-wide
        :class:`~keystone_tpu.workflow.stage_pool.SharedStagePool`
        under ``(signature, pool_token)`` — so co-served tenant walks
        over the same flush compute each shared prefix ONCE, and a pool
        hit prunes the whole prefix sub-walk.  All three default to
        None/empty: the pre-pool walk is byte-identical (pinned)."""
        from keystone_tpu.utils import guard

        self.graph = graph
        self.results: Dict[G.GraphId, Any] = {}
        self.profile = profile
        if node_retries is None:
            from keystone_tpu.workflow.pipeline import PipelineEnv

            node_retries = PipelineEnv.stage_retries()
        self.node_retries = max(0, int(node_retries))
        self.timings: Dict[G.NodeId, float] = {}
        self.deadline = guard.as_deadline(deadline)
        self._stage_seconds = guard.stage_deadline_seconds()
        self._breaker_threshold = guard.stage_breaker_threshold()
        #: the shared-stage tier is active only when ALL THREE are
        #: given: a pool without a token could leak results across
        #: different request batches
        self._pool = stage_pool if pool_token is not None else None
        self._pool_token = pool_token
        self._pool_sigs: Dict[G.NodeId, tuple] = dict(pool_sigs or {})

    def execute(self, target: G.GraphId):
        if isinstance(target, G.SinkId):
            target = self.graph.sink_dependencies[target]
        return self._eval(target)

    def _eval(self, target: G.GraphId):
        if target in self.results:
            return self.results[target]
        if isinstance(target, G.SourceId):
            raise RuntimeError(
                f"unbound source {target}: apply the pipeline to data before executing"
            )
        op = self.graph.operators[target]
        # shared-stage pool read-through BEFORE the dep walk: a hit on
        # the sharing frontier prunes the whole prefix sub-walk (that
        # pruning IS the multi-tenant win — the first co-served tenant
        # computed it this flush).  Key = (content-addressed prefix
        # signature, flush token): results can never leak across
        # different request batches.
        pool_sig = (
            self._pool_sigs.get(target) if self._pool is not None else None
        )
        if pool_sig is not None:
            hit, pooled = self._pool.get((pool_sig, self._pool_token))
            if hit:
                self.results[target] = pooled
                return pooled
        deps = [self._eval(d) for d in self.graph.dependencies[target]]
        from keystone_tpu.obs import ledger, metrics
        from keystone_tpu.utils import guard

        brk = self._stage_breaker(op, target)
        delays = None
        failed_seconds = 0.0
        degraded = False
        attempts_made = 0
        with ledger.span(
            "executor.stage", node=op.label(), node_id=target.id
        ) as sp:
            if brk is not None and not brk.allow():
                # the node's breaker is open: don't spend an attempt (or
                # deadline budget) on a stage presumed broken — degrade
                # immediately, or refuse with CircuitOpenError
                t0 = time.perf_counter()
                result = self._degrade(op, deps, reason="breaker_open")
                degraded = True
            else:
                for attempt in range(self.node_retries + 1):
                    attempts_made = attempt + 1
                    # t0 restarts per attempt: profile timings charge each
                    # node ONLY its successful attempt — failed attempts and
                    # the retry backoff sleeps used to skew
                    # ProfilingAutoCacheRule placement (a flaky node looked
                    # expensive exactly when it should not have)
                    t0 = time.perf_counter()
                    try:
                        # the fault site sits INSIDE the retry scope — and
                        # inside the watchdog, so an injected hang is
                        # converted to DeadlineExceeded (an OSError) and
                        # retried/degraded exactly like a raised fault,
                        # which is what the chaos tests assert
                        from keystone_tpu.faults import fault_point

                        def _run():
                            fault_point("executor.stage", node=op.label())
                            return self._execute_op(op, deps)

                        result = guard.run_with_deadline(
                            _run,
                            self._attempt_deadline(),
                            site="executor.stage",
                            node=op.label(),
                        )
                        if brk is not None:
                            brk.record_success()
                        break
                    except Exception as e:
                        failed_seconds += time.perf_counter() - t0
                        # a blown EXECUTOR-wide budget ends the stage's
                        # retry loop immediately: every further attempt
                        # would be born expired, and the backoff sleeps
                        # alone could overshoot the promised wall-clock
                        # bound by node_retries × max_delay per node.
                        # Likewise a breaker THIS failure just opened:
                        # retrying against it repeats exactly the cost
                        # the breaker exists to stop paying (state(),
                        # not allow(), so no half-open probe is consumed)
                        budget_blown = (
                            self.deadline is not None and self.deadline.expired()
                        )
                        if brk is not None and not budget_blown:
                            # born-expired attempts after the run budget
                            # blew are artifacts of the OVERALL deadline,
                            # not evidence about this node — charging
                            # them would open healthy nodes' breakers
                            # (which persist across fits in-process)
                            brk.record_failure()
                        breaker_opened = (
                            brk is not None and brk.state() == guard.OPEN
                        )
                        if (
                            attempt >= self.node_retries
                            or budget_blown
                            or breaker_opened
                        ):
                            if _degradable(op) is not None:
                                # budget spent on a node that declared a
                                # substitute: degrade instead of failing
                                # the whole run.  t0 restarts so profile
                                # timings charge the node only the
                                # SUBSTITUTE's cost — the failed attempt
                                # (possibly a full deadline wait) is
                                # retry-budget cost, not compute profile
                                t0 = time.perf_counter()
                                result = self._degrade(
                                    op, deps, reason="budget_exhausted", error=e
                                )
                                degraded = True
                                break
                            if failed_seconds:
                                metrics.inc(
                                    "executor.failed_attempt_seconds", failed_seconds
                                )
                            raise
                        metrics.inc("executor.stage_retries")
                        ledger.event(
                            "executor.retry",
                            node=op.label(),
                            attempt=attempt + 1,
                            error=f"{type(e).__name__}: {e}"[:200],
                        )
                        logger.warning(
                            "stage %s failed (%s); retry %d/%d",
                            op.label(),
                            e,
                            attempt + 1,
                            self.node_retries,
                        )
                        # brief backoff (+jitter) before the re-run: transient
                        # causes (preemption, flaky interconnect) need a beat to
                        # clear, and decorrelating parallel executors helps
                        if delays is None:
                            from keystone_tpu.utils.durable import backoff_delays

                            delays = iter(
                                backoff_delays(
                                    self.node_retries, base_delay=0.05, max_delay=1.0
                                )
                            )
                        time.sleep(next(delays, 1.0))
            if failed_seconds:
                # failed-attempt time is real cost, but it belongs to the
                # RETRY budget, not the node's compute profile
                metrics.inc("executor.failed_attempt_seconds", failed_seconds)
            if sp is not None:
                # attempts = stage-body executions actually started (0
                # when the breaker refused the stage outright)
                sp.set(attempts=attempts_made, retries=max(0, attempts_made - 1))
                if degraded:
                    sp.set(degraded=True)
                if failed_seconds:
                    sp.set(failed_attempt_seconds=failed_seconds)
            if self.profile:
                _sync_expr(result)
                self.timings[target] = time.perf_counter() - t0
        if pool_sig is not None and not degraded:
            # publish for the flush's co-served tenants.  NEVER publish
            # a degraded result: a substitute's output is this run's
            # compromise, not the stage's value — sharing it would
            # silently degrade every other tenant too.
            self._pool.put((pool_sig, self._pool_token), result)
        if not getattr(op, "no_memoize", False):
            # no_memoize nodes (over the HBM budget — workflow/profiling.py)
            # recompute per consumer instead of pinning their output
            self.results[target] = result
        return result

    def _attempt_deadline(self):
        """Per-attempt watchdog budget, or None (the inert path: no
        thread is spawned).  With an executor-wide deadline, the
        remaining time is apportioned evenly over not-yet-executed
        nodes — recomputed each stage, so early finishers donate their
        slack — and never outlives the overall deadline; the
        KEYSTONE_STAGE_DEADLINE env knob caps each attempt on top."""
        from keystone_tpu.utils import guard

        if self.deadline is None:
            if self._stage_seconds is None:
                return None
            return guard.Deadline.after(self._stage_seconds)
        remaining_nodes = max(1, len(self.graph.operators) - len(self.results))
        share = self.deadline.remaining() / remaining_nodes
        if self._stage_seconds is not None:
            share = min(share, self._stage_seconds)
        return self.deadline.child(share)

    def _stage_breaker(self, op, target):
        """The node's circuit breaker, or None when breakers are off
        (no KEYSTONE_BREAKER_THRESHOLD — the default, costing one
        attribute check per stage).

        Key choice: label alone collides (every DelegatingOperator is
        labelled 'apply'; same-class transformers share a class name),
        and one flaky node must never open the breaker of a healthy
        twin.  The key therefore adds the transformer's stable
        ``signature()`` when it has one — parameter-identical nodes
        share breaker state across executors/fits in this process,
        which is the registry's point — and falls back to the
        transformer/operator OBJECT identity for signatureless nodes
        (graph node ids restart per graph, so they would collide across
        independently-built pipelines; object identity persists across
        executors over the same graph, which is the case that matters)."""
        if self._breaker_threshold is None:
            return None
        from keystone_tpu.utils import guard

        t = getattr(op, "transformer", None)
        sig = None
        if t is not None:
            try:
                sig = t.signature()
            except Exception:
                sig = None
        if sig is not None:
            disc = f"{hash(sig) & 0xFFFFFFFF:08x}"
        else:
            # monotonic token stamped on the object, NOT id(): the
            # registry outlives the graph, and CPython readily recycles
            # a freed object's address — an id key could hand a healthy
            # new node a dead node's OPEN breaker
            obj = t if t is not None else op
            disc = getattr(obj, "_breaker_token", None)
            if disc is None:
                disc = f"t{next(_BREAKER_TOKENS)}"
                try:
                    obj._breaker_token = disc
                except AttributeError:
                    # unwritable object (slots/frozen): fall back to a
                    # fresh token per executor construction — state
                    # persists within this executor's walk only
                    pass
        return guard.breaker(
            f"executor.stage:{op.label()}:{disc}",
            threshold=self._breaker_threshold,
        )

    def _degrade(self, op, deps, reason: str, error=None):
        """Apply the node's degradation substitute (declared fallback,
        or Identity for ``optional`` nodes) instead of the node itself,
        emitting the ``degraded`` ledger event + counter.  A
        non-degradable node refused by its breaker raises
        ``CircuitOpenError`` — the run fails loudly, never silently
        skips a mandatory stage."""
        from keystone_tpu.obs import ledger, metrics
        from keystone_tpu.utils import guard

        sub = _degradable(op)
        if sub is None:
            raise guard.CircuitOpenError(
                f"stage {op.label()!r}: circuit breaker is open and the "
                "node declares no fallback/optional degradation"
            )
        metrics.inc("executor.degraded", node=op.label())
        ledger.event(
            "degraded",
            node=op.label(),
            substitute=sub.label,
            reason=reason,
            error=None
            if error is None
            else f"{type(error).__name__}: {error}"[:200],
        )
        logger.warning(
            "stage %s degraded to %s (%s)", op.label(), sub.label, reason
        )
        return _apply_transformer(sub, deps)

    def _execute_op(self, op: G.Operator, deps):
        if isinstance(op, G.DatasetOperator):
            return DatasetExpr(as_dataset(op.dataset))
        if isinstance(op, G.DatumOperator):
            return DatumExpr(op.datum)
        if isinstance(op, G.TransformerOperator):
            return _apply_transformer(op.transformer, deps)
        if isinstance(op, G.EstimatorOperator):
            return _fit_estimator(op.estimator, deps)
        if isinstance(op, G.DelegatingOperator):
            t = deps[0]
            if not isinstance(t, TransformerExpr):
                raise TypeError("DelegatingOperator expects a fitted transformer dep 0")
            return _apply_transformer(t.transformer, deps[1:])
        if isinstance(op, G.GatherOperator):
            return _gather(deps)
        raise TypeError(f"unknown operator {op!r}")


def _degradable(op):
    """The substitute transformer a failed node degrades to: its
    declared ``fallback``, :class:`Identity` for ``optional`` nodes,
    else None (the node is mandatory — failure propagates)."""
    t = getattr(op, "transformer", None)
    if t is None:
        return None
    fb = getattr(t, "fallback", None)
    if fb is not None:
        return fb
    if getattr(t, "optional", False):
        from keystone_tpu.workflow.transformer import Identity

        return Identity()
    return None


def block_on_arrays(obj, _seen=None, _depth=0, visit=None) -> None:
    """Block until every device array reachable from ``obj`` is computed.

    Transformers are plain objects, not pytrees, and solvers nest state
    (e.g. a model holding a scaler holding mean/std arrays) — a flat
    ``jax.tree.leaves(vars(t))`` walk stops at the nested object and
    misses its arrays, silently under-blocking.  This walks attributes,
    containers, and dataclass-like objects recursively (cycle-safe).

    ``visit``: optional callback applied to each device array INSTEAD of
    blocking — FittedPipeline.read_back uses it to force a real
    device→host read per array (axon's block_until_ready returns
    without draining the stream)."""
    if _depth > 8:
        return
    if _seen is None:
        _seen = set()
    if id(obj) in _seen:
        return
    _seen.add(id(obj))
    if hasattr(obj, "block_until_ready"):
        if visit is not None:
            visit(obj)
        else:
            obj.block_until_ready()
        return
    if isinstance(obj, dict):
        children = list(obj.values())
    elif isinstance(obj, (list, tuple, set, frozenset)):
        children = list(obj)
    elif hasattr(obj, "__dict__") and not isinstance(obj, type):
        children = list(vars(obj).values())
    else:
        return
    for c in children:
        if c is not None and not isinstance(c, (str, bytes, int, float, bool)):
            block_on_arrays(c, _seen, _depth + 1, visit=visit)


def _sync_expr(result) -> None:
    """Block until a node's result is actually computed, so profile-mode
    timings charge each node its own device time.  Fit nodes return a
    Transformer (not a pytree) — block on every array it holds (including
    nested model state), else the async solve would be misattributed to
    the next dataset-producing node."""
    if isinstance(result, DatasetExpr):
        result.dataset.cache()
    elif isinstance(result, DatumExpr):
        block_on_arrays(result.value)
    elif isinstance(result, TransformerExpr):
        block_on_arrays(result.transformer)


def _apply_transformer(t: Transformer, deps):
    if len(deps) != 1:
        raise ValueError(f"{t.label}: transformers are unary, got {len(deps)} deps")
    d = deps[0]
    if isinstance(d, DatasetExpr):
        return DatasetExpr(t.apply_dataset(d.dataset))
    if isinstance(d, DatumExpr):
        return DatumExpr(t.apply_one(d.value))
    raise TypeError(f"{t.label}: cannot apply to {d!r}")


def _gather(deps):
    import jax.numpy as jnp

    from keystone_tpu.workflow.dataset import StreamDataset

    if all(isinstance(d, DatasetExpr) for d in deps):
        if any(isinstance(d.dataset, StreamDataset) for d in deps):
            if not all(isinstance(d.dataset, StreamDataset) for d in deps):
                raise TypeError(
                    "Gather mixes streaming and materialized branches; "
                    "the branches of one source are either all streams or none"
                )
            return DatasetExpr(StreamDataset.zip_concat([d.dataset for d in deps]))
        base = deps[0].dataset
        arrs = [d.dataset.array for d in deps]
        return DatasetExpr(base.with_array(jnp.concatenate(arrs, axis=-1)))
    if all(isinstance(d, DatumExpr) for d in deps):
        import jax.numpy as jnp

        return DatumExpr(jnp.concatenate([jnp.asarray(d.value) for d in deps], axis=-1))
    raise TypeError("Gather expects homogeneous dataset or datum deps")


def _fit_estimator(est: Estimator, deps):
    data = deps[0]
    if not isinstance(data, DatasetExpr):
        raise TypeError(f"{est.label}.fit expects a dataset dependency")
    if isinstance(est, LabelEstimator):
        if len(deps) < 2 or not isinstance(deps[1], DatasetExpr):
            raise TypeError(f"{est.label}.fit expects (data, labels) dataset deps")
        fitted = est.fit_dataset(data.dataset, deps[1].dataset)
    else:
        fitted = est.fit_dataset(data.dataset)
    return TransformerExpr(fitted)
