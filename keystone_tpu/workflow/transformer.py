"""Transformer — the framework's single extension point.

Reference: workflow/Transformer.scala § Transformer[A,B] — an abstract
unary op with ``apply(a: A): B`` plus ``apply(RDD[A]): RDD[B]`` (default
``rdd.map``), ``andThen`` composition, and ``Transformer.apply(fn)`` for
lambda nodes.

TPU translation: ``apply_one`` is the per-datum op; the batch path
``apply_batch`` defaults to ``vmap(apply_one)`` over a sharded device
array — XLA compiles and shards it, replacing closure-shipped executor
map tasks.  Most concrete ops override ``apply_batch`` directly with
natively-batched code (conv, einsum), which is both simpler and faster
than the reference's per-datum formulation.
"""

from __future__ import annotations

import weakref
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.workflow.dataset import Dataset, as_dataset

#: per-transformer jitted apply_batch wrappers (see _apply_batch_jitted)
_JIT_APPLY_CACHE = weakref.WeakKeyDictionary()

#: CLASS-shared jitted applies for transformers declaring traced_attrs:
#: (cls, jit_static(), input signature, param signature) -> jitted fn
#: (or None = memoized untraceable for that exact signature).  Values
#: hold parameter-stripped template copies, never fitted arrays.
_SHARED_APPLY_CACHE: dict = {}


def stripped_template(t: "Transformer") -> "Transformer":
    """Shallow copy of ``t`` safe to pin in a process-lifetime shared
    cache: traced_attrs are nulled (they arrive as traced arguments),
    and derived caches that hold strong refs to fitted arrays — the
    cached_fingerprint attr (``_fp``) and per-instance jit dicts — are
    dropped, or the template would pin the first fit's arrays forever.
    The single source for both shared-apply sites (Transformer and
    FusedTransformer)."""
    import copy

    tpl = copy.copy(t)
    for name in type(t).traced_attrs:
        setattr(tpl, name, None)
    for derived in ("_fp", "_jitted"):
        if derived in getattr(tpl, "__dict__", {}):
            try:
                delattr(tpl, derived)
            except AttributeError:
                pass
    return tpl


def traced_param_sig(t: "Transformer") -> tuple:
    """Hashable structure signature of an instance's traced parameters
    (pytree treedef + leaf dtypes per attr).  Part of the shared-cache
    key, so an instance whose parameter VALUES cannot trace poisons only
    its own signature — never the whole class."""
    sig = []
    for name in type(t).traced_attrs:
        v = getattr(t, name)
        if v is None:
            sig.append((name, None))
        else:
            leaves, treedef = jax.tree_util.tree_flatten(v)
            sig.append(
                (
                    name,
                    str(treedef),
                    tuple(str(getattr(x, "dtype", type(x).__name__)) for x in leaves),
                )
            )
    return tuple(sig)

#: canonical apply chunk (rows); 0 = whole-batch applies.
#: Chunking pins the compiled programs' shapes so they stop scaling
#: with dataset size.  DEFAULT ON since r5, decided by program COUNT
#: (VERDICT r4 item 4 — wall clock was the wrong instrument under this
#: environment's ambient drift): at a NEW dataset size n=8192, the
#: chunked fit ran 88/88 programs from the persistent compile cache
#: (ZERO cold compiles; wall 44.6 s → 11.5 s) where the unchunked fit
#: paid 9 cold full-shape compiles; n=4096 cold-shape: 29 (one-time
#: chunk plumbing) vs 46 misses and 79.5 s → 50.7 s (BASELINE.md r5
#: "chunked applies by program count").  The warm bench-fit path
#: (n=2048 ≤ chunk) takes the whole-batch branch and is unaffected.
#: Bit-parity with whole-batch applies is pinned by
#: tests/test_workflow.py; multi-device meshes still disable chunking
#: (per-chunk resharding collectives — see _apply_chunk_rows).
_APPLY_CHUNK_DEFAULT = 2048


def _apply_chunk_rows() -> int:
    """Row-chunk size for device applies; 0 disables.

    ``KEYSTONE_APPLY_CHUNK`` is a FORCE flag: it bypasses the
    multi-device guard below (the mesh-sharded tests opt in through it
    deliberately — a row slice of a sharded array pays per-chunk
    resharding collectives, which is a performance hazard, not a
    correctness one).  The default-path guard disables chunking
    whenever the data mesh spans >1 device, where per-shard shapes are
    already smaller."""
    import os

    env = os.environ.get("KEYSTONE_APPLY_CHUNK", "").strip()
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "KEYSTONE_APPLY_CHUNK=%r is not an integer; chunking "
                "stays DISABLED",
                env,
            )
            return 0
    if not _APPLY_CHUNK_DEFAULT:
        return 0
    try:
        from keystone_tpu.parallel.mesh import active_mesh

        m = active_mesh()
        if m is not None and m.devices.size > 1:
            return 0
    except Exception:
        pass
    try:
        if len(jax.devices()) > 1:
            return 0
    except Exception:
        return 0
    return _APPLY_CHUNK_DEFAULT


def iter_row_chunks(arr, mask, chunk: int):
    """Yield ``(rows, mask_rows, start)`` in fixed-size row chunks, the
    ragged tail PADDED UP to ``chunk`` (mask pad rows are zero — callers
    slice outputs back to the true row count).  The single source of the
    chunk/pad discipline shared by Transformer._apply_dataset_chunked
    and ColumnSampler's offset-keyed chunked sampling — their bit-parity
    guarantees both ride this one implementation."""
    for i in range(0, arr.shape[0], chunk):
        a = arr[i : i + chunk]
        m = mask[i : i + chunk] if mask is not None else None
        short = chunk - a.shape[0]
        if short > 0:
            a = jnp.pad(a, ((0, short),) + ((0, 0),) * (a.ndim - 1))
            if m is not None:
                m = jnp.pad(m, ((0, short),) + ((0, 0),) * (m.ndim - 1))
        yield a, m, i


class Chainable:
    """Mixin providing ``and_then`` / ``__or__`` composition sugar."""

    def and_then(self, nxt, data=None, labels=None):
        from keystone_tpu.workflow.pipeline import Pipeline

        return Pipeline.of(self).and_then(nxt, data=data, labels=labels)

    def __or__(self, nxt):
        return self.and_then(nxt)


class Transformer(Chainable):
    #: True for ops that run on host Python objects (e.g. tokenizers).
    is_host: bool = False
    #: host ops whose per-item work is trivial (a str method) opt OUT of
    #: the host_map worker pool — IPC would dwarf the work
    parallel_host: bool = True
    #: Names of array-valued (or None) instance attributes passed as
    #: TRACED arguments to a class-shared jitted apply_batch, so every
    #: instance of the class shares ONE compiled program per input
    #: signature.  Two measured wins (BASELINE.md r5 "traced-parameter
    #: applies"): N instances stop tracing/compiling N duplicate
    #: programs, and fitted device arrays stop being closure constants —
    #: jax lowering reads every closed-over device array back to host
    #: (~0.4 s tunnel RTT per array here, stacking to the fit's 4.7 s
    #: worst node), and embedding VALUES keys the persistent compile
    #: cache by the fit's bits, so every refit recompiled from scratch.
    #: Declaring classes must route every OTHER attribute that shapes
    #: the trace through jit_static().  Empty = per-instance programs.
    traced_attrs: tuple = ()
    #: True for transformers whose apply_batch manages its OWN jit and
    #: program cache (FusedTransformer).  The generic per-instance jit
    #: wrapper must NOT wrap these: an outer per-instance jit would
    #: inline the inner program and embed its traced stage parameters
    #: as outer-program constants, nullifying cross-instance sharing.
    self_jitted: bool = False
    #: Graceful degradation (workflow/executor.py): an ``optional``
    #: stage whose retry/deadline budget is exhausted — or whose circuit
    #: breaker is open — is replaced by :class:`Identity` (its input
    #: passes through untouched) instead of failing the run.  A
    #: ``fallback`` transformer (set via :meth:`with_fallback`) is the
    #: substitute applied instead.  Default: neither — failure
    #: propagates, exactly as before.
    optional: bool = False
    fallback: Optional["Transformer"] = None

    def with_fallback(self, substitute: "Transformer") -> "Transformer":
        """A copy of this transformer that degrades to ``substitute``:
        when this stage's failure budget (retries, deadline) is spent or
        its breaker is open, the executor applies ``substitute`` to the
        stage's input and emits a ``degraded`` ledger event instead of
        failing the run.  The substitute must accept the same input
        (e.g. a cheaper featurizer, or a constant-output scorer)."""
        import copy

        c = copy.copy(self)
        c.fallback = substitute
        return c

    @property
    def label(self) -> str:
        return type(self).__name__

    # ---------------------------------------------------------- identity
    def params(self):
        """Hashable parameter tuple for CSE equality; None => never merged."""
        return None

    def signature(self):
        p = self.params()
        if p is None:
            return None
        sig = (type(self).__name__, p)
        if self.optional or self.fallback is not None:
            # degradation declarations are part of node identity: CSE
            # merging an optional/fallback node with a plain twin would
            # silently widen (or drop) the degradation contract
            fb = self.fallback
            fb_sig = None if fb is None else (fb.signature() or id(fb))
            sig = sig + ("degrade", self.optional, fb_sig)
        return sig

    def jit_static(self):
        """Hashable key covering every non-traced attribute that affects
        apply_batch's trace structure; part of the shared-program cache
        key for classes declaring traced_attrs."""
        return ()

    # Optimizer hook: physical-operator choice (workflow/NodeOptimizationRule).
    def choose_physical(self, sample) -> "Transformer":
        """Return the best physical implementation of this logical
        transformer given a data sample (shapes).  Default: self."""
        return self

    # ------------------------------------------------------------- apply
    def apply_one(self, x):
        raise NotImplementedError(type(self).__name__)

    def apply_batch(self, xs, mask=None):
        """Batched apply; default is vmap of apply_one."""
        return jax.vmap(self.apply_one)(xs)

    def apply_dataset(self, ds: Dataset) -> Dataset:
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(ds, StreamDataset):
            if ds.is_host:
                if not self.is_host:
                    raise TypeError(
                        f"{self.label} is a device transformer; this stream "
                        "carries host objects. Featurize to arrays first."
                    )
                # host transformer over a host stream: map items lazily,
                # batch by batch — the raw corpus never materializes.
                # host_map fans large batches over worker processes on
                # multi-core hosts (raise stream_batch_size to engage
                # it); small batches, single-core hosts, and trivial ops
                # (parallel_host=False) map sequentially
                if self.parallel_host:
                    from keystone_tpu.utils.hostmap import host_map

                    out = ds.map_batches(
                        lambda batch, _mask: host_map(self.apply_one, batch)
                    )
                else:
                    out = ds.map_batches(
                        lambda batch, _mask: [self.apply_one(x) for x in batch]
                    )
                # provenance for the native text fast path: the base raw
                # stream plus the host transformers applied since —
                # consumers (ops/nlp_native) can re-run the whole chain
                # in C++ from the raw docs instead of the per-item maps
                base, stages = getattr(ds, "_host_chain", None) or (ds, ())
                out._host_chain = (base, stages + (self,))
                return out
            if self.is_host:
                raise TypeError(
                    f"{self.label} is a host transformer; streams carry device "
                    "batches. Featurize to arrays before streaming."
                )
            return ds.map_batches(self._apply_batch_jitted)
        if ds.is_host or self.is_host:
            if self.is_host and self.parallel_host:
                # pure-Python host op: worker-pool for large inputs
                # (device transformers stay sequential — worker
                # processes must never run device code)
                from keystone_tpu.utils.hostmap import host_map

                out = host_map(self.apply_one, ds.items)
            else:
                out = [self.apply_one(x) for x in ds.items]
            if out and isinstance(out[0], (jnp.ndarray,)) or _stackable(out):
                try:
                    return ds.with_array(jnp.stack([jnp.asarray(o) for o in out]))
                except (TypeError, ValueError):
                    pass
            res = ds.with_items(out)
            # provenance for the native text fast path, mirroring the
            # host-STREAM branch above: in-memory host datasets chain
            # through with_items, so downstream featurizers can re-run
            # the whole chain in C++ from the base items
            base, stages = getattr(ds, "_host_chain", None) or (ds, ())
            res._host_chain = (base, stages + (self,))
            return res
        chunk = _apply_chunk_rows()
        if chunk and ds.array.shape[0] > chunk:
            return self._apply_dataset_chunked(ds, chunk)
        result = self._apply_batch_jitted(ds.array, ds.mask)
        if isinstance(result, tuple):  # (values, mask) for ragged producers
            return ds.with_array(result[0], mask=result[1])
        return ds.with_array(result)

    def _apply_dataset_chunked(self, ds: Dataset, chunk: int) -> Dataset:
        """Apply in fixed-size row chunks (the ragged tail padded UP to
        the canonical chunk, then sliced off) so the number of distinct
        compiled programs stops scaling with dataset size: an n=8192 fit
        re-traced and cache-loaded every stage at 8192-row shapes — the
        measured ~60 s of a 79 s fit — where the 2048-row programs were
        already warm from smaller runs.  Semantically free: transformer
        apply IS a per-item map (apply_one is the contract), so chunk
        boundaries cannot change any row.  Disabled on multi-device data
        meshes (``_apply_chunk_rows`` → 0): a row slice of a sharded
        array would trigger resharding collectives per chunk."""
        arr, mask = ds.array, ds.mask
        n0 = arr.shape[0]
        vals, masks = [], []
        for a, m, _start in iter_row_chunks(arr, mask, chunk):
            r = self._apply_batch_jitted(a, m)
            if isinstance(r, tuple):
                vals.append(r[0])
                masks.append(r[1])
            else:
                vals.append(r)
        out = jnp.concatenate(vals, axis=0)[:n0]
        if masks:
            return ds.with_array(
                out, mask=jnp.concatenate(masks, axis=0)[:n0]
            )
        return ds.with_array(out)

    def _apply_batch_jitted(self, xs, mask):
        """Run apply_batch as ONE compiled program.

        Un-fused nodes (raw-graph execution: saved-state walks, single-node
        applies) would otherwise dispatch op-by-op eagerly — slower, and on
        the axon TPU backend an eager FFT dispatch corrupts the device
        stream for the rest of the process.  Untraceable apply_batch
        implementations (host-side numpy, data-dependent Python) fall back
        to the eager path.

        The per-instance cache is keyed by (matmul mode, traced signature):
        the mode key — the RESOLVED policy, one of f32/bf16/bf16_apply,
        so e.g. enabling the bf16 apply path (utils/precision.py §
        bf16_apply) retraces every chunked/whole-batch apply instead of
        reusing a stale executable — and the signature key confines a
        trace failure to the one input signature that caused it: one odd
        mask/dtype combination must not pin every later call of this
        instance to the eager path."""
        from keystone_tpu.utils import precision

        if type(self).self_jitted:
            return self.apply_batch(xs, mask=mask)
        # Keyed by (mode, dtype, rank, mask-presence) — NOT concrete shapes:
        # jit itself retraces per shape under one wrapper, and traceability
        # failures are dtype/mask/structure-driven, so a shape-keyed memo
        # would re-pay a doomed trace (and re-warn) for every ragged batch.
        sig = (
            precision.matmul_mode(),
            str(getattr(xs, "dtype", "")),
            getattr(xs, "ndim", None),
            None if mask is None else str(getattr(mask, "dtype", "")),
        )
        if type(self).traced_attrs:
            return self._apply_batch_shared(xs, mask, sig)
        entry = _JIT_APPLY_CACHE.get(self)
        if entry is None:
            entry = {}
            _JIT_APPLY_CACHE[self] = entry
        sentinel = object()
        fn = entry.get(sig, sentinel)
        if fn is None:  # memoized "untraceable" FOR THIS SIGNATURE
            return self.apply_batch(xs, mask=mask)
        if fn is sentinel:
            # weak cache, NOT an instance attribute: jitted callables are
            # unpicklable and must not ride along in FittedPipeline.save.
            # The closure holds weakref.ref(self) — closing over self
            # would make the cache VALUE pin its own KEY alive forever.
            self_ref = weakref.ref(self)
            fn = jax.jit(lambda a, m: self_ref().apply_batch(a, mask=m))
            entry[sig] = fn
        try:
            return fn(xs, mask)
        except (TypeError, jax.errors.JAXTypeError):
            entry[sig] = None  # don't re-pay a failed trace for this sig
            import logging

            logging.getLogger(__name__).warning(
                "%s.apply_batch is untraceable for signature %s; using the "
                "eager path (hazardous on the axon backend for FFT ops)",
                self.label,
                sig,
            )
            return self.apply_batch(xs, mask=mask)

    def _apply_batch_shared(self, xs, mask, sig):
        """Class-shared jitted apply for traced_attrs declarers.

        The jitted callable closes over a parameter-STRIPPED template
        copy of the first instance seen per (class, jit_static) key and
        rebinds the traced attributes to tracer values at trace time —
        so the compiled program is a pure function of parameter shapes,
        shared by every instance and every refit."""
        import copy

        cls = type(self)
        params = {}
        for name in cls.traced_attrs:
            v = getattr(self, name)
            if v is not None and any(
                isinstance(leaf, np.ndarray)
                for leaf in jax.tree_util.tree_leaves(v)
            ):
                # host-resident parameters (e.g. an unpickled model, or
                # a pytree like FisherVector.gmm holding numpy arrays)
                # would re-transfer on EVERY call as jit arguments;
                # commit them to device once, on the instance
                v = jax.tree_util.tree_map(
                    lambda a: jnp.asarray(a) if isinstance(a, np.ndarray) else a,
                    v,
                )
                setattr(self, name, v)
            params[name] = v
        key = (cls, self.jit_static(), sig, traced_param_sig(self))
        sentinel = object()
        fn = _SHARED_APPLY_CACHE.get(key, sentinel)
        if fn is None:  # memoized "untraceable" for this exact signature
            return self.apply_batch(xs, mask=mask)
        if fn is sentinel:
            template = stripped_template(self)

            def run(p, a, m):
                obj = copy.copy(template)
                for name, v in p.items():
                    setattr(obj, name, v)
                return obj.apply_batch(a, mask=m)

            fn = _SHARED_APPLY_CACHE[key] = jax.jit(run)
        try:
            return fn(params, xs, mask)
        except (TypeError, jax.errors.JAXTypeError):
            _SHARED_APPLY_CACHE[key] = None
            import logging

            logging.getLogger(__name__).warning(
                "%s.apply_batch is untraceable for signature %s; using the "
                "eager path (hazardous on the axon backend for FFT ops)",
                self.label,
                sig,
            )
            return self.apply_batch(xs, mask=mask)

    def __call__(self, x):
        from keystone_tpu.workflow.pipeline import Pipeline, PipelineDataset

        if isinstance(x, (Pipeline, PipelineDataset)):
            return Pipeline.of(self)(x)
        if isinstance(x, Dataset):
            return self.apply_dataset(x)
        return self.apply_one(x)

    def __repr__(self):
        return self.label


class LambdaTransformer(Transformer):
    """``Transformer.apply(fn)`` analogue: wrap a function as a node."""

    def __init__(
        self,
        fn: Callable,
        batch_fn: Optional[Callable] = None,
        name: str = "Lambda",
        host: bool = False,
    ):
        self._fn = fn
        self._batch_fn = batch_fn
        self._name = name
        self.is_host = host

    @property
    def label(self):
        return self._name

    def apply_one(self, x):
        return self._fn(x)

    def apply_batch(self, xs, mask=None):
        if self._batch_fn is not None:
            return self._batch_fn(xs)
        return jax.vmap(self._fn)(xs)


def transformer(fn=None, *, batch=None, name=None, host=False):
    """Decorator/factory for lambda nodes: ``transformer(lambda x: x * 2)``."""

    def make(f):
        return LambdaTransformer(
            f, batch_fn=batch, name=name or getattr(f, "__name__", "Lambda"), host=host
        )

    if fn is not None:
        return make(fn)
    return make


class Identity(Transformer):
    def params(self):
        return ()

    def apply_one(self, x):
        return x

    def apply_batch(self, xs, mask=None):
        return xs


class Cacher(Transformer):
    """Identity that forces materialization — the unit of the caching
    optimizer (nodes/util/Cacher.scala).  On TPU this means "block until
    the stage's arrays are resident in HBM" so downstream stages (and the
    profiler) see a stage boundary rather than one fused program."""

    def params(self):
        return None  # each Cacher is its own node; never CSE-merged away

    def apply_one(self, x):
        return x

    def apply_dataset(self, ds: Dataset) -> Dataset:
        return ds.cache()


def _stackable(out) -> bool:
    import numpy as np

    return (
        len(out) > 0
        and all(hasattr(o, "shape") for o in out)
        and len({np.shape(o) for o in out}) == 1
    )
