"""Transformer — the framework's single extension point.

Reference: workflow/Transformer.scala § Transformer[A,B] — an abstract
unary op with ``apply(a: A): B`` plus ``apply(RDD[A]): RDD[B]`` (default
``rdd.map``), ``andThen`` composition, and ``Transformer.apply(fn)`` for
lambda nodes.

TPU translation: ``apply_one`` is the per-datum op; the batch path
``apply_batch`` defaults to ``vmap(apply_one)`` over a sharded device
array — XLA compiles and shards it, replacing closure-shipped executor
map tasks.  Most concrete ops override ``apply_batch`` directly with
natively-batched code (conv, einsum), which is both simpler and faster
than the reference's per-datum formulation.
"""

from __future__ import annotations

import weakref
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from keystone_tpu.workflow.dataset import Dataset, as_dataset

#: per-transformer jitted apply_batch wrappers (see _apply_batch_jitted)
_JIT_APPLY_CACHE = weakref.WeakKeyDictionary()


class Chainable:
    """Mixin providing ``and_then`` / ``__or__`` composition sugar."""

    def and_then(self, nxt, data=None, labels=None):
        from keystone_tpu.workflow.pipeline import Pipeline

        return Pipeline.of(self).and_then(nxt, data=data, labels=labels)

    def __or__(self, nxt):
        return self.and_then(nxt)


class Transformer(Chainable):
    #: True for ops that run on host Python objects (e.g. tokenizers).
    is_host: bool = False
    #: host ops whose per-item work is trivial (a str method) opt OUT of
    #: the host_map worker pool — IPC would dwarf the work
    parallel_host: bool = True

    @property
    def label(self) -> str:
        return type(self).__name__

    # ---------------------------------------------------------- identity
    def params(self):
        """Hashable parameter tuple for CSE equality; None => never merged."""
        return None

    def signature(self):
        p = self.params()
        return None if p is None else (type(self).__name__, p)

    # Optimizer hook: physical-operator choice (workflow/NodeOptimizationRule).
    def choose_physical(self, sample) -> "Transformer":
        """Return the best physical implementation of this logical
        transformer given a data sample (shapes).  Default: self."""
        return self

    # ------------------------------------------------------------- apply
    def apply_one(self, x):
        raise NotImplementedError(type(self).__name__)

    def apply_batch(self, xs, mask=None):
        """Batched apply; default is vmap of apply_one."""
        return jax.vmap(self.apply_one)(xs)

    def apply_dataset(self, ds: Dataset) -> Dataset:
        from keystone_tpu.workflow.dataset import StreamDataset

        if isinstance(ds, StreamDataset):
            if ds.is_host:
                if not self.is_host:
                    raise TypeError(
                        f"{self.label} is a device transformer; this stream "
                        "carries host objects. Featurize to arrays first."
                    )
                # host transformer over a host stream: map items lazily,
                # batch by batch — the raw corpus never materializes.
                # host_map fans large batches over worker processes on
                # multi-core hosts (raise stream_batch_size to engage
                # it); small batches, single-core hosts, and trivial ops
                # (parallel_host=False) map sequentially
                if self.parallel_host:
                    from keystone_tpu.utils.hostmap import host_map

                    return ds.map_batches(
                        lambda batch, _mask: host_map(self.apply_one, batch)
                    )
                return ds.map_batches(
                    lambda batch, _mask: [self.apply_one(x) for x in batch]
                )
            if self.is_host:
                raise TypeError(
                    f"{self.label} is a host transformer; streams carry device "
                    "batches. Featurize to arrays before streaming."
                )
            return ds.map_batches(self._apply_batch_jitted)
        if ds.is_host or self.is_host:
            if self.is_host and self.parallel_host:
                # pure-Python host op: worker-pool for large inputs
                # (device transformers stay sequential — worker
                # processes must never run device code)
                from keystone_tpu.utils.hostmap import host_map

                out = host_map(self.apply_one, ds.items)
            else:
                out = [self.apply_one(x) for x in ds.items]
            if out and isinstance(out[0], (jnp.ndarray,)) or _stackable(out):
                try:
                    return ds.with_array(jnp.stack([jnp.asarray(o) for o in out]))
                except (TypeError, ValueError):
                    pass
            return ds.with_items(out)
        result = self._apply_batch_jitted(ds.array, ds.mask)
        if isinstance(result, tuple):  # (values, mask) for ragged producers
            return ds.with_array(result[0], mask=result[1])
        return ds.with_array(result)

    def _apply_batch_jitted(self, xs, mask):
        """Run apply_batch as ONE compiled program.

        Un-fused nodes (raw-graph execution: saved-state walks, single-node
        applies) would otherwise dispatch op-by-op eagerly — slower, and on
        the axon TPU backend an eager FFT dispatch corrupts the device
        stream for the rest of the process.  Untraceable apply_batch
        implementations (host-side numpy, data-dependent Python) fall back
        to the eager path.

        The per-instance cache is keyed by (matmul mode, traced signature):
        the mode key makes precision-policy flips retrace instead of
        reusing a stale executable, and the signature key confines a trace
        failure to the one input signature that caused it — one odd
        mask/dtype combination must not pin every later call of this
        instance to the eager path."""
        from keystone_tpu.utils import precision

        # Keyed by (mode, dtype, rank, mask-presence) — NOT concrete shapes:
        # jit itself retraces per shape under one wrapper, and traceability
        # failures are dtype/mask/structure-driven, so a shape-keyed memo
        # would re-pay a doomed trace (and re-warn) for every ragged batch.
        sig = (
            precision.matmul_mode(),
            str(getattr(xs, "dtype", "")),
            getattr(xs, "ndim", None),
            None if mask is None else str(getattr(mask, "dtype", "")),
        )
        entry = _JIT_APPLY_CACHE.get(self)
        if entry is None:
            entry = {}
            _JIT_APPLY_CACHE[self] = entry
        sentinel = object()
        fn = entry.get(sig, sentinel)
        if fn is None:  # memoized "untraceable" FOR THIS SIGNATURE
            return self.apply_batch(xs, mask=mask)
        if fn is sentinel:
            # weak cache, NOT an instance attribute: jitted callables are
            # unpicklable and must not ride along in FittedPipeline.save.
            # The closure holds weakref.ref(self) — closing over self
            # would make the cache VALUE pin its own KEY alive forever.
            self_ref = weakref.ref(self)
            fn = jax.jit(lambda a, m: self_ref().apply_batch(a, mask=m))
            entry[sig] = fn
        try:
            return fn(xs, mask)
        except (TypeError, jax.errors.JAXTypeError):
            entry[sig] = None  # don't re-pay a failed trace for this sig
            import logging

            logging.getLogger(__name__).warning(
                "%s.apply_batch is untraceable for signature %s; using the "
                "eager path (hazardous on the axon backend for FFT ops)",
                self.label,
                sig,
            )
            return self.apply_batch(xs, mask=mask)

    def __call__(self, x):
        from keystone_tpu.workflow.pipeline import Pipeline, PipelineDataset

        if isinstance(x, (Pipeline, PipelineDataset)):
            return Pipeline.of(self)(x)
        if isinstance(x, Dataset):
            return self.apply_dataset(x)
        return self.apply_one(x)

    def __repr__(self):
        return self.label


class LambdaTransformer(Transformer):
    """``Transformer.apply(fn)`` analogue: wrap a function as a node."""

    def __init__(
        self,
        fn: Callable,
        batch_fn: Optional[Callable] = None,
        name: str = "Lambda",
        host: bool = False,
    ):
        self._fn = fn
        self._batch_fn = batch_fn
        self._name = name
        self.is_host = host

    @property
    def label(self):
        return self._name

    def apply_one(self, x):
        return self._fn(x)

    def apply_batch(self, xs, mask=None):
        if self._batch_fn is not None:
            return self._batch_fn(xs)
        return jax.vmap(self._fn)(xs)


def transformer(fn=None, *, batch=None, name=None, host=False):
    """Decorator/factory for lambda nodes: ``transformer(lambda x: x * 2)``."""

    def make(f):
        return LambdaTransformer(
            f, batch_fn=batch, name=name or getattr(f, "__name__", "Lambda"), host=host
        )

    if fn is not None:
        return make(fn)
    return make


class Identity(Transformer):
    def params(self):
        return ()

    def apply_one(self, x):
        return x

    def apply_batch(self, xs, mask=None):
        return xs


class Cacher(Transformer):
    """Identity that forces materialization — the unit of the caching
    optimizer (nodes/util/Cacher.scala).  On TPU this means "block until
    the stage's arrays are resident in HBM" so downstream stages (and the
    profiler) see a stage boundary rather than one fused program."""

    def params(self):
        return None  # each Cacher is its own node; never CSE-merged away

    def apply_one(self, x):
        return x

    def apply_dataset(self, ds: Dataset) -> Dataset:
        return ds.cache()


def _stackable(out) -> bool:
    import numpy as np

    return (
        len(out) > 0
        and all(hasattr(o, "shape") for o in out)
        and len({np.shape(o) for o in out}) == 1
    )
