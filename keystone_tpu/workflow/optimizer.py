"""Whole-pipeline optimizer.

Reference: workflow/Optimizer.scala — a Catalyst-style rule executor
(batches with Once/FixedPoint strategies) over the pipeline Graph, with
three rule families (SURVEY.md §2.1):

  - EquivalentNodeMergeRule: CSE — merge structurally identical subgraphs
    so e.g. two branches sharing SIFT compute it once.
  - AutoCacheRule: decide which shared outputs to materialize.
  - NodeOptimizationRule: per-node physical operator choice from sampled
    data statistics.

The TPU twist (SURVEY.md §7): XLA already does CSE/fusion *within* a
compiled stage; this optimizer works *across* stages — it decides
materialization points, and it fuses maximal linear chains of device
transformers into single jit-compiled stages (StageFusionRule), so a
featurization chain costs one XLA program, not one dispatch per node.
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional, Sequence

import jax

from keystone_tpu.workflow import graph as G
from keystone_tpu.workflow.estimator import Estimator
from keystone_tpu.workflow.transformer import Cacher, Transformer

logger = logging.getLogger(__name__)


class Rule:
    name: str = "rule"

    def apply(self, graph: G.Graph) -> G.Graph:
        raise NotImplementedError


class Once:
    def __init__(self):
        self.max_iterations = 1


class FixedPoint:
    def __init__(self, max_iterations: int = 20):
        self.max_iterations = max_iterations


class RuleBatch:
    def __init__(self, name: str, strategy, rules: Sequence[Rule]):
        self.name = name
        self.strategy = strategy
        self.rules = list(rules)


class Optimizer:
    """Executes rule batches until their strategy is exhausted or the graph
    stops changing (workflow/Optimizer.scala § RuleExecutor.execute)."""

    def __init__(self, batches: Sequence[RuleBatch]):
        self.batches = list(batches)

    def execute(self, graph: G.Graph) -> G.Graph:
        import time

        from keystone_tpu.obs import ledger, metrics

        with ledger.span("optimizer.execute"):
            for batch in self.batches:
                for _ in range(batch.strategy.max_iterations):
                    before = _graph_fingerprint(graph)
                    for rule in batch.rules:
                        t0 = time.perf_counter()
                        graph = rule.apply(graph)
                        dt = time.perf_counter() - t0
                        metrics.observe(
                            "optimizer.rule_seconds", dt, rule=rule.name
                        )
                        ledger.event(
                            "optimizer.rule",
                            rule=rule.name,
                            batch=batch.name,
                            seconds=dt,
                        )
                    if _graph_fingerprint(graph) == before:
                        break
        return graph


def _graph_fingerprint(g: G.Graph):
    return (
        tuple(sorted((n.id, id(op)) for n, op in g.operators.items())),
        tuple(sorted((n.id, tuple(d.id for d in ds)) for n, ds in g.dependencies.items())),
    )


# --------------------------------------------------------------------- CSE
class EquivalentNodeMergeRule(Rule):
    """Merge nodes whose operator + entire input prefix are structurally
    equal (workflow/EquivalentNodeMergeRule.scala).  This is what makes
    ``Pipeline.gather`` branches sharing a SIFT prefix compute it once."""

    name = "EquivalentNodeMerge"

    def apply(self, graph: G.Graph) -> G.Graph:
        memo: dict = {}
        groups: dict = {}
        for n in graph.topological_nodes():
            sig = graph.prefix_signature(n, memo)
            if sig is not None and sig[0] != "unique":
                groups.setdefault(sig, []).append(n)
        for sig, nodes in groups.items():
            if len(nodes) < 2:
                continue
            keep = min(nodes)
            for other in nodes:
                if other == keep:
                    continue
                graph = graph.replace_dependency(other, keep)
                graph = graph.remove_node(other)
        return graph


# ----------------------------------------------------------- materialization
class AutoMaterializeRule(Rule):
    """Insert Cacher nodes after outputs consumed by >1 dependent.

    The reference's AutoCacheRule profiles nodes on sampled partitions and
    greedily places ``.cache()`` calls under a cluster-memory budget
    (workflow/AutoCacheRule.scala).  Here the executor already memoizes
    per-node results, so "cache or recompute" is decided structurally:
    shared outputs get an explicit materialization barrier, which also
    pins them as stage boundaries for the fusion rule below.  A cost-model
    driven HBM-vs-recompute variant is the round-2 refinement.
    """

    name = "AutoMaterialize"

    def apply(self, graph: G.Graph) -> G.Graph:
        for n in list(graph.topological_nodes()):
            op = graph.operators.get(n)
            if not isinstance(op, (G.TransformerOperator,)):
                continue
            if isinstance(op.transformer, Cacher):
                continue
            deps_on_n = [d for d in graph.dependents(n) if not isinstance(d, G.SinkId)]
            already = any(
                isinstance(graph.operators.get(d), G.TransformerOperator)
                and isinstance(graph.operators[d].transformer, Cacher)
                for d in deps_on_n
                if isinstance(d, G.NodeId)
            )
            if len(deps_on_n) > 1 and not already:
                graph, cache_node = graph.add_node(
                    G.TransformerOperator(Cacher()), (n,)
                )
                for d in deps_on_n:
                    if isinstance(d, G.NodeId):
                        graph = graph.set_dependencies(
                            d,
                            tuple(
                                cache_node if x == n else x
                                for x in graph.dependencies[d]
                            ),
                        )
        return graph


# ------------------------------------------------------------- node choice
class NodeChoiceRule(Rule):
    """Physical operator selection (workflow/NodeOptimizationRule).

    For estimators that override ``choose_physical``, executes the
    estimator's input subgraph on a small sample (the analogue of the
    reference's optimizer-time sampling Spark jobs) and lets the estimator
    pick its best physical implementation — e.g. a local exact solve for
    small data vs the distributed block solver, or dense vs sparse LBFGS.
    """

    name = "NodeChoice"

    def __init__(self, sample_size: int = 256):
        self.sample_size = sample_size

    def apply(self, graph: G.Graph) -> G.Graph:
        from keystone_tpu.workflow.dataset import Dataset
        from keystone_tpu.workflow.executor import DatasetExpr, GraphExecutor
        from keystone_tpu.workflow.transformer import Transformer

        # full dataset size: lets size-based choices (local vs
        # distributed solve) see past the truncated sample
        full_n = max(
            (
                op.dataset.n if isinstance(op.dataset, Dataset) else len(op.dataset)
                for op in graph.operators.values()
                if isinstance(op, G.DatasetOperator)
            ),
            default=None,
        )
        for n in list(graph.topological_nodes()):
            op = graph.operators.get(n)
            if isinstance(op, G.EstimatorOperator):
                node = op.estimator
                overridden = (
                    type(node).choose_physical is not Estimator.choose_physical
                )
                rewrap = G.EstimatorOperator
            elif isinstance(op, G.TransformerOperator):
                node = op.transformer
                overridden = (
                    type(node).choose_physical is not Transformer.choose_physical
                )
                rewrap = G.TransformerOperator
            else:
                continue
            if not overridden:
                continue
            sample = None
            try:
                ex = _SampleExecutor(graph, self.sample_size)
                expr = ex.execute(graph.dependencies[n][0])
                if isinstance(expr, DatasetExpr):
                    sample = expr.dataset
            except Exception as e:  # sampling is best-effort, like upstream
                logger.debug("node-choice sampling failed for %s: %s", node.label, e)
            import inspect

            if "full_n" in inspect.signature(node.choose_physical).parameters:
                chosen = node.choose_physical(sample, full_n=full_n)
            else:
                chosen = node.choose_physical(sample)
            if chosen is not node:
                logger.info("node choice: %s -> %s", node.label, chosen.label)
                graph = graph.set_operator(n, rewrap(chosen))
        return graph


class _SampleExecutor:
    """Executes a subgraph with dataset literals truncated to k rows."""

    def __init__(self, graph: G.Graph, k: int):
        from keystone_tpu.workflow.executor import GraphExecutor

        self._inner = GraphExecutor(_truncate_datasets(graph, k))

    def execute(self, target):
        return self._inner.execute(target)


def _truncate_datasets(graph: G.Graph, k: int) -> G.Graph:
    from keystone_tpu.workflow.dataset import Dataset, StreamDataset, as_dataset

    for n, op in list(graph.operators.items()):
        if isinstance(op, G.DatasetOperator):
            ds = as_dataset(op.dataset)
            if isinstance(ds, StreamDataset):
                # sample the first batch(es) — materializing the whole
                # stream to truncate it would defeat out-of-core (the
                # reference's AutoCacheRule samples partitions the same
                # way); the sampled rows stand in for the stream in the
                # truncated PROFILING graph only
                import numpy as np

                if ds.is_host:
                    items, got2 = [], 0
                    for batch, _m in ds.device_batches():
                        items.extend(batch)
                        got2 += len(batch)
                        if got2 >= k:
                            break
                    if items:
                        graph = graph.set_operator(
                            n, G.DatasetOperator(Dataset(items[:k]))
                        )
                    continue
                parts, masks, got = [], [], 0
                for arr, mask in ds.device_batches():
                    parts.append(np.asarray(arr))
                    if mask is not None:
                        masks.append(np.asarray(mask))
                    got += arr.shape[0]
                    if got >= k:
                        break
                if not parts:
                    continue
                sample = np.concatenate(parts, axis=0)[:k]
                m = min(k, ds.n)
                # ragged streams: keep the per-batch masks, or sampled
                # nodes would treat padded descriptor rows as real data
                smask = (
                    np.concatenate(masks, axis=0)[:k] if masks else None
                )
                sliced = Dataset(sample, n=m, mask=smask, shard=False)
                graph = graph.set_operator(n, G.DatasetOperator(sliced))
            elif not ds.is_host and ds.n > k:
                sliced = Dataset(ds.array[:k], n=min(k, ds.n), shard=False)
                graph = graph.set_operator(n, G.DatasetOperator(sliced))
            elif ds.is_host and ds.n > k:
                graph = graph.set_operator(
                    n, G.DatasetOperator(Dataset(ds.items[:k]))
                )
    return graph


# ------------------------------------------------------------- stage fusion

#: class-shared jitted fused chains, keyed by (per-stage share keys,
#: matmul mode) — see FusedTransformer._share_key
_FUSED_SHARED_CACHE: dict = {}


def _stage_share_key(s: Transformer):
    """Identity of one stage for cross-instance program sharing.

    Stages declaring traced_attrs share by (class, jit_static) with
    their arrays passed as traced arguments; stages without share by
    (class, params()) — the CSE contract already promises params()
    fully identifies such a transformer.  None = not shareable (params()
    is None), which disables sharing for the whole chain."""
    ta = type(s).traced_attrs
    if ta:
        st = s.jit_static()
        return None if st is None else ("T", type(s), st)
    p = s.params()
    return None if p is None else ("C", type(s), p)


class FusedTransformer(Transformer):
    """A maximal linear chain of device transformers compiled as ONE jit
    stage.  This is the TPU replacement for the reference's per-node
    ``rdd.map`` chain: stage boundaries = jit boundaries (SURVEY.md §7)."""

    # apply_batch manages its own program caches below; the generic
    # per-instance jit wrapper must not add an outer jit, or the shared
    # chain's traced stage parameters become outer-program constants
    self_jitted = True

    def __init__(self, stages: Sequence[Transformer]):
        self.stages = list(stages)
        self._jitted = {}  # matmul mode -> jitted fn; never pickled

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_jitted"] = {}
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        if not isinstance(self._jitted, dict):  # pre-dict pickles stored None
            self._jitted = {}

    @property
    def label(self):
        return "Fused[" + " > ".join(s.label for s in self.stages) + "]"

    def params(self):
        ps = tuple(s.params() for s in self.stages)
        return None if any(p is None for p in ps) else ps

    def apply_one(self, x):
        for s in self.stages:
            x = s.apply_one(x)
        return x

    def apply_batch(self, xs, mask=None):
        # Keyed by the resolved matmul mode (utils/precision.py
        # invariant): a policy flip must retrace, not reuse a
        # stale-precision executable.  'bf16_apply' is its own key — the
        # fused chain is where the apply policy pays most (every stage's
        # bf16 casts shrink the in-program streams XLA fuses across), so
        # the whole chain recompiles under the new policy as one program.
        from keystone_tpu.utils import precision

        mode = precision.matmul_mode()
        skeys = tuple(_stage_share_key(s) for s in self.stages)
        if all(k is not None for k in skeys):
            # the input signature scopes the untraceable memo (one odd
            # dtype/rank must not pin every later call of the chain to
            # the per-instance path — same discipline as
            # Transformer._apply_batch_jitted)
            from keystone_tpu.workflow.transformer import traced_param_sig

            ckey = (
                skeys,
                mode,
                str(getattr(xs, "dtype", "")),
                getattr(xs, "ndim", None),
                tuple(traced_param_sig(s) for s in self.stages),
            )
            try:
                return self._apply_shared(ckey, xs)
            except (TypeError, jax.errors.JAXTypeError):
                _FUSED_SHARED_CACHE[ckey] = None
        fn = self._jitted.get(mode)
        if fn is None:
            stages = list(self.stages)

            def run(arr):
                for s in stages:
                    arr = s.apply_batch(arr)
                return arr

            fn = self._jitted[mode] = jax.jit(run)
        return fn(xs)

    def _apply_shared(self, ckey, xs):
        """Cross-instance shared jitted chain: stage parameters ride as
        traced arguments (Transformer.traced_attrs), so e.g. the two
        branch tails Fused[SignedHellinger > NormalizeRows] compile ONCE
        and refits never invalidate the persistent compile cache."""
        import copy

        sentinel = object()
        entry = _FUSED_SHARED_CACHE.get(ckey, sentinel)
        if entry is None:  # memoized untraceable for this chain+signature
            raise TypeError("fused chain memoized untraceable")  # caller falls back
        if entry is sentinel:
            # Bound the cache: chains whose stage params() embed per-fit
            # fingerprints mint a fresh key every refit, and each entry's
            # templates pin that fit's non-traced arrays.  FIFO-evict —
            # an evicted-but-live chain just rebuilds its entry.
            while len(_FUSED_SHARED_CACHE) >= 128:
                _FUSED_SHARED_CACHE.pop(next(iter(_FUSED_SHARED_CACHE)))
            from keystone_tpu.workflow.transformer import stripped_template

            templates = [stripped_template(s) for s in self.stages]

            def run(plist, arr):
                for t, p in zip(templates, plist):
                    obj = copy.copy(t)
                    for name, v in p.items():
                        setattr(obj, name, v)
                    arr = obj.apply_batch(arr)
                return arr

            entry = _FUSED_SHARED_CACHE[ckey] = jax.jit(run)
        plist = [
            {name: getattr(s, name) for name in type(s).traced_attrs}
            for s in self.stages
        ]
        return entry(plist, xs)


class StageFusionRule(Rule):
    """Fuse consecutive single-consumer device TransformerOperators."""

    name = "StageFusion"

    def apply(self, graph: G.Graph) -> G.Graph:
        changed = True
        while changed:
            changed = False
            for n in graph.topological_nodes():
                op = graph.operators.get(n)
                if not _fusable(op):
                    continue
                deps_on_n = graph.dependents(n)
                if len(deps_on_n) != 1 or isinstance(deps_on_n[0], G.SinkId):
                    continue
                m = deps_on_n[0]
                mop = graph.operators.get(m)
                if not _fusable(mop) or graph.dependencies[m] != (n,):
                    continue
                stages = _stages(op) + _stages(mop)
                fused_op = G.TransformerOperator(FusedTransformer(stages))
                # the fused node's OUTPUT is m's output: if the cache rule
                # flagged m over-HBM-budget (no_memoize → recompute per
                # consumer), the fused replacement must carry the flag or
                # the executor pins the very output the device can't
                # afford.  (n's flag needs no propagation: fusing a
                # single-consumer n eliminates its output entirely.)
                if getattr(mop, "no_memoize", False):
                    fused_op.no_memoize = True
                graph = graph.set_operator(m, fused_op)
                graph = graph.set_dependencies(m, graph.dependencies[n])
                graph = graph.remove_node(n)
                changed = True
                break
        return graph


def _fusable(op) -> bool:
    return (
        isinstance(op, G.TransformerOperator)
        and not op.transformer.is_host
        and getattr(op.transformer, "fusable", True)
        and not isinstance(op.transformer, Cacher)
        # degradation-declaring stages (optional / with_fallback —
        # workflow/executor.py) must stay standalone nodes: fusing one
        # into a chain would make the executor fail the WHOLE chain
        # where the user asked for that one stage to degrade
        and not getattr(op.transformer, "optional", False)
        and getattr(op.transformer, "fallback", None) is None
    )


def _stages(op) -> list:
    t = op.transformer
    return list(t.stages) if isinstance(t, FusedTransformer) else [t]


class PallasFvFusionRule(Rule):
    """Collapse the FV hot path's per-stage dispatch chain into the
    fused Pallas forward megakernel.

    An adjacent single-consumer ``PCATransformer → FisherVector`` pair
    becomes ONE ``FusedPcaFisherVector`` node
    (ops/fisher_pallas.fused_forward_pallas): descriptors stream from
    HBM once instead of round-tripping between the stages, and the
    per-stage program launches become one.  When the upstream
    ``SIFTExtractor`` feeds the PCA exclusively, its L2→clamp→re-L2
    normalize tail is absorbed into the kernel too (the extractor is
    swapped for a raw-descriptor copy), making the fused node a true
    sift-normalize → PCA-project → FV-encode forward.

    Fires only when the computation targets a Pallas-capable device
    (``pallas_supported()``); CPU meshes and dryruns keep the pre-rule
    graph, so compile-count and byte-identity pins are untouched.
    The ``fused_fv`` gate resolves through the planner precedence:
    ``KEYSTONE_FUSED_FV=0`` (the documented env override) disables the
    rule outright, else an installed ``PhysicalPlan`` that sampled the
    chain as cheaper ('xla' winner) disables it; with neither, the rule
    fires wherever Pallas runs — the historical static default."""

    name = "PallasFvFusion"

    def apply(self, graph: G.Graph) -> G.Graph:
        import os

        if os.environ.get("KEYSTONE_FUSED_FV", "1") == "0":
            return graph
        if os.environ.get("KEYSTONE_FUSED_FV") is None:
            # env unset: consult the installed plan (env stays the
            # stronger override; no plan leaves the legacy path intact)
            try:
                from keystone_tpu.planner import registry as _plans

                if _plans.planned_gate("fused_fv") == "xla":
                    return graph
            except Exception:
                pass
        from keystone_tpu.ops.fisher_pallas import pallas_supported

        if not pallas_supported():
            return graph
        import copy

        from keystone_tpu.models.pca import PCATransformer
        from keystone_tpu.ops.fisher import FisherVector, FusedPcaFisherVector
        from keystone_tpu.ops.sift import SIFTExtractor

        def _plain(op) -> bool:
            # degradation-declaring stages must stay standalone nodes
            # (same contract as _fusable): the executor degrades THEM,
            # not a fused stranger
            return (
                isinstance(op, G.TransformerOperator)
                and not getattr(op.transformer, "optional", False)
                and getattr(op.transformer, "fallback", None) is None
            )

        changed = True
        while changed:
            changed = False
            for n in graph.topological_nodes():
                op = graph.operators.get(n)
                if not _plain(op) or not isinstance(
                    op.transformer, PCATransformer
                ):
                    continue
                deps_on_n = graph.dependents(n)
                if len(deps_on_n) != 1 or isinstance(deps_on_n[0], G.SinkId):
                    continue
                m = deps_on_n[0]
                mop = graph.operators.get(m)
                if (
                    not _plain(mop)
                    or not isinstance(mop.transformer, FisherVector)
                    or graph.dependencies[m] != (n,)
                ):
                    continue
                fv = mop.transformer
                if fv.use_pallas is False:
                    continue  # an explicit opt-out covers the fused form too
                # absorb the SIFT normalize tail when the extractor's
                # output feeds ONLY this PCA (a shared extractor must
                # keep emitting normalized descriptors for its other
                # consumers — vocabulary samplers in the fit graph)
                sift_normalize = False
                pca_deps = graph.dependencies[n]
                if len(pca_deps) == 1:
                    s = pca_deps[0]
                    sop = graph.operators.get(s)
                    if (
                        _plain(sop)
                        and isinstance(sop.transformer, SIFTExtractor)
                        and sop.transformer.normalize
                        and tuple(graph.dependents(s)) == (n,)
                    ):
                        raw_sift = copy.copy(sop.transformer)
                        raw_sift.normalize = False
                        graph = graph.set_operator(
                            s, G.TransformerOperator(raw_sift)
                        )
                        sift_normalize = True
                fused_op = G.TransformerOperator(
                    FusedPcaFisherVector(
                        op.transformer,
                        fv.gmm,
                        sift_normalize=sift_normalize,
                        use_pallas=fv.use_pallas,
                    )
                )
                # the fused node's output is m's output — carry the
                # cache rule's over-budget flag (see StageFusionRule)
                if getattr(mop, "no_memoize", False):
                    fused_op.no_memoize = True
                graph = graph.set_operator(m, fused_op)
                graph = graph.set_dependencies(m, graph.dependencies[n])
                graph = graph.remove_node(n)
                changed = True
                break
        return graph


# ------------------------------------------------------------------ default
class ProfiledMaterializeRule(Rule):
    """Default materialization pass (r2): the HBM-budgeted
    ProfilingAutoCacheRule with the budget read from the actual device,
    falling back to the structural AutoMaterializeRule when profiling is
    unavailable (no device stats, unexecutable sample, host-only graph).

    This is the promotion VERDICT r1 item 8 asked for: the reference's
    AutoCacheRule (sampled profiling + memory-budget greedy placement,
    workflow/AutoCacheRule.scala) is now the DEFAULT path, not a
    hand-wired option."""

    name = "ProfiledMaterialize"

    def __init__(self, sample_size: int = 64):
        self.sample_size = int(sample_size)

    def apply(self, graph: G.Graph) -> G.Graph:
        try:
            from keystone_tpu.workflow.profiling import (
                ProfilingAutoCacheRule,
                device_hbm_budget,
            )

            return ProfilingAutoCacheRule(
                budget_bytes=device_hbm_budget(),
                sample_size=self.sample_size,
                static_cost=True,
            ).apply(graph)
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "profiled materialization failed (%s); using structural rule", e
            )
            return AutoMaterializeRule().apply(graph)


def default_optimizer(
    sample_size: int = 256, materialize_sample_size: int = 64
) -> Optimizer:
    """``sample_size`` governs node-choice sampling;
    ``materialize_sample_size`` the profiled materialization pass (kept
    smaller by default — it executes the whole prefix graph per node)."""
    return Optimizer(
        [
            RuleBatch("cse", FixedPoint(5), [EquivalentNodeMergeRule()]),
            RuleBatch("node-choice", Once(), [NodeChoiceRule(sample_size)]),
            RuleBatch(
                "materialize",
                Once(),
                [ProfiledMaterializeRule(materialize_sample_size)],
            ),
            # Pallas FV fusion first: it targets the (non-fusable)
            # PCA→FV pair specifically, before the generic chain fuser
            # sweeps the remaining linear runs
            RuleBatch(
                "fusion", Once(), [PallasFvFusionRule(), StageFusionRule()]
            ),
        ]
    )
