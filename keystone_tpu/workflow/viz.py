"""Pipeline visualization: Graph → Graphviz DOT.

The reference exposes ``Pipeline.toDOT`` for debugging its DAGs
(workflow/Pipeline.scala); same idea here, plus optimizer before/after
diffing is just two calls.

Observability overlay: pass per-node ``timings`` (seconds) and/or
``retries`` keyed by node label — either hand-built, from
``utils/tracing.stage_timings`` (keys ``"{node_id}:{label}"`` also
match), or folded out of a run ledger with :func:`ledger_overlay` — and
nodes render with their measured time (and retry count) under the
label, shaded by share of total time::

    timings, retries = ledger_overlay("/tmp/obs/run_abc.jsonl")
    dot = to_dot(pipe.graph, timings=timings, retries=retries)

Analyzer overlay: pass ``findings`` (``keystone_tpu.analysis`` Finding
records) and offending nodes fill red (error) / yellow (warning) with
their finding codes — ``python -m keystone_tpu.cli check --dot OUT``
writes exactly this.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from keystone_tpu.workflow import graph as G


def ledger_overlay(ledger_path: str) -> Tuple[Dict[str, float], Dict[str, int]]:
    """(timings, retries) per node from a run-ledger JSONL file — the
    shared ``obs.ledger.fold_stage_spans`` fold (one reader of the span
    schema, shared with tools/obs_report.py).  Unique labels key by bare
    label (matches any graph the caller overlays onto); duplicate
    labels key by ``"{node_id}:{label}"`` so two branches holding the
    same transformer type stay distinct instead of each displaying the
    merged total."""
    from collections import Counter

    from keystone_tpu.obs.ledger import fold_stage_spans

    folded = fold_stage_spans(ledger_path)
    label_count = Counter(st["label"] for st in folded.values())
    timings: Dict[str, float] = {}
    retries: Dict[str, int] = {}
    for key, st in folded.items():
        k = st["label"] if label_count[st["label"]] == 1 else key
        timings[k] = st["seconds"]
        if st["retries"]:
            retries[k] = st["retries"]
    return timings, retries


def _lookup(overlay: Optional[dict], n, label: str):
    """Overlay value for a node: exact label, or a stage_timings-style
    ``"{node_id}:{label}"`` key."""
    if not overlay:
        return None
    if label in overlay:
        return overlay[label]
    return overlay.get(f"{n.id}:{label}")


#: analyzer-overlay fills: worst severity per node wins, and a finding
#: fill beats the timing shade (a broken node matters more than a slow
#: one)
_SEVERITY_FILL = {"error": "#ff9999", "warning": "#ffe680"}


def _findings_by_node(findings) -> Dict[int, list]:
    by_node: Dict[int, list] = {}
    for f in findings or ():
        if getattr(f, "node", None) is not None:
            by_node.setdefault(f.node, []).append(f)
    return by_node


def to_dot(
    graph: G.Graph,
    name: str = "pipeline",
    timings: Optional[Dict[str, float]] = None,
    retries: Optional[Dict[str, int]] = None,
    findings=None,
) -> str:
    """``findings``: analyzer Finding records (or an AnalysisReport) —
    offending nodes fill red (error) / yellow (warning) with their
    finding codes under the label, and graph-level findings render as a
    standalone note node.  ``cli.py check --dot`` writes this overlay."""
    findings = list(findings) if findings is not None else []
    by_node = _findings_by_node(findings)
    lines = [f"digraph {name} {{", "  rankdir=LR;", "  node [shape=box, fontsize=10];"]
    total = sum(timings.values()) if timings else 0.0
    for s in graph.sources:
        lines.append(f'  "{s!r}" [shape=ellipse, label="source {s.id}"];')
    for n in graph.topological_nodes():
        op = graph.operators[n]
        shape = {
            G.DatasetOperator: "cylinder",
            G.DatumOperator: "cylinder",
            G.EstimatorOperator: "house",
        }.get(type(op), "box")
        label = op.label().replace('"', "'")
        extra = ""
        seconds = _lookup(timings, n, op.label())
        nretries = _lookup(retries, n, op.label())
        annot = []
        if seconds is not None:
            annot.append(f"{seconds:.3f}s")
        if nretries:
            annot.append(f"x{int(nretries)} retries")
        if annot:
            label = label + "\\n" + " ".join(annot)
        if seconds is not None and total > 0:
            # share-of-total shading: the hot path jumps out of the graph
            share = min(1.0, seconds / total)
            extra = (
                ', style=filled, fillcolor="0.08 %0.2f 1.0"' % (0.1 + 0.8 * share)
            )
        node_findings = by_node.get(n.id)
        if node_findings:
            worst = (
                "error"
                if any(f.severity == "error" for f in node_findings)
                else "warning"
            )
            codes = sorted({f.code for f in node_findings})
            label = label + "\\n" + " ".join(codes[:3])
            extra = f', style=filled, fillcolor="{_SEVERITY_FILL[worst]}"'
        lines.append(f'  "{n!r}" [shape={shape}, label="{label}"{extra}];')
        for d in graph.dependencies[n]:
            lines.append(f'  "{d!r}" -> "{n!r}";')
    for k, d in graph.sink_dependencies.items():
        lines.append(f'  "{k!r}" [shape=ellipse, label="sink {k.id}"];')
        lines.append(f'  "{d!r}" -> "{k!r}";')
    graph_level = [f for f in findings if getattr(f, "node", None) is None]
    if graph_level:
        worst = (
            "error"
            if any(f.severity == "error" for f in graph_level)
            else "warning"
        )
        codes = sorted({f.code for f in graph_level})
        note = "analysis: " + " ".join(codes[:4])
        lines.append(
            f'  "analysis_findings" [shape=note, label="{note}", '
            f'style=filled, fillcolor="{_SEVERITY_FILL[worst]}"];'
        )
    lines.append("}")
    return "\n".join(lines)
