"""Pipeline visualization: Graph → Graphviz DOT.

The reference exposes ``Pipeline.toDOT`` for debugging its DAGs
(workflow/Pipeline.scala); same idea here, plus optimizer before/after
diffing is just two calls.
"""

from __future__ import annotations

from keystone_tpu.workflow import graph as G


def to_dot(graph: G.Graph, name: str = "pipeline") -> str:
    lines = [f"digraph {name} {{", "  rankdir=LR;", "  node [shape=box, fontsize=10];"]
    for s in graph.sources:
        lines.append(f'  "{s!r}" [shape=ellipse, label="source {s.id}"];')
    for n in graph.topological_nodes():
        op = graph.operators[n]
        shape = {
            G.DatasetOperator: "cylinder",
            G.DatumOperator: "cylinder",
            G.EstimatorOperator: "house",
        }.get(type(op), "box")
        label = op.label().replace('"', "'")
        lines.append(f'  "{n!r}" [shape={shape}, label="{label}"];')
        for d in graph.dependencies[n]:
            lines.append(f'  "{d!r}" -> "{n!r}";')
    for k, d in graph.sink_dependencies.items():
        lines.append(f'  "{k!r}" [shape=ellipse, label="sink {k.id}"];')
        lines.append(f'  "{d!r}" -> "{k!r}";')
    lines.append("}")
    return "\n".join(lines)
