"""The core data abstraction: a Dataset is a sharded batched array.

The reference's unit of distributed data is ``RDD[T]`` — a partitioned
collection of single datums, batched into per-partition matrices only
inside solvers (utils/MatrixUtils.scala § rowsToMatrix).  On TPU the
efficient form is the opposite: data lives batched from the start as a
device array with its leading axis sharded over the mesh 'data' axis;
"partitions" are the per-device shards XLA sees.

Three payload kinds flow through pipelines:
  - device arrays: (n, ...) jnp arrays, the normal case;
  - ragged arrays: (n, max_k, d) with a boolean (n, max_k) mask — e.g.
    per-image SIFT descriptor sets (pad-and-mask, SURVEY.md §7 hard part d);
  - host lists: arbitrary Python objects (e.g. raw text for NLP nodes),
    which stay on host until a featurizer produces arrays.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel import mesh as _mesh


class Dataset:
    """A (possibly padded) batch with true length ``n``."""

    def __init__(
        self,
        data: Any,
        n: Optional[int] = None,
        mask: Optional[jnp.ndarray] = None,
        shard: bool = True,
        name: Optional[str] = None,
    ):
        #: optional stable identity — lets prefix signatures (CSE, saved
        #: state) match across processes; unnamed datasets use object id
        self.name = name
        if isinstance(data, (list, tuple)) and not _all_arrays(data):
            # Host payload (strings, PyTrees, variable-shape objects).
            self._host: Optional[list] = list(data)
            self._array = None
            self.n = len(self._host) if n is None else n
            self.mask = None
        else:
            arr = data
            if isinstance(arr, (list, tuple)):
                arr = np.stack([np.asarray(a) for a in arr], axis=0)
            true_n = arr.shape[0] if n is None else n
            self._host = None
            self._array = _mesh.shard_batch(arr) if shard else jnp.asarray(arr)
            self.n = true_n
            self.mask = mask

    # ------------------------------------------------------------ access
    @property
    def is_host(self) -> bool:
        return self._host is not None

    @property
    def array(self) -> jnp.ndarray:
        """Padded, device-resident array. Rows >= n are padding."""
        if self._array is None:
            raise TypeError("host-payload Dataset has no array; featurize it first")
        return self._array

    @property
    def items(self) -> list:
        if self._host is not None:
            return self._host
        return [np.asarray(self._array[i]) for i in range(self.n)]

    def numpy(self) -> np.ndarray:
        """Unpadded host copy."""
        return np.asarray(self.array)[: self.n]

    def __len__(self) -> int:
        return self.n

    # --------------------------------------------------------- derivation
    def with_array(self, arr, mask=None) -> "Dataset":
        """New Dataset sharing this one's true length (padding preserved)."""
        d = Dataset.__new__(Dataset)
        d._host = None
        d._array = arr
        d.n = self.n
        d.mask = mask if mask is not None else None
        d.name = None
        return d

    def with_items(self, items: Sequence) -> "Dataset":
        d = Dataset.__new__(Dataset)
        d._host = list(items)
        d._array = None
        d.n = self.n
        d.mask = None
        d.name = None
        return d

    def cache(self) -> "Dataset":
        """Force materialization (the Cacher analogue, nodes/util/Cacher.scala).

        JAX arrays are already materialized once computed; this blocks on
        completion so downstream timing/profiling sees real costs.
        """
        if self._array is not None:
            self._array.block_until_ready()
        return self

    def __repr__(self):
        if self.is_host:
            return f"Dataset(host, n={self.n})"
        return f"Dataset(shape={tuple(self.array.shape)}, n={self.n})"


def _all_arrays(seq) -> bool:
    return len(seq) > 0 and all(
        isinstance(x, (np.ndarray, jnp.ndarray)) and hasattr(x, "shape") for x in seq
    ) and len({np.shape(x) for x in seq}) == 1


def as_dataset(x, shard: bool = True) -> Dataset:
    if isinstance(x, Dataset):
        return x
    return Dataset(x, shard=shard)
