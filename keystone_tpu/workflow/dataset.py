"""The core data abstraction: a Dataset is a sharded batched array.

The reference's unit of distributed data is ``RDD[T]`` — a partitioned
collection of single datums, batched into per-partition matrices only
inside solvers (utils/MatrixUtils.scala § rowsToMatrix).  On TPU the
efficient form is the opposite: data lives batched from the start as a
device array with its leading axis sharded over the mesh 'data' axis;
"partitions" are the per-device shards XLA sees.

Three payload kinds flow through pipelines:
  - device arrays: (n, ...) jnp arrays, the normal case;
  - ragged arrays: (n, max_k, d) with a boolean (n, max_k) mask — e.g.
    per-image SIFT descriptor sets (pad-and-mask, SURVEY.md §7 hard part d);
  - host lists: arbitrary Python objects (e.g. raw text for NLP nodes),
    which stay on host until a featurizer produces arrays.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel import mesh as _mesh


class Dataset:
    """A (possibly padded) batch with true length ``n``."""

    def __init__(
        self,
        data: Any,
        n: Optional[int] = None,
        mask: Optional[jnp.ndarray] = None,
        shard: bool = True,
        name: Optional[str] = None,
    ):
        #: optional stable identity — lets prefix signatures (CSE, saved
        #: state) match across processes; unnamed datasets use object id
        self.name = name
        if isinstance(data, (list, tuple)) and not _all_arrays(data):
            # Host payload (strings, PyTrees, variable-shape objects).
            self._host: Optional[list] = list(data)
            self._array = None
            self.n = len(self._host) if n is None else n
            self.mask = None
        else:
            arr = data
            if isinstance(arr, (list, tuple)):
                arr = np.stack([np.asarray(a) for a in arr], axis=0)
            true_n = arr.shape[0] if n is None else n
            self._host = None
            self._array = _mesh.shard_batch(arr) if shard else jnp.asarray(arr)
            self.n = true_n
            self.mask = mask

    # ------------------------------------------------------------ access
    @property
    def is_host(self) -> bool:
        return self._host is not None

    @property
    def array(self) -> jnp.ndarray:
        """Padded, device-resident array. Rows >= n are padding."""
        if self._array is None:
            raise TypeError("host-payload Dataset has no array; featurize it first")
        return self._array

    @property
    def items(self) -> list:
        if self._host is not None:
            return self._host
        return [np.asarray(self._array[i]) for i in range(self.n)]

    def numpy(self) -> np.ndarray:
        """Unpadded host copy."""
        return np.asarray(self.array)[: self.n]

    @property
    def item_shape(self) -> tuple:
        """Per-item shape — StreamDataset overrides via peek_shape so
        pipelines can derive feature dims without materializing."""
        return tuple(self.array.shape[1:])

    def __len__(self) -> int:
        return self.n

    # --------------------------------------------------------- derivation
    def with_array(self, arr, mask=None) -> "Dataset":
        """New Dataset sharing this one's true length (padding preserved)."""
        d = Dataset.__new__(Dataset)
        d._host = None
        d._array = arr
        d.n = self.n
        d.mask = mask if mask is not None else None
        d.name = None
        return d

    def with_items(self, items: Sequence) -> "Dataset":
        d = Dataset.__new__(Dataset)
        d._host = list(items)
        d._array = None
        d.n = self.n
        d.mask = None
        d.name = None
        return d

    def cache(self) -> "Dataset":
        """Force materialization (the Cacher analogue, nodes/util/Cacher.scala).

        JAX arrays are already materialized once computed; this blocks on
        completion so downstream timing/profiling sees real costs.
        """
        if self._array is not None:
            self._array.block_until_ready()
        return self

    def __repr__(self):
        if self.is_host:
            return f"Dataset(host, n={self.n})"
        return f"Dataset(shape={tuple(self.array.shape)}, n={self.n})"


class StreamDataset(Dataset):
    """A lazily-evaluated, re-iterable stream of host batches — the
    out-of-core path through the Pipeline DAG.

    The reference streams data through RDD partition iterators so no
    executor ever holds the full dataset (SURVEY.md §2.9); this is the
    TPU analogue: transformers map over the stream batch-by-batch
    (upload → one compiled apply → stay on device for the next map), and
    the block solvers spill the resulting features to a
    :class:`~keystone_tpu.workflow.blockstore.FeatureBlockStore` and fit
    out-of-core, so the feature matrix never needs to fit in HBM.

    ``source``: a callable returning an iterator of host batches (or a
    re-iterable).  Each batch is a ``(m_i, ...)`` array or an
    ``(array, mask)`` pair for ragged payloads.  ``n`` — total rows.
    ``prefetch`` > 0 moves the source's host work (decode, transforms)
    onto a background thread that stays ``prefetch`` batches ahead of
    the consumer (loaders pass their decode cost through this).

    ``host=True`` marks a stream of HOST-object batches (lists of
    texts, term dicts, CSR rows — the text pipelines' payloads before
    featurization): host transformers map over it item-by-item per
    batch, and nothing touches a device until a featurizer produces
    arrays or CSR.  This is how a raw corpus larger than host RAM
    streams through tokenize→n-gram→vocab→CSR (the CSR output is
    orders of magnitude smaller and is collected normally).

    Estimators without a streaming fit path fall back to
    :attr:`array` / :attr:`items`, which materialize the whole stream
    (with a warning) — correctness is preserved everywhere, the
    out-of-core guarantee only where implemented.
    """

    def __init__(
        self,
        source,
        n: int,
        name: Optional[str] = None,
        prefetch: int = 0,
        host: bool = False,
        retries: int = 0,
        max_bad_batches: int = 0,
        timeout: Optional[float] = None,
    ):
        self.name = name
        self.n = int(n)
        self._host = None
        self._array = None
        self._host_stream = bool(host)
        self.mask = None
        if not callable(source) and iter(source) is source:
            # A one-shot iterator would be shared (and interleaved!) by
            # fan-out consumers — e.g. the two branches of a Gather.
            raise ValueError(
                "StreamDataset source must be re-iterable: pass a callable "
                "returning a fresh iterator (or a list of batches), not a "
                "one-shot generator/iterator"
            )
        if retries > 0 or max_bad_batches > 0 or timeout is not None:
            # flaky-source hardening (loaders/stream.resilient): bounded
            # per-batch retry with backoff, then a drop quota — wrapped
            # UNDER prefetched so retries run on the producer thread.
            # ``timeout`` adds a per-fetch watchdog: a silently-hung
            # source raises (DeadlineExceeded, an OSError) into the
            # same retry/quota machinery instead of stalling the fit
            from keystone_tpu.loaders.stream import resilient

            source = resilient(
                source,
                retries=retries,
                max_bad_batches=max_bad_batches,
                timeout=timeout,
            )
        if prefetch > 0:
            from keystone_tpu.loaders.stream import prefetched

            source = prefetched(source, prefetch=prefetch)

        if host:

            def gen():
                src = source() if callable(source) else iter(source)
                for batch in src:
                    yield list(batch), None

        else:

            def gen():
                src = source() if callable(source) else iter(source)
                for batch in src:
                    arr, mask = (
                        batch if isinstance(batch, tuple) else (batch, None)
                    )
                    yield jnp.asarray(arr), (
                        None if mask is None else jnp.asarray(mask)
                    )

        self._gen = gen

    @property
    def is_host(self) -> bool:
        return self._host_stream

    @classmethod
    def _wrap(
        cls, gen, n: int, name: Optional[str] = None, host: bool = False
    ) -> "StreamDataset":
        d = cls.__new__(cls)
        d.name = name
        d.n = int(n)
        d._host = None
        d._array = None
        d._host_stream = bool(host)
        d.mask = None
        d._gen = gen
        return d

    # --------------------------------------------------------- streaming
    def device_batches(self):
        """Iterate ``(array, mask_or_None)`` device batches."""
        return self._gen()

    def peek_shape(self) -> tuple:
        """Per-item shape ``(...)`` from the first batch (cached) —
        lets callers derive feature dims without materializing the
        stream (costs one batch's host work on first call)."""
        if not hasattr(self, "_peek_shape"):
            for arr, _ in self._gen():
                self._peek_shape = tuple(np.shape(arr)[1:])
                break
            else:
                raise ValueError("empty stream")
        return self._peek_shape

    @property
    def item_shape(self) -> tuple:
        return self.peek_shape()

    def batches(self):
        """Iterate host batches of the mapped values (numpy for device
        streams, lists for host streams)."""
        for arr, _ in self._gen():
            yield arr if self._host_stream else np.asarray(arr)

    def map_batches(self, fn, host: Optional[bool] = None) -> "StreamDataset":
        """Lazily compose a per-batch function ``fn(batch, mask)``
        (returning an array/list or an (array, mask) pair) over the
        stream.  ``host`` sets the CHILD stream's payload kind; default:
        same as this stream."""
        parent = self._gen

        def gen():
            for arr, mask in parent():
                out = fn(arr, mask)
                if isinstance(out, tuple):
                    yield out
                else:
                    yield out, None

        return StreamDataset._wrap(
            gen,
            self.n,
            host=self._host_stream if host is None else host,
        )

    @staticmethod
    def zip_concat(streams: Sequence["StreamDataset"]) -> "StreamDataset":
        """Gather analogue for streams: zip batches, concat on the last
        axis.  All streams must share batch structure (in pipelines they
        are branches mapped over ONE source, so they do by construction)."""
        ns = {s.n for s in streams}
        if len(ns) != 1:
            raise ValueError(f"gathered streams disagree on n: {sorted(ns)}")
        gens = [s._gen for s in streams]

        def gen():
            for parts in zip(*(g() for g in gens), strict=True):
                arrs = [a for a, _ in parts]
                yield jnp.concatenate(arrs, axis=-1), None

        return StreamDataset._wrap(gen, streams[0].n)

    # -------------------------------------------------- Dataset protocol
    @property
    def array(self) -> jnp.ndarray:
        """Materialize the stream into one sharded device array (escape
        hatch for consumers without a streaming path; defeats out-of-core)."""
        if self._host_stream:
            raise TypeError(
                "host-payload StreamDataset has no array; featurize it first"
            )
        if self._array is None:
            import logging

            logging.getLogger(__name__).warning(
                "materializing StreamDataset (n=%d) into device memory; "
                "this consumer has no out-of-core path",
                self.n,
            )
            parts = []
            masks = []
            for arr, mask in self._gen():
                parts.append(np.asarray(arr))
                if mask is not None:
                    masks.append(np.asarray(mask))
            arr = np.concatenate(parts, axis=0)
            self._array = _mesh.shard_batch(arr)
            if masks:
                self.mask = _mesh.shard_batch(np.concatenate(masks, axis=0))
        return self._array

    @property
    def items(self) -> list:
        if self._host_stream:
            # collecting a host stream is often BY DESIGN small (CSR
            # rows after featurization); log at debug, not warning
            if self._host is None:
                import logging

                logging.getLogger(__name__).debug(
                    "collecting host StreamDataset (n=%d) items", self.n
                )
                out: list = []
                for batch, _ in self._gen():
                    out.extend(batch)
                self._host = out
            return self._host
        self.array
        return [np.asarray(self._array[i]) for i in range(self.n)]

    def cache(self) -> "StreamDataset":
        # A Cacher inserted by the optimizer must NOT collapse the stream
        # into memory — out-of-core is the point.  No-op.
        return self

    def __repr__(self):
        kind = "host, " if self._host_stream else ""
        return f"StreamDataset({kind}n={self.n})"


def _all_arrays(seq) -> bool:
    return len(seq) > 0 and all(
        isinstance(x, (np.ndarray, jnp.ndarray)) and hasattr(x, "shape") for x in seq
    ) and len({np.shape(x) for x in seq}) == 1


def as_dataset(x, shard: bool = True) -> Dataset:
    if isinstance(x, Dataset):
        return x
    return Dataset(x, shard=shard)
