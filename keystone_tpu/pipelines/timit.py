"""TimitPipeline (reference pipelines/speech/timit/TimitPipeline.scala):
MFCC frames → StandardScaler → CosineRandomFeatures (in blocks, gathered)
→ BlockWeightedLeastSquares (147 classes) → MaxClassifier."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.timit import TimitFeaturesDataLoader, DIM, NUM_CLASSES
from keystone_tpu.models import BlockWeightedLeastSquaresEstimator
from keystone_tpu.ops import (
    ClassLabelIndicators,
    CosineRandomFeatures,
    MaxClassifier,
)
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.workflow import Dataset, Pipeline


@dataclasses.dataclass
class Config:
    features_path: Optional[str] = None
    labels_path: Optional[str] = None
    test_features_path: Optional[str] = None
    test_labels_path: Optional[str] = None
    num_cosine_features: int = 4096
    cosine_block_size: int = 1024
    gamma: float = 0.05
    num_epochs: int = 3
    lam: float = 1e-3
    mixture_weight: float = 0.5
    solver_block_size: int = 1024
    num_classes: int = NUM_CLASSES
    seed: int = 0
    synthetic_n: int = 4096
    model_path: Optional[str] = None
    # out-of-core: stream MFCC frames from disk per sweep; the cosine
    # feature matrix spills to a FeatureBlockStore instead of HBM
    stream: bool = False
    stream_batch_size: int = 8192


class TimitPipeline:
    name = "TimitPipeline"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_labels: Dataset) -> Pipeline:
        (dim,) = train_x.item_shape  # stream-safe (peeks one batch)
        num_blocks = max(1, config.num_cosine_features // config.cosine_block_size)
        branches = [
            Pipeline.of(
                CosineRandomFeatures.init(
                    dim,
                    config.cosine_block_size,
                    gamma=config.gamma,
                    seed=config.seed + i,
                )
            )
            for i in range(num_blocks)
        ]
        featurizer = Pipeline.of(StandardScaler().with_data(train_x)).then_pipeline(
            Pipeline.gather(branches)
        )
        labels_pm1 = ClassLabelIndicators(config.num_classes)(train_labels)
        return featurizer.and_then(
            BlockWeightedLeastSquaresEstimator(
                block_size=config.solver_block_size,
                num_iter=config.num_epochs,
                lam=config.lam,
                mixture_weight=config.mixture_weight,
            ),
            train_x,
            labels_pm1,
        ).and_then(MaxClassifier())

    @staticmethod
    def run(config: Config) -> dict:
        _train_cache = []

        def _train():
            # cached: the no-test-set path uses train as test AND build()
            # needs it — one parse, not two
            if not _train_cache:
                if config.features_path:
                    loader = (
                        TimitFeaturesDataLoader.stream
                        if config.stream
                        else TimitFeaturesDataLoader.load
                    )
                    kw = (
                        {"batch_size": config.stream_batch_size}
                        if config.stream
                        else {}
                    )
                    _train_cache.append(
                        loader(config.features_path, config.labels_path, **kw)
                    )
                else:
                    synth = TimitFeaturesDataLoader.synthetic(
                        config.synthetic_n, config.num_classes, seed=1
                    )
                    if config.stream:
                        # demo/test path: stream the synthetic frames in
                        # batches so the out-of-core fit path engages
                        from keystone_tpu.loaders.stream import stream_labeled

                        synth = stream_labeled(
                            synth, config.stream_batch_size
                        )
                    _train_cache.append(synth)
            return _train_cache[0]

        if config.features_path:
            test = (
                TimitFeaturesDataLoader.load(
                    config.test_features_path, config.test_labels_path
                )
                if config.test_features_path
                else _train()
            )
        else:
            test = TimitFeaturesDataLoader.synthetic(
                config.synthetic_n // 4, config.num_classes, seed=2
            )

        def build():
            # train loads ONLY when a fit is needed (saved-model runs with
            # a separate test set skip it)
            train = _train()
            return TimitPipeline.build(config, train.data, train.labels)

        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path, build, config=fit_relevant_config(config)
        )
        fit_time = time.time() - t0
        preds = fitted(test.data).get()
        m = MulticlassClassifierEvaluator(config.num_classes).evaluate(
            preds, test.labels
        )
        return {
            "pipeline": TimitPipeline.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "test_error": m.total_error,
            "accuracy": m.accuracy,
            # macro metrics surface class-balance effects: on skewed
            # data they are what mixture_weight exists to move
            "macro_f1": m.macro_f1,
            "macro_recall": m.macro_recall,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=TimitPipeline.name)
    p.add_argument("--features-path")
    p.add_argument("--labels-path")
    p.add_argument("--num-cosine-features", type=int, default=4096)
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--lam", type=float, default=1e-3)
    p.add_argument("--num-classes", type=int, default=NUM_CLASSES)
    p.add_argument("--synthetic-n", type=int, default=4096)
    p.add_argument("--model-path")
    p.add_argument(
        "--stream",
        "--out-of-core",
        action="store_true",
        dest="stream",
        help="stream MFCC frames from disk; cosine features spill to a "
        "disk block store instead of residing in HBM",
    )
    p.add_argument("--stream-batch-size", type=int, default=8192)
    a = p.parse_args(argv)
    cfg = Config(
        features_path=a.features_path,
        labels_path=a.labels_path,
        num_cosine_features=a.num_cosine_features,
        num_epochs=a.num_epochs,
        lam=a.lam,
        num_classes=a.num_classes,
        synthetic_n=a.synthetic_n,
        model_path=a.model_path,
        stream=a.stream,
        stream_batch_size=a.stream_batch_size,
    )
    print(TimitPipeline.run(cfg))


if __name__ == "__main__":
    main()
