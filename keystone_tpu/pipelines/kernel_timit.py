"""KernelTimitPipeline — the kernel-methods variant of the TIMIT
scenario (arXiv:1602.05310 evaluates kernel systems on TIMIT): MFCC
frames → StandardScaler → NystromFeatures (seeded landmark sampling +
whitening solve; K_nm streams at apply time) → BlockLeastSquares (147
classes) → MaxClassifier.

Where ``pipelines/timit.py`` approximates the Gaussian kernel with
random cosine features, this variant uses the data-dependent Nyström
map — same solver, same labels plumbing, a genuinely kernel feature
space.  ``--stream`` keeps the MFCC frames out of core end to end:
landmarks are collected in one streaming pass and the solver spills to
a FeatureBlockStore."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.timit import TimitFeaturesDataLoader, NUM_CLASSES
from keystone_tpu.models import BlockLeastSquaresEstimator, NystromFeatures
from keystone_tpu.models.kernel_ridge import GaussianKernelGenerator
from keystone_tpu.ops import ClassLabelIndicators, MaxClassifier
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.workflow import Dataset, Pipeline


@dataclasses.dataclass
class Config:
    features_path: Optional[str] = None
    labels_path: Optional[str] = None
    test_features_path: Optional[str] = None
    test_labels_path: Optional[str] = None
    num_landmarks: int = 2048
    gamma: float = 0.015
    nystrom_reg: float = 1e-7
    num_epochs: int = 3
    lam: float = 1e-5
    solver_block_size: int = 1024
    num_classes: int = NUM_CLASSES
    seed: int = 0
    synthetic_n: int = 4096
    model_path: Optional[str] = None
    # out-of-core: stream MFCC frames from disk; landmarks sample in
    # one pass and the Nyström features spill to a FeatureBlockStore
    stream: bool = False
    stream_batch_size: int = 8192


class KernelTimitPipeline:
    name = "KernelTimitPipeline"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_labels: Dataset) -> Pipeline:
        kern = GaussianKernelGenerator(config.gamma)
        labels_pm1 = ClassLabelIndicators(config.num_classes)(train_labels)
        return (
            Pipeline.of(StandardScaler().with_data(train_x))
            .and_then(
                NystromFeatures(
                    kern,
                    num_landmarks=config.num_landmarks,
                    reg=config.nystrom_reg,
                    seed=config.seed,
                ),
                train_x,
            )
            .and_then(
                BlockLeastSquaresEstimator(
                    block_size=config.solver_block_size,
                    num_iter=config.num_epochs,
                    lam=config.lam,
                ),
                train_x,
                labels_pm1,
            )
            .and_then(MaxClassifier())
        )

    @staticmethod
    def run(config: Config) -> dict:
        _train_cache = []

        def _train():
            if not _train_cache:
                if config.features_path:
                    loader = (
                        TimitFeaturesDataLoader.stream
                        if config.stream
                        else TimitFeaturesDataLoader.load
                    )
                    kw = (
                        {"batch_size": config.stream_batch_size}
                        if config.stream
                        else {}
                    )
                    _train_cache.append(
                        loader(config.features_path, config.labels_path, **kw)
                    )
                else:
                    synth = TimitFeaturesDataLoader.synthetic(
                        config.synthetic_n, config.num_classes, seed=1
                    )
                    if config.stream:
                        from keystone_tpu.loaders.stream import stream_labeled

                        synth = stream_labeled(
                            synth, config.stream_batch_size
                        )
                    _train_cache.append(synth)
            return _train_cache[0]

        if config.features_path:
            test = (
                TimitFeaturesDataLoader.load(
                    config.test_features_path, config.test_labels_path
                )
                if config.test_features_path
                else _train()
            )
        else:
            test = TimitFeaturesDataLoader.synthetic(
                config.synthetic_n // 4, config.num_classes, seed=2
            )

        def build():
            train = _train()
            return KernelTimitPipeline.build(config, train.data, train.labels)

        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path, build, config=fit_relevant_config(config)
        )
        fit_time = time.time() - t0
        preds = fitted(test.data).get()
        m = MulticlassClassifierEvaluator(config.num_classes).evaluate(
            preds, test.labels
        )
        return {
            "pipeline": KernelTimitPipeline.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "test_error": m.total_error,
            "accuracy": m.accuracy,
            "macro_f1": m.macro_f1,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=KernelTimitPipeline.name)
    p.add_argument("--features-path")
    p.add_argument("--labels-path")
    p.add_argument("--num-landmarks", type=int, default=2048)
    p.add_argument("--gamma", type=float, default=0.015)
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--lam", type=float, default=1e-5)
    p.add_argument("--num-classes", type=int, default=NUM_CLASSES)
    p.add_argument("--synthetic-n", type=int, default=4096)
    p.add_argument("--model-path")
    p.add_argument(
        "--stream",
        "--out-of-core",
        action="store_true",
        dest="stream",
        help="stream MFCC frames from disk; landmarks sample in one "
        "pass and Nyström features spill to a disk block store",
    )
    p.add_argument("--stream-batch-size", type=int, default=8192)
    a = p.parse_args(argv)
    cfg = Config(
        features_path=a.features_path,
        labels_path=a.labels_path,
        num_landmarks=a.num_landmarks,
        gamma=a.gamma,
        num_epochs=a.num_epochs,
        lam=a.lam,
        num_classes=a.num_classes,
        synthetic_n=a.synthetic_n,
        model_path=a.model_path,
        stream=a.stream,
        stream_batch_size=a.stream_batch_size,
    )
    print(KernelTimitPipeline.run(cfg))


if __name__ == "__main__":
    main()
