"""Example applications (reference src/main/scala/pipelines/).

Each app mirrors the reference's shape: a flag-parsed config dataclass, a
``build(...)`` assembling the pipeline from nodes, and a ``run(config)``
returning metrics.  Run via ``python -m keystone_tpu.cli <AppName> [flags]``
(the bin/run-pipeline.sh analogue) or ``python -m keystone_tpu.pipelines.<module>``.
"""

from keystone_tpu.pipelines.mnist_random_fft import MnistRandomFFT  # noqa: F401
from keystone_tpu.pipelines.linear_pixels import LinearPixels  # noqa: F401
from keystone_tpu.pipelines.random_patch_cifar import RandomPatchCifar  # noqa: F401
from keystone_tpu.pipelines.newsgroups import NewsgroupsPipeline  # noqa: F401
from keystone_tpu.pipelines.timit import TimitPipeline  # noqa: F401
from keystone_tpu.pipelines.imagenet_sift_lcs_fv import ImageNetSiftLcsFV  # noqa: F401
from keystone_tpu.pipelines.voc_sift_fisher import VOCSIFTFisher  # noqa: F401
from keystone_tpu.pipelines.amazon_reviews import AmazonReviewsPipeline  # noqa: F401
from keystone_tpu.pipelines.kernel_timit import KernelTimitPipeline  # noqa: F401
from keystone_tpu.pipelines.kernel_cifar import KernelCifarPipeline  # noqa: F401

ALL_PIPELINES = {
    "MnistRandomFFT": MnistRandomFFT,
    "LinearPixels": LinearPixels,
    "RandomPatchCifar": RandomPatchCifar,
    "NewsgroupsPipeline": NewsgroupsPipeline,
    "TimitPipeline": TimitPipeline,
    "ImageNetSiftLcsFV": ImageNetSiftLcsFV,
    "VOCSIFTFisher": VOCSIFTFisher,
    "AmazonReviewsPipeline": AmazonReviewsPipeline,
    "KernelTimitPipeline": KernelTimitPipeline,
    "KernelCifarPipeline": KernelCifarPipeline,
}
