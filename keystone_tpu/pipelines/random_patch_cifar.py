"""RandomPatchCifar (reference
pipelines/images/cifar/RandomPatchCifar.scala):

RandomPatcher (patches from train images) → ZCAWhitener (fit on patches)
→ Convolver with the whitened patches as filters → SymmetricRectifier →
sum-Pooler over a grid → flatten/standardize → BlockLeastSquares →
MaxClassifier.

As in the reference, the filter learning (patch sampling + ZCA) happens
imperatively at build time; the resulting Convolver folds the whitening
into its filters (Convolver.from_whitened_patches)."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

import jax.numpy as jnp

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.cifar import CifarLoader, NUM_CLASSES
from keystone_tpu.models import BlockLeastSquaresEstimator, ZCAWhitenerEstimator
from keystone_tpu.ops import (
    ClassLabelIndicators,
    Convolver,
    ImageVectorizer,
    MaxClassifier,
    Pooler,
    RandomPatcher,
    SymmetricRectifier,
)
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.workflow import Dataset, Pipeline


@dataclasses.dataclass
class Config:
    train_path: Optional[str] = None
    test_path: Optional[str] = None
    num_filters: int = 256
    patch_size: int = 6
    patches_per_image: int = 10
    pool_size: int = 13
    pool_stride: int = 13
    alpha: float = 0.25
    lam: float = 1e-2
    block_size: int = 1024
    num_iter: int = 2
    zca_eps: float = 0.1
    seed: int = 0
    synthetic_n: int = 512
    model_path: Optional[str] = None


class RandomPatchCifar:
    name = "RandomPatchCifar"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_labels: Dataset) -> Pipeline:
        # --- feature learning (imperative, as upstream) ---
        patcher = RandomPatcher(
            config.patches_per_image, config.patch_size, config.patch_size,
            seed=config.seed,
        )
        patches = patcher.apply_dataset(train_x)  # (n*ppi, ps*ps*3)
        num = min(config.num_filters, patches.n)
        flat = patches.array[:num]
        whitener = ZCAWhitenerEstimator(eps=config.zca_eps).fit_dataset(patches)
        white_patches = whitener.apply_batch(flat)
        conv = Convolver.from_whitened_patches(
            white_patches,
            whitener,
            (config.patch_size, config.patch_size, 3),
        )
        featurizer = (
            Pipeline.of(conv)
            .and_then(SymmetricRectifier(alpha=config.alpha))
            .and_then(Pooler(config.pool_stride, config.pool_size))
            .and_then(ImageVectorizer())
        )
        labels_pm1 = ClassLabelIndicators(NUM_CLASSES)(train_labels)
        scaled = featurizer.and_then(StandardScaler(), train_x)
        return scaled.and_then(
            BlockLeastSquaresEstimator(
                block_size=config.block_size,
                num_iter=config.num_iter,
                lam=config.lam,
            ),
            train_x,
            labels_pm1,
        ).and_then(MaxClassifier())

    @staticmethod
    def run(config: Config) -> dict:
        if config.train_path:
            test = CifarLoader.load(config.test_path or config.train_path)
        else:
            test = CifarLoader.synthetic(config.synthetic_n // 4, seed=2)

        def build():
            # train loads ONLY when a fit is needed (saved-model runs skip it)
            train = (
                CifarLoader.load(config.train_path)
                if config.train_path
                else CifarLoader.synthetic(config.synthetic_n, seed=1)
            )
            return RandomPatchCifar.build(config, train.data, train.labels)

        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path, build, config=fit_relevant_config(config)
        )
        fit_time = time.time() - t0
        preds = fitted(test.data).get()
        m = MulticlassClassifierEvaluator(NUM_CLASSES).evaluate(preds, test.labels)
        return {
            "pipeline": RandomPatchCifar.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "test_error": m.total_error,
            "accuracy": m.accuracy,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=RandomPatchCifar.name)
    p.add_argument("--train-path")
    p.add_argument("--test-path")
    p.add_argument("--num-filters", type=int, default=256)
    p.add_argument("--lam", type=float, default=1e-2)
    p.add_argument("--synthetic-n", type=int, default=512)
    p.add_argument("--model-path")
    a = p.parse_args(argv)
    cfg = Config(
        train_path=a.train_path,
        test_path=a.test_path,
        num_filters=a.num_filters,
        lam=a.lam,
        synthetic_n=a.synthetic_n,
        model_path=a.model_path,
    )
    print(RandomPatchCifar.run(cfg))


if __name__ == "__main__":
    main()
