"""NewsgroupsPipeline (reference pipelines/text/NewsgroupsPipeline.scala):
Trim → LowerCase → Tokenizer → NGrams(1,2) → log TermFrequency →
CommonSparseFeatures → NaiveBayes (or least squares) → MaxClassifier."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader
from keystone_tpu.models import LinearMapEstimator, NaiveBayesEstimator
from keystone_tpu.ops import (
    ClassLabelIndicators,
    CommonSparseFeatures,
    LowerCase,
    MaxClassifier,
    NGramsFeaturizer,
    TermFrequency,
    Tokenizer,
    Trimmer,
    log_tf,
)
from keystone_tpu.workflow import Dataset, Pipeline



@dataclasses.dataclass
class Config:
    data_path: Optional[str] = None
    test_path: Optional[str] = None
    num_features: int = 100000
    ngrams: int = 2
    head: str = "nb"  # "nb" | "ls"
    nb_lam: float = 1.0
    ls_lam: float = 1e-2
    num_classes: int = 4
    synthetic_n: int = 400
    model_path: Optional[str] = None
    # out-of-core: stream raw document texts from the directory tree per
    # sweep (host StreamDataset); requires test_path, since the
    # train/test split of a stream is the caller's responsibility
    stream: bool = False
    stream_batch_size: int = 512


class NewsgroupsPipeline:
    name = "NewsgroupsPipeline"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_labels: Dataset) -> Pipeline:
        # ONE decision for representation AND solver contract: sparse
        # features imply the sparse heads (NB counts via scatter-add;
        # LS via the no-intercept sparse-gradient solver — desyncing
        # representation and solver silently changes model semantics)
        sparse = config.num_features >= 16384
        featurizer = (
            Pipeline.of(Trimmer())
            .and_then(LowerCase())
            .and_then(Tokenizer())
            .and_then(NGramsFeaturizer(tuple(range(1, config.ngrams + 1))))
            .and_then(TermFrequency(log_tf))
            # large vocabularies stay CSR end to end: NB scatter-adds
            # its counts, LS fits via the sparse-gradient solver — the
            # reference NodeOptimizationRule's dense-vs-sparse choice
            .and_then(
                CommonSparseFeatures(config.num_features, sparse_output=sparse),
                train_x,
            )
        )
        if config.head == "nb":
            head = featurizer.and_then(
                NaiveBayesEstimator(config.num_classes, lam=config.nb_lam),
                train_x,
                train_labels,
            )
        else:
            labels_pm1 = ClassLabelIndicators(config.num_classes)(train_labels)
            # the sparse route fits no intercept (centering would
            # densify): make that explicit at the call site instead of
            # relying on the swap's runtime warning
            head = featurizer.and_then(
                LinearMapEstimator(
                    lam=config.ls_lam, fit_intercept=not sparse
                ),
                train_x,
                labels_pm1,
            )
        return head.and_then(MaxClassifier())

    @staticmethod
    def run(config: Config) -> dict:
        # train/test come from ONE load+split, so the load stays eager
        # (the test half is always needed, even for saved-model runs)
        if config.stream and config.data_path and not config.test_path:
            raise ValueError(
                "--stream needs --test-path: a streamed train tree "
                "cannot be split in place"
            )
        if config.data_path and config.test_path:
            import os

            # ONE group→label mapping from the TRAIN tree's group DIRS,
            # shared with the test load — independently-derived mappings
            # would silently misalign labels when the trees differ, and
            # stray files must not become phantom classes
            groups = sorted(
                g
                for g in os.listdir(config.data_path)
                if os.path.isdir(os.path.join(config.data_path, g))
            )
            if config.stream:
                train = NewsgroupsDataLoader.stream(
                    config.data_path,
                    groups=groups,
                    batch_size=config.stream_batch_size,
                )
            else:
                train = NewsgroupsDataLoader.load(
                    config.data_path, groups=groups
                )
            test = NewsgroupsDataLoader.load(config.test_path, groups=groups)
            config = dataclasses.replace(config, num_classes=len(groups))
        elif config.data_path:
            data = NewsgroupsDataLoader.load(config.data_path)
            num_classes = int(data.labels.numpy().max()) + 1
            config = dataclasses.replace(config, num_classes=num_classes)
            train, test = data.split(0.8, seed=0)
        else:
            train = NewsgroupsDataLoader.synthetic(
                config.synthetic_n, config.num_classes, seed=1
            )
            test = NewsgroupsDataLoader.synthetic(
                config.synthetic_n // 4, config.num_classes, seed=2
            )
        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path,
            lambda: NewsgroupsPipeline.build(config, train.data, train.labels),
            config=fit_relevant_config(config),
        )
        fit_time = time.time() - t0
        preds = fitted(test.data).get()
        m = MulticlassClassifierEvaluator(config.num_classes).evaluate(
            preds, test.labels
        )
        return {
            "pipeline": NewsgroupsPipeline.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "test_error": m.total_error,
            "accuracy": m.accuracy,
            "macro_f1": m.macro_f1,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=NewsgroupsPipeline.name)
    p.add_argument("--data-path")
    p.add_argument("--test-path")
    p.add_argument("--num-features", type=int, default=100000)
    p.add_argument("--head", choices=["nb", "ls"], default="nb")
    p.add_argument("--synthetic-n", type=int, default=400)
    p.add_argument("--model-path")
    p.add_argument(
        "--stream",
        "--out-of-core",
        action="store_true",
        dest="stream",
        help="stream raw document texts from the train tree per sweep "
        "(requires --test-path)",
    )
    p.add_argument("--stream-batch-size", type=int, default=512)
    a = p.parse_args(argv)
    cfg = Config(
        data_path=a.data_path,
        test_path=a.test_path,
        num_features=a.num_features,
        head=a.head,
        synthetic_n=a.synthetic_n,
        model_path=a.model_path,
        stream=a.stream,
        stream_batch_size=a.stream_batch_size,
    )
    print(NewsgroupsPipeline.run(cfg))


if __name__ == "__main__":
    main()
