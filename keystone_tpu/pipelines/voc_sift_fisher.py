"""VOCSIFTFisher (reference pipelines/images/voc/VOCSIFTFisher.scala):
SIFT → PCA → GMM Fisher vectors → BlockWeightedLeastSquares on multilabel
±1 targets → mean average precision."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator
from keystone_tpu.loaders.voc import VOCLoader, NUM_CLASSES
from keystone_tpu.models import BlockWeightedLeastSquaresEstimator
from keystone_tpu.ops import GrayScaler, PixelScaler, SIFTExtractor
from keystone_tpu.workflow import Dataset, Pipeline


@dataclasses.dataclass
class Config:
    images_dir: Optional[str] = None
    annotations_dir: Optional[str] = None
    sift_step: int = 6
    sift_bin_size: int = 4
    pca_dims: int = 64
    gmm_k: int = 16
    gmm_iters: int = 10
    descriptor_samples_per_image: int = 64
    lam: float = 1e-4
    mixture_weight: float = 0.25
    solver_block_size: int = 4096
    num_epochs: int = 2
    seed: int = 0
    synthetic_n: int = 48
    image_size: int = 64
    model_path: Optional[str] = None


class VOCSIFTFisher:
    name = "VOCSIFTFisher"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_multilabels: Dataset) -> Pipeline:
        from keystone_tpu.pipelines.imagenet_sift_lcs_fv import _fv_branch

        # uint8 images → [0,1] floats on device (cheap transfer; see
        # ImageNetSiftLcsFV.build)
        sift_base = (
            Pipeline.of(PixelScaler(only_if_integer=True))
            .and_then(GrayScaler())
            .and_then(
                SIFTExtractor(
                    step=config.sift_step, bin_sizes=(config.sift_bin_size,)
                )
            )
        )
        branch = _fv_branch(sift_base, config, train_x, seed=config.seed)
        # multilabels are 0/1; targets are ±1
        from keystone_tpu.workflow import transformer

        to_pm1 = transformer(
            lambda y: y * 2.0 - 1.0, name="MultilabelPM1"
        )
        labels_pm1 = to_pm1(train_multilabels)
        return branch.and_then(
            BlockWeightedLeastSquaresEstimator(
                block_size=config.solver_block_size,
                num_iter=config.num_epochs,
                lam=config.lam,
                mixture_weight=config.mixture_weight,
            ),
            train_x,
            labels_pm1,
        )

    @staticmethod
    def run(config: Config) -> dict:
        # train/test come from ONE load+split, so the load stays eager
        # (the test half is always needed, even for saved-model runs)
        sz = (config.image_size, config.image_size)
        if config.images_dir:
            # image_size governs the resize for real JPEGs too (the
            # ImageNet app's convention)
            data = VOCLoader.load(
                config.images_dir, config.annotations_dir, size=sz
            )
            train, test = data.split(0.7, seed=0)
        else:
            train = VOCLoader.synthetic(config.synthetic_n, size=sz, seed=1)
            test = VOCLoader.synthetic(max(8, config.synthetic_n // 3), size=sz, seed=2)
        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path,
            lambda: VOCSIFTFisher.build(config, train.data, train.labels),
            config=fit_relevant_config(config),
        )
        fit_time = time.time() - t0
        scores = fitted(test.data).get().numpy()
        mean_ap = MeanAveragePrecisionEvaluator(NUM_CLASSES).evaluate(
            scores, test.labels.numpy()
        )
        return {
            "pipeline": VOCSIFTFisher.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "mean_ap": mean_ap,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=VOCSIFTFisher.name)
    p.add_argument("--images-dir")
    p.add_argument("--annotations-dir")
    p.add_argument("--gmm-k", type=int, default=16)
    p.add_argument("--synthetic-n", type=int, default=48)
    p.add_argument("--model-path")
    a = p.parse_args(argv)
    cfg = Config(
        images_dir=a.images_dir,
        annotations_dir=a.annotations_dir,
        gmm_k=a.gmm_k,
        synthetic_n=a.synthetic_n,
        model_path=a.model_path,
    )
    print(VOCSIFTFisher.run(cfg))


if __name__ == "__main__":
    main()
