"""VOCSIFTFisher (reference pipelines/images/voc/VOCSIFTFisher.scala):
SIFT → PCA → GMM Fisher vectors → BlockWeightedLeastSquares on multilabel
±1 targets → mean average precision."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator
from keystone_tpu.loaders.voc import VOCLoader, NUM_CLASSES
from keystone_tpu.models import BlockWeightedLeastSquaresEstimator
from keystone_tpu.ops import GrayScaler, PixelScaler, SIFTExtractor
from keystone_tpu.workflow import Dataset, Pipeline


@dataclasses.dataclass
class Config:
    images_dir: Optional[str] = None
    annotations_dir: Optional[str] = None
    sift_step: int = 6
    sift_bin_size: int = 4
    pca_dims: int = 64
    gmm_k: int = 16
    gmm_iters: int = 10
    descriptor_samples_per_image: int = 64
    lam: float = 1e-4
    mixture_weight: float = 0.25
    solver_block_size: int = 4096
    num_epochs: int = 2
    seed: int = 0
    synthetic_n: int = 48
    image_size: int = 64
    model_path: Optional[str] = None
    # out-of-core: stream training JPEGs (re-decoded per sweep on a
    # prefetch thread) so the FV feature matrix spills to a disk block
    # store instead of HBM — the last of the eight apps to gain the
    # uniform --stream story (VERDICT r3 weak-4)
    stream: bool = False
    stream_batch_size: int = 32


class VOCSIFTFisher:
    name = "VOCSIFTFisher"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_multilabels: Dataset) -> Pipeline:
        from keystone_tpu.pipelines.imagenet_sift_lcs_fv import _fv_branch

        # uint8 images → [0,1] floats on device (cheap transfer; see
        # ImageNetSiftLcsFV.build)
        sift_base = (
            Pipeline.of(PixelScaler(only_if_integer=True))
            .and_then(GrayScaler())
            .and_then(
                SIFTExtractor(
                    step=config.sift_step, bin_sizes=(config.sift_bin_size,)
                )
            )
        )
        branch = _fv_branch(sift_base, config, train_x, seed=config.seed)
        # multilabels are 0/1; targets are ±1
        from keystone_tpu.workflow import transformer

        to_pm1 = transformer(
            lambda y: y * 2.0 - 1.0, name="MultilabelPM1"
        )
        labels_pm1 = to_pm1(train_multilabels)
        return branch.and_then(
            BlockWeightedLeastSquaresEstimator(
                block_size=config.solver_block_size,
                num_iter=config.num_epochs,
                lam=config.lam,
                mixture_weight=config.mixture_weight,
            ),
            train_x,
            labels_pm1,
        )

    @staticmethod
    def run(config: Config) -> dict:
        import numpy as np

        sz = (config.image_size, config.image_size)
        if config.images_dir:
            # image_size governs the resize for real JPEGs too (the
            # ImageNet app's convention).  The 70/30 split follows
            # LabeledData.split's convention (seeded permutation) but is
            # computed over the INDEX so the train rows can stream
            # without decoding the test rows eagerly first.
            # ONE XML pass shared by the test load and train load/stream
            idx = VOCLoader.index(config.images_dir, config.annotations_dir)
            n_total = len(idx[0])
            perm = np.random.default_rng(0).permutation(n_total)
            cut = int(n_total * 0.7)
            test = VOCLoader.load(
                config.images_dir,
                config.annotations_dir,
                size=sz,
                indices=perm[cut:],
                index=idx,
            )

            def _train():
                if config.stream:
                    return VOCLoader.stream(
                        config.images_dir,
                        config.annotations_dir,
                        size=sz,
                        batch_size=config.stream_batch_size,
                        indices=perm[:cut],
                        index=idx,
                    )
                return VOCLoader.load(
                    config.images_dir,
                    config.annotations_dir,
                    size=sz,
                    indices=perm[:cut],
                    index=idx,
                )

        else:
            test = VOCLoader.synthetic(
                max(8, config.synthetic_n // 3), size=sz, seed=2
            )

            def _train():
                if config.stream:
                    return VOCLoader.synthetic_stream(
                        config.synthetic_n,
                        size=sz,
                        seed=1,
                        batch_size=config.stream_batch_size,
                    )
                return VOCLoader.synthetic(config.synthetic_n, size=sz, seed=1)

        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        def build():
            # loaded ONLY when a fit is needed (saved-model runs skip it)
            train = _train()
            return VOCSIFTFisher.build(config, train.data, train.labels)

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path,
            build,
            config=fit_relevant_config(config),
        )
        fit_time = time.time() - t0
        scores = fitted(test.data).get().numpy()
        mean_ap = MeanAveragePrecisionEvaluator(NUM_CLASSES).evaluate(
            scores, test.labels.numpy()
        )
        return {
            "pipeline": VOCSIFTFisher.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "mean_ap": mean_ap,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=VOCSIFTFisher.name)
    p.add_argument("--images-dir")
    p.add_argument("--annotations-dir")
    p.add_argument("--gmm-k", type=int, default=16)
    p.add_argument("--synthetic-n", type=int, default=48)
    p.add_argument("--model-path")
    p.add_argument(
        "--stream",
        "--out-of-core",
        action="store_true",
        dest="stream",
        help="stream training JPEGs from disk; FV features spill to a "
        "disk block store instead of residing in HBM",
    )
    p.add_argument("--stream-batch-size", type=int, default=32)
    a = p.parse_args(argv)
    cfg = Config(
        images_dir=a.images_dir,
        annotations_dir=a.annotations_dir,
        gmm_k=a.gmm_k,
        synthetic_n=a.synthetic_n,
        model_path=a.model_path,
        stream=a.stream,
        stream_batch_size=a.stream_batch_size,
    )
    print(VOCSIFTFisher.run(cfg))


if __name__ == "__main__":
    main()
