"""ImageNetSiftLcsFV — the north-star workload (reference
pipelines/images/imagenet/ImageNetSiftLcsFV.scala):

Two branches over the input images:
  SIFT: GrayScaler → dense SIFT → [PCA(64) fit on sampled descriptors] →
        [GMM(k) fit on sampled projected descriptors] → FisherVector →
        SignedHellinger → NormalizeRows
  LCS:  LCSExtractor → same PCA/GMM/FV tail
concat (gather) → BlockWeightedLeastSquares → TopKClassifier(5);
top-5 error via MulticlassClassifierEvaluator / AugmentedExamplesEvaluator.

The PCA and GMM vocabulary fits happen *inside* the pipeline graph on
ColumnSampler-reduced descriptor sets rooted at the training Dataset, so
the CSE rule merges the shared SIFT/LCS prefixes — the featurization of
the training set runs once even though three estimators consume it.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

import numpy as np

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.imagenet import ImageNetLoader
from keystone_tpu.models import BlockWeightedLeastSquaresEstimator, PCAEstimator
from keystone_tpu.ops import (
    ClassLabelIndicators,
    ColumnSampler,
    GMMFisherVectorEstimator,
    GrayScaler,
    LCSExtractor,
    NormalizeRows,
    PixelScaler,
    SIFTExtractor,
    SignedHellingerMapper,
    TopKClassifier,
)
from keystone_tpu.workflow import Dataset, Pipeline


@dataclasses.dataclass
class Config:
    train_path: Optional[str] = None
    test_path: Optional[str] = None
    num_classes: int = 16
    sift_step: int = 6
    sift_bin_size: int = 4
    lcs_step: int = 6
    lcs_subpatch: int = 6
    pca_dims: int = 64
    gmm_k: int = 16
    gmm_iters: int = 10
    descriptor_samples_per_image: int = 64
    lam: float = 1e-4
    mixture_weight: float = 0.25
    solver_block_size: int = 4096
    num_epochs: int = 2
    top_k: int = 5
    seed: int = 0
    synthetic_n: int = 64
    image_size: int = 64
    # reference 10-view test-time augmentation (center+corners × flips,
    # AugmentedExamplesEvaluator); view_patch=0 → ⅞ of image_size
    augmented_eval: bool = False
    view_patch: int = 0
    # persist/reuse the fitted pipeline (standard and augmented paths;
    # the config is saved alongside and checked on load)
    model_path: Optional[str] = None
    # out-of-core: load training images as a StreamDataset (tar shards
    # re-decoded per sweep on a prefetch thread) so the feature matrix
    # spills to a FeatureBlockStore instead of HBM — the reference's
    # ImageNetLoader-streams-through-RDD-partitions scaling path
    stream: bool = False
    stream_batch_size: int = 64


def _fv_branch(base: Pipeline, config: Config, train_x: Dataset, seed: int) -> Pipeline:
    """descriptor extractor pipeline → PCA → GMM/FV → normalization."""
    sampled = ColumnSampler(config.descriptor_samples_per_image, seed=seed)(
        base(train_x)
    )
    pca_pipe = Pipeline.from_estimator(
        PCAEstimator(config.pca_dims, center=True), sampled
    )
    with_pca = base.then_pipeline(pca_pipe)
    gmm_sampled = ColumnSampler(config.descriptor_samples_per_image, seed=seed + 1)(
        with_pca(train_x)
    )
    fv_pipe = Pipeline.from_estimator(
        GMMFisherVectorEstimator(
            config.gmm_k, max_iterations=config.gmm_iters, seed=seed
        ),
        gmm_sampled,
    )
    return (
        with_pca.then_pipeline(fv_pipe)
        .and_then(SignedHellingerMapper())
        .and_then(NormalizeRows())
    )


class ImageNetSiftLcsFV:
    name = "ImageNetSiftLcsFV"
    Config = Config

    @staticmethod
    def build_scorer(
        config: Config, train_x: Dataset, train_labels: Dataset
    ) -> Pipeline:
        """Pipeline ending at raw class scores (no prediction head) —
        what augmented-view evaluation averages before argmax."""
        # images arrive as uint8 (4× cheaper host→device transfer — the
        # dominant cost at scale); scale to [0,1] floats ON DEVICE.  Both
        # branches start with an identical PixelScaler, so CSE merges the
        # cast into one node.
        sift_base = (
            Pipeline.of(PixelScaler(only_if_integer=True))
            .and_then(GrayScaler())
            .and_then(
                SIFTExtractor(
                    step=config.sift_step, bin_sizes=(config.sift_bin_size,)
                )
            )
        )
        lcs_base = Pipeline.of(PixelScaler(only_if_integer=True)).and_then(
            LCSExtractor(step=config.lcs_step, subpatch_size=config.lcs_subpatch)
        )
        sift_branch = _fv_branch(sift_base, config, train_x, seed=config.seed)
        lcs_branch = _fv_branch(lcs_base, config, train_x, seed=config.seed + 100)
        featurizer = Pipeline.gather([sift_branch, lcs_branch])
        labels_pm1 = ClassLabelIndicators(config.num_classes)(train_labels)
        return featurizer.and_then(
            BlockWeightedLeastSquaresEstimator(
                block_size=config.solver_block_size,
                num_iter=config.num_epochs,
                lam=config.lam,
                mixture_weight=config.mixture_weight,
            ),
            train_x,
            labels_pm1,
        )

    @staticmethod
    def build(config: Config, train_x: Dataset, train_labels: Dataset) -> Pipeline:
        return ImageNetSiftLcsFV.build_scorer(
            config, train_x, train_labels
        ).and_then(TopKClassifier(config.top_k))

    @staticmethod
    def run(config: Config) -> dict:
        sz = (config.image_size, config.image_size)
        if config.train_path:
            # image_size governs the resize for real tars too, so train
            # and test always agree on resolution
            test = ImageNetLoader.load(
                config.test_path or config.train_path, size=sz
            )
        else:
            test = ImageNetLoader.synthetic(
                max(8, config.synthetic_n // 4), config.num_classes, size=sz, seed=2
            )

        def _train():
            # loaded ONLY when a fit is needed (saved-model runs skip it)
            if config.stream:
                if config.train_path:
                    return ImageNetLoader.stream(
                        config.train_path,
                        size=sz,
                        batch_size=config.stream_batch_size,
                    )
                return ImageNetLoader.synthetic_stream(
                    config.synthetic_n,
                    config.num_classes,
                    size=sz,
                    seed=1,
                    batch_size=config.stream_batch_size,
                )
            if config.train_path:
                return ImageNetLoader.load(config.train_path, size=sz)
            return ImageNetLoader.synthetic(
                config.synthetic_n, config.num_classes, size=sz, seed=1
            )

        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        labs = test.labels.numpy()
        if config.augmented_eval:
            # reference path: score 10 views per test image, average
            # scores per image id, then classify (call stack SURVEY §3.4)
            from keystone_tpu.evaluation import AugmentedExamplesEvaluator
            from keystone_tpu.ops import CenterCornerPatcher

            def build_scorer():
                train = _train()
                return ImageNetSiftLcsFV.build_scorer(
                    config, train.data, train.labels
                )

            t0 = time.time()
            scorer, loaded = FittedPipeline.fit_or_load(
                config.model_path, build_scorer, config=fit_relevant_config(config)
            )
            fit_time = time.time() - t0
            # crop to the true count — Dataset.array carries mesh-padding
            # rows that would otherwise become phantom test images; patch
            # size follows the ACTUAL image height (test_path images need
            # not match the synthetic-data image_size knob)
            imgs = test.data.array[: test.data.n]
            p = config.view_patch or (imgs.shape[1] * 7 // 8)
            views = CenterCornerPatcher(p, p, horizontal_flips=True).apply_batch(
                imgs
            )
            n, nv = views.shape[0], views.shape[1]
            flat = Dataset(views.reshape(n * nv, p, p, views.shape[-1]))
            scores = scorer(flat).get().numpy()
            ids = np.repeat(np.arange(n), nv)
            evaluator = AugmentedExamplesEvaluator(config.num_classes)
            m = evaluator.evaluate(scores, ids, labs)
            # top-k from the SAME per-image aggregation evaluate uses
            agg, _ = evaluator.averaged_scores(scores, ids)
            order = np.argsort(-agg, axis=1)[:, : config.top_k]
            topk_hit = (order == labs[:, None]).any(axis=1)
        else:

            def build():
                train = _train()
                return ImageNetSiftLcsFV.build(config, train.data, train.labels)

            t0 = time.time()
            fitted, loaded = FittedPipeline.fit_or_load(
                config.model_path, build, config=fit_relevant_config(config)
            )
            fit_time = time.time() - t0
            topk = fitted(test.data).get().numpy()  # (n, top_k) class ids
            top1 = topk[:, 0]
            topk_hit = (topk == labs[:, None]).any(axis=1)
            m = MulticlassClassifierEvaluator(config.num_classes).evaluate(
                top1, labs
            )
        return {
            "pipeline": ImageNetSiftLcsFV.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "top1_error": m.total_error,
            "top5_error": float(1.0 - topk_hit.mean()),
            "accuracy": m.accuracy,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=ImageNetSiftLcsFV.name)
    p.add_argument("--train-path")
    p.add_argument("--test-path")
    p.add_argument("--num-classes", type=int, default=16)
    p.add_argument("--gmm-k", type=int, default=16)
    p.add_argument("--pca-dims", type=int, default=64)
    p.add_argument("--lam", type=float, default=1e-4)
    p.add_argument("--synthetic-n", type=int, default=64)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--augmented-eval", action="store_true")
    p.add_argument("--model-path")
    p.add_argument(
        "--stream",
        "--out-of-core",
        action="store_true",
        dest="stream",
        help="stream training images from tar shards; features spill to "
        "a disk block store instead of residing in HBM",
    )
    p.add_argument("--stream-batch-size", type=int, default=64)
    a = p.parse_args(argv)
    cfg = Config(
        train_path=a.train_path,
        test_path=a.test_path,
        num_classes=a.num_classes,
        gmm_k=a.gmm_k,
        pca_dims=a.pca_dims,
        lam=a.lam,
        synthetic_n=a.synthetic_n,
        image_size=a.image_size,
        augmented_eval=a.augmented_eval,
        model_path=a.model_path,
        stream=a.stream,
        stream_batch_size=a.stream_batch_size,
    )
    print(ImageNetSiftLcsFV.run(cfg))


if __name__ == "__main__":
    main()
