"""MnistRandomFFT (reference
pipelines/images/mnist/MnistRandomFFT.scala): replicate
{RandomSignNode → PaddedFFT → LinearRectifier} × num_ffts over the pixel
vector, gather/concat, exact least squares, MaxClassifier."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.mnist import MnistLoader, NUM_CLASSES
from keystone_tpu.models import LinearMapEstimator
from keystone_tpu.ops import (
    ClassLabelIndicators,
    LinearRectifier,
    MaxClassifier,
    PaddedFFT,
    PixelScaler,
    RandomSignNode,
)
from keystone_tpu.workflow import Dataset, Pipeline


@dataclasses.dataclass
class Config:
    train_path: Optional[str] = None
    test_path: Optional[str] = None
    num_ffts: int = 4
    lam: float = 1e-2
    seed: int = 0
    synthetic_n: int = 2048
    # persist/reuse the fitted pipeline (the reference's serializable
    # PipelineModel flow): fit once, save; later runs load and only score
    model_path: Optional[str] = None
    # out-of-core: re-parse the training CSV per sweep; the exact solver
    # accumulates sufficient statistics batch-by-batch
    stream: bool = False
    stream_batch_size: int = 4096


class MnistRandomFFT:
    name = "MnistRandomFFT"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_labels: Dataset) -> Pipeline:
        (dim,) = train_x.item_shape  # stream-safe (peeks one batch)
        branches = [
            Pipeline.of(RandomSignNode.init(dim, seed=config.seed + i))
            .and_then(PaddedFFT())
            .and_then(LinearRectifier(0.0))
            for i in range(config.num_ffts)
        ]
        # pixels → [0,1] before featurizing: keeps the f32 solver's normal
        # equations well-conditioned (the f64 reference skipped this)
        featurizer = Pipeline.of(PixelScaler()).then_pipeline(
            Pipeline.gather(branches)
        )
        labels_pm1 = ClassLabelIndicators(NUM_CLASSES)(train_labels)
        return featurizer.and_then(
            LinearMapEstimator(lam=config.lam), train_x, labels_pm1
        ).and_then(MaxClassifier())

    @staticmethod
    def run(config: Config) -> dict:
        from keystone_tpu.loaders.stream import require_stream_test_path

        require_stream_test_path(config)
        if config.train_path:
            test = MnistLoader.load(config.test_path or config.train_path)
        else:
            test = MnistLoader.synthetic(config.synthetic_n // 4, seed=2)

        def build():
            # training data loads ONLY when a fit is actually needed —
            # scoring runs with a saved model skip it entirely
            from keystone_tpu.loaders.stream import resolve_train_source

            train = resolve_train_source(
                config,
                load=MnistLoader.load,
                stream=MnistLoader.stream,
                synthetic=lambda: MnistLoader.synthetic(
                    config.synthetic_n, seed=1
                ),
            )
            return MnistRandomFFT.build(config, train.data, train.labels)

        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path, build, config=fit_relevant_config(config)
        )
        fit_time = time.time() - t0
        preds = fitted(test.data).get()
        metrics = MulticlassClassifierEvaluator(NUM_CLASSES).evaluate(
            preds, test.labels
        )
        return {
            "pipeline": MnistRandomFFT.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "test_error": metrics.total_error,
            "accuracy": metrics.accuracy,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=MnistRandomFFT.name)
    p.add_argument("--train-path")
    p.add_argument("--test-path")
    p.add_argument("--num-ffts", type=int, default=4)
    p.add_argument("--lam", type=float, default=1e-2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--synthetic-n", type=int, default=2048)
    p.add_argument("--model-path")
    from keystone_tpu.loaders.stream import add_stream_args

    add_stream_args(p, default_batch_size=4096, noun="the training CSV")
    a = p.parse_args(argv)
    cfg = Config(
        a.train_path, a.test_path, a.num_ffts, a.lam, a.seed, a.synthetic_n,
        model_path=a.model_path,
        stream=a.stream,
        stream_batch_size=a.stream_batch_size,
    )
    print(MnistRandomFFT.run(cfg))


if __name__ == "__main__":
    main()
