"""KernelCifarPipeline — kernel CIFAR via Nyström: raw pixels →
ImageVectorizer → StandardScaler → NystromFeatures → BlockLeastSquares
→ MaxClassifier.

The kernel counterpart of ``pipelines/linear_pixels.py``: same input
plumbing, but the linear solve runs in the m-dimensional Nyström
feature space of a Gaussian kernel over scaled pixels — the scenario
family the kernel BCD line (arXiv:1602.05310) evaluates.  ``--stream``
keeps CIFAR records out of core."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.cifar import CifarLoader, NUM_CLASSES
from keystone_tpu.models import BlockLeastSquaresEstimator, NystromFeatures
from keystone_tpu.models.kernel_ridge import GaussianKernelGenerator
from keystone_tpu.ops import ClassLabelIndicators, ImageVectorizer, MaxClassifier
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.workflow import Dataset, Pipeline


@dataclasses.dataclass
class Config:
    train_path: Optional[str] = None
    test_path: Optional[str] = None
    num_landmarks: int = 2048
    gamma: float = 2e-4
    nystrom_reg: float = 1e-7
    num_epochs: int = 3
    lam: float = 1e-5
    solver_block_size: int = 1024
    seed: int = 0
    synthetic_n: int = 1024
    model_path: Optional[str] = None
    # out-of-core: re-read CIFAR records from disk per pass
    stream: bool = False
    stream_batch_size: int = 1024


class KernelCifarPipeline:
    name = "KernelCifarPipeline"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_labels: Dataset) -> Pipeline:
        kern = GaussianKernelGenerator(config.gamma)
        labels_pm1 = ClassLabelIndicators(NUM_CLASSES)(train_labels)
        vec = Pipeline.of(ImageVectorizer())
        scaled = vec.and_then(
            StandardScaler().with_data(vec(train_x))
        )
        return (
            scaled.and_then(
                NystromFeatures(
                    kern,
                    num_landmarks=config.num_landmarks,
                    reg=config.nystrom_reg,
                    seed=config.seed,
                ),
                train_x,
            )
            .and_then(
                BlockLeastSquaresEstimator(
                    block_size=config.solver_block_size,
                    num_iter=config.num_epochs,
                    lam=config.lam,
                ),
                train_x,
                labels_pm1,
            )
            .and_then(MaxClassifier())
        )

    @staticmethod
    def run(config: Config) -> dict:
        from keystone_tpu.loaders.stream import require_stream_test_path

        require_stream_test_path(config)
        if config.train_path:
            test = CifarLoader.load(config.test_path or config.train_path)
        else:
            test = CifarLoader.synthetic(config.synthetic_n // 4, seed=2)

        def build():
            from keystone_tpu.loaders.stream import resolve_train_source

            train = resolve_train_source(
                config,
                load=CifarLoader.load,
                stream=CifarLoader.stream,
                synthetic=lambda: CifarLoader.synthetic(
                    config.synthetic_n, seed=1
                ),
            )
            return KernelCifarPipeline.build(config, train.data, train.labels)

        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path, build, config=fit_relevant_config(config)
        )
        fit_time = time.time() - t0
        preds = fitted(test.data).get()
        m = MulticlassClassifierEvaluator(NUM_CLASSES).evaluate(
            preds, test.labels
        )
        return {
            "pipeline": KernelCifarPipeline.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "test_error": m.total_error,
            "accuracy": m.accuracy,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=KernelCifarPipeline.name)
    p.add_argument("--train-path")
    p.add_argument("--test-path")
    p.add_argument("--num-landmarks", type=int, default=2048)
    p.add_argument("--gamma", type=float, default=2e-4)
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--lam", type=float, default=1e-5)
    p.add_argument("--synthetic-n", type=int, default=1024)
    p.add_argument("--model-path")
    from keystone_tpu.loaders.stream import add_stream_args

    add_stream_args(p, default_batch_size=1024, noun="CIFAR records")
    a = p.parse_args(argv)
    print(KernelCifarPipeline.run(Config(
        train_path=a.train_path,
        test_path=a.test_path,
        num_landmarks=a.num_landmarks,
        gamma=a.gamma,
        num_epochs=a.num_epochs,
        lam=a.lam,
        synthetic_n=a.synthetic_n,
        model_path=a.model_path,
        stream=a.stream,
        stream_batch_size=a.stream_batch_size,
    )))


if __name__ == "__main__":
    main()
