"""AmazonReviewsPipeline (reference
pipelines/text/AmazonReviewsPipeline.scala): n-grams → term frequency →
feature hashing → logistic regression (binary sentiment)."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import BinaryClassifierEvaluator
from keystone_tpu.loaders.amazon import AmazonReviewsDataLoader
from keystone_tpu.models import LogisticRegressionEstimator
from keystone_tpu.ops import (
    HashingTF,
    LowerCase,
    MaxClassifier,
    NGramsFeaturizer,
    TermFrequency,
    Tokenizer,
    Trimmer,
    log_tf,
)
from keystone_tpu.workflow import Dataset, Pipeline



@dataclasses.dataclass
class Config:
    data_path: Optional[str] = None
    num_features: int = 16384
    ngrams: int = 2
    lam: float = 1e-4
    num_iters: int = 40
    synthetic_n: int = 600
    model_path: Optional[str] = None
    # out-of-core: stream review texts from the JSON-lines file per
    # sweep (host StreamDataset); requires test_path
    test_path: Optional[str] = None
    stream: bool = False
    stream_batch_size: int = 1024


class AmazonReviewsPipeline:
    name = "AmazonReviewsPipeline"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_labels: Dataset) -> Pipeline:
        featurizer = (
            Pipeline.of(Trimmer())
            .and_then(LowerCase())
            .and_then(Tokenizer())
            .and_then(NGramsFeaturizer(tuple(range(1, config.ngrams + 1))))
            .and_then(TermFrequency(log_tf))
            # hashed features stay CSR at large dimensions: the logistic
            # solver fits them with gather/scatter gradients (the role
            # MLlib's SparseVector logreg played in the reference)
            .and_then(
                HashingTF(
                    config.num_features,
                    sparse_output=config.num_features >= 16384,
                )
            )
        )
        return featurizer.and_then(
            LogisticRegressionEstimator(
                num_classes=2, lam=config.lam, num_iters=config.num_iters
            ),
            train_x,
            train_labels,
        ).and_then(MaxClassifier())

    @staticmethod
    def run(config: Config) -> dict:
        # train/test come from ONE load+split, so the load stays eager
        # (the test half is always needed, even for saved-model runs)
        if config.stream and config.data_path:
            if not config.test_path:
                raise ValueError(
                    "--stream needs --test-path: a streamed JSON-lines "
                    "file cannot be split in place"
                )
            train = AmazonReviewsDataLoader.stream(
                config.data_path, batch_size=config.stream_batch_size
            )
            test = AmazonReviewsDataLoader.load(config.test_path)
        elif config.data_path and config.test_path:
            # explicit test file: honor it, no split
            train = AmazonReviewsDataLoader.load(config.data_path)
            test = AmazonReviewsDataLoader.load(config.test_path)
        elif config.data_path:
            data = AmazonReviewsDataLoader.load(config.data_path)
            train, test = data.split(0.8, seed=0)
        else:
            train = AmazonReviewsDataLoader.synthetic(config.synthetic_n, seed=1)
            test = AmazonReviewsDataLoader.synthetic(config.synthetic_n // 4, seed=2)
        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path,
            lambda: AmazonReviewsPipeline.build(config, train.data, train.labels),
            config=fit_relevant_config(config),
        )
        fit_time = time.time() - t0
        preds = fitted(test.data).get()
        m = BinaryClassifierEvaluator().evaluate(preds, test.labels)
        return {
            "pipeline": AmazonReviewsPipeline.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "accuracy": m.accuracy,
            "f1": m.f1,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=AmazonReviewsPipeline.name)
    p.add_argument("--data-path")
    p.add_argument("--test-path")
    p.add_argument("--num-features", type=int, default=16384)
    p.add_argument("--synthetic-n", type=int, default=600)
    p.add_argument("--model-path")
    p.add_argument(
        "--stream",
        "--out-of-core",
        action="store_true",
        dest="stream",
        help="stream review texts from the JSON-lines file per sweep "
        "(requires --test-path)",
    )
    p.add_argument("--stream-batch-size", type=int, default=1024)
    a = p.parse_args(argv)
    print(
        AmazonReviewsPipeline.run(
            Config(
                data_path=a.data_path,
                test_path=a.test_path,
                stream=a.stream,
                stream_batch_size=a.stream_batch_size,
                num_features=a.num_features,
                synthetic_n=a.synthetic_n,
                model_path=a.model_path,
            )
        )
    )


if __name__ == "__main__":
    main()
