"""AmazonReviewsPipeline (reference
pipelines/text/AmazonReviewsPipeline.scala): n-grams → term frequency →
feature hashing → logistic regression (binary sentiment)."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import BinaryClassifierEvaluator
from keystone_tpu.loaders.amazon import AmazonReviewsDataLoader
from keystone_tpu.models import LogisticRegressionEstimator
from keystone_tpu.ops import (
    HashingTF,
    LowerCase,
    MaxClassifier,
    NGramsFeaturizer,
    TermFrequency,
    Tokenizer,
    Trimmer,
    log_tf,
)
from keystone_tpu.workflow import Dataset, Pipeline



@dataclasses.dataclass
class Config:
    data_path: Optional[str] = None
    num_features: int = 16384
    ngrams: int = 2
    lam: float = 1e-4
    num_iters: int = 40
    synthetic_n: int = 600
    model_path: Optional[str] = None


class AmazonReviewsPipeline:
    name = "AmazonReviewsPipeline"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_labels: Dataset) -> Pipeline:
        featurizer = (
            Pipeline.of(Trimmer())
            .and_then(LowerCase())
            .and_then(Tokenizer())
            .and_then(NGramsFeaturizer(tuple(range(1, config.ngrams + 1))))
            .and_then(TermFrequency(log_tf))
            # hashed features stay CSR at large dimensions: the logistic
            # solver fits them with gather/scatter gradients (the role
            # MLlib's SparseVector logreg played in the reference)
            .and_then(
                HashingTF(
                    config.num_features,
                    sparse_output=config.num_features >= 16384,
                )
            )
        )
        return featurizer.and_then(
            LogisticRegressionEstimator(
                num_classes=2, lam=config.lam, num_iters=config.num_iters
            ),
            train_x,
            train_labels,
        ).and_then(MaxClassifier())

    @staticmethod
    def run(config: Config) -> dict:
        # train/test come from ONE load+split, so the load stays eager
        # (the test half is always needed, even for saved-model runs)
        if config.data_path:
            data = AmazonReviewsDataLoader.load(config.data_path)
            train, test = data.split(0.8, seed=0)
        else:
            train = AmazonReviewsDataLoader.synthetic(config.synthetic_n, seed=1)
            test = AmazonReviewsDataLoader.synthetic(config.synthetic_n // 4, seed=2)
        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path,
            lambda: AmazonReviewsPipeline.build(config, train.data, train.labels),
            config=fit_relevant_config(config),
        )
        fit_time = time.time() - t0
        preds = fitted(test.data).get()
        m = BinaryClassifierEvaluator().evaluate(preds, test.labels)
        return {
            "pipeline": AmazonReviewsPipeline.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "accuracy": m.accuracy,
            "f1": m.f1,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=AmazonReviewsPipeline.name)
    p.add_argument("--data-path")
    p.add_argument("--num-features", type=int, default=16384)
    p.add_argument("--synthetic-n", type=int, default=600)
    p.add_argument("--model-path")
    a = p.parse_args(argv)
    print(
        AmazonReviewsPipeline.run(
            Config(
                data_path=a.data_path,
                num_features=a.num_features,
                synthetic_n=a.synthetic_n,
                model_path=a.model_path,
            )
        )
    )


if __name__ == "__main__":
    main()
