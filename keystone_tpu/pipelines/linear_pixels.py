"""LinearPixels (reference pipelines/images/cifar/LinearPixels.scala):
the CIFAR baseline — raw pixels → exact least squares → MaxClassifier."""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.cifar import CifarLoader, NUM_CLASSES
from keystone_tpu.models import LinearMapEstimator
from keystone_tpu.ops import ClassLabelIndicators, ImageVectorizer, MaxClassifier
from keystone_tpu.workflow import Dataset, Pipeline


@dataclasses.dataclass
class Config:
    train_path: Optional[str] = None
    test_path: Optional[str] = None
    lam: float = 1e-3
    synthetic_n: int = 1024
    model_path: Optional[str] = None
    # out-of-core: re-read CIFAR records from disk per sweep; the exact
    # solver accumulates sufficient statistics batch-by-batch
    stream: bool = False
    stream_batch_size: int = 1024


class LinearPixels:
    name = "LinearPixels"
    Config = Config

    @staticmethod
    def build(config: Config, train_x: Dataset, train_labels: Dataset) -> Pipeline:
        labels_pm1 = ClassLabelIndicators(NUM_CLASSES)(train_labels)
        return (
            Pipeline.of(ImageVectorizer())
            .and_then(LinearMapEstimator(lam=config.lam), train_x, labels_pm1)
            .and_then(MaxClassifier())
        )

    @staticmethod
    def run(config: Config) -> dict:
        from keystone_tpu.loaders.stream import require_stream_test_path

        require_stream_test_path(config)
        if config.train_path:
            test = CifarLoader.load(config.test_path or config.train_path)
        else:
            test = CifarLoader.synthetic(config.synthetic_n // 4, seed=2)

        def build():
            # train loads ONLY when a fit is needed (saved-model runs skip it)
            from keystone_tpu.loaders.stream import resolve_train_source

            train = resolve_train_source(
                config,
                load=CifarLoader.load,
                stream=CifarLoader.stream,
                synthetic=lambda: CifarLoader.synthetic(
                    config.synthetic_n, seed=1
                ),
            )
            return LinearPixels.build(config, train.data, train.labels)

        from keystone_tpu.workflow.pipeline import (
            FittedPipeline,
            fit_relevant_config,
        )

        t0 = time.time()
        fitted, loaded = FittedPipeline.fit_or_load(
            config.model_path, build, config=fit_relevant_config(config)
        )
        fit_time = time.time() - t0
        preds = fitted(test.data).get()
        m = MulticlassClassifierEvaluator(NUM_CLASSES).evaluate(preds, test.labels)
        return {
            "pipeline": LinearPixels.name,
            "fit_seconds": fit_time,
            "model_loaded": loaded,
            "test_error": m.total_error,
            "accuracy": m.accuracy,
        }


def main(argv=None):
    p = argparse.ArgumentParser(description=LinearPixels.name)
    p.add_argument("--train-path")
    p.add_argument("--test-path")
    p.add_argument("--lam", type=float, default=1e-3)
    p.add_argument("--synthetic-n", type=int, default=1024)
    p.add_argument("--model-path")
    from keystone_tpu.loaders.stream import add_stream_args

    add_stream_args(p, default_batch_size=1024, noun="CIFAR records")
    a = p.parse_args(argv)
    print(LinearPixels.run(Config(
        a.train_path, a.test_path, a.lam, a.synthetic_n,
        model_path=a.model_path,
        stream=a.stream,
        stream_batch_size=a.stream_batch_size,
    )))


if __name__ == "__main__":
    main()
