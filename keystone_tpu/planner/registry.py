"""The physical-choice registry: every gate and knob, one precedence.

KeystoneML's optimizer (PAPER.md, ICDE 2017 §4) chooses *physical*
operator implementations per logical stage from sampled cost models.
Before this package, the TPU rebuild made those choices with scattered
environment gates — ``KEYSTONE_FUSED_FV``, ``KEYSTONE_GRAM_PALLAS``,
``KEYSTONE_MATMUL`` — each read at its own dispatch site with its own
default.  This module is the consolidation: one literal registry of
every gate (a named physical choice with enumerated candidates) and
every knob (a named numeric serving parameter with validated bounds),
plus the process-global installed :class:`~keystone_tpu.planner.plan.
PhysicalPlan` that dispatch sites consult.

Resolution precedence at EVERY dispatch site, documented once here:

    explicit argument  >  env override  >  installed plan  >  static default

Env vars are thereby demoted from the *mechanism* to a documented
*override*: with no plan installed and no env set, every site resolves
to its historical static default through the identical code path — the
no-plan behavior is byte-identical and pinned by regression tests.

``GATES``/``KNOBS``/``OPERATIONAL_ENV`` are **literal** dicts/sets so
``tools/lint.py``'s ``gate`` rule can parse them from the AST without
importing the package (the fault-site registry discipline): a new
``KEYSTONE_*`` env read controlling a physical choice must be
registered here or carry ``# lint: allow-gate``.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

#: Physical-choice gates.  ``kind`` decides how the env override is
#: decoded: ``switch`` gates read "0" as the fallback candidate and any
#: other set value as the preferred candidate (the historical
#: ``KEYSTONE_X=0`` escape-hatch grammar); ``mode`` gates read the env
#: value as a candidate name directly.  The first candidate is the
#: static default (what the site did before the planner existed);
#: ``tpu_only`` lists candidates that only a Pallas-capable backend can
#: run (the cost model never samples them elsewhere, and the analysis
#: ``plan`` pass flags a shipped plan that picked one for this backend).
GATES = {
    "fused_fv": {
        "env": "KEYSTONE_FUSED_FV",
        "kind": "switch",
        "candidates": ("pallas", "xla"),
        "tpu_only": ("pallas",),
        "doc": "PCA->FisherVector forward: fused Pallas megakernel vs "
               "per-stage XLA chain (workflow/optimizer.PallasFvFusionRule)",
    },
    "gram_pallas": {
        "env": "KEYSTONE_GRAM_PALLAS",
        "kind": "switch",
        "candidates": ("pallas", "xla"),
        "tpu_only": ("pallas",),
        "doc": "kernel gram blocks: fused Pallas tile kernel vs the "
               "bit-identical XLA chain (ops/gram_pallas.gram_block)",
    },
    "matmul": {
        "env": "KEYSTONE_MATMUL",
        "kind": "mode",
        "candidates": ("auto", "bf16", "f32", "bf16_apply"),
        "tpu_only": ("bf16", "bf16_apply"),
        "doc": "featurize/apply matmul precision policy "
               "(utils/precision.matmul_mode); solver math (sdot) is "
               "correctness-critical and NEVER under the plan",
    },
}

#: Serving knobs a plan may carry, with the validated bounds the
#: analysis ``plan`` pass and the PlanTuner enforce.  ``env`` names the
#: historical override where one exists (still honored, above the plan).
KNOBS = {
    "buckets": {
        "env": None,
        "kind": "int_tuple",
        "min": 1,
        "max": 65536,
        "doc": "padding-bucket sizes (serve/service.default_buckets)",
    },
    "max_wait_ms": {
        "env": None,
        "kind": "float",
        "min": 0.0,
        "max": 1000.0,
        "doc": "micro-batch flush wait (PipelineService)",
    },
    "dispatch_window": {
        "env": None,
        "kind": "int",
        "min": 1,
        "max": 64,
        "doc": "per-replica outstanding-flush window (fleet.set_window)",
    },
    "hedge_ms": {
        "env": None,
        "kind": "float",
        "min": 0.0,
        "max": 60000.0,
        "doc": "straggler hedge delay (PipelineService hedge_ms)",
    },
    "pool_budget_bytes": {
        "env": "KEYSTONE_POOL_BUDGET_BYTES",
        "kind": "int",
        "min": 1 << 20,
        "max": 1 << 40,
        "doc": "shared stage pool HBM budget "
               "(workflow/profiling.pool_budget_bytes)",
    },
}

#: ``KEYSTONE_*`` env vars that do NOT select a physical implementation
#: or plan-managed knob — operational/debug/test configuration the
#: ``gate`` lint rule must not flag.  Registering a new operational env
#: here (or a new physical gate in GATES) is the rule's escape path;
#: a one-off read can carry ``# lint: allow-gate`` instead.
OPERATIONAL_ENV = {
    "KEYSTONE_APPLY_CHUNK",
    "KEYSTONE_AUTO_SPILL",
    "KEYSTONE_BF16_APPLY_FORCE",  # test-only parity override, not a choice
    "KEYSTONE_BREAKER_RESET",
    "KEYSTONE_BREAKER_THRESHOLD",
    "KEYSTONE_CACHE_PROFILE_ALL",
    "KEYSTONE_COMPILE_CACHE",
    "KEYSTONE_FAULTS",
    "KEYSTONE_HANG_SECONDS",
    "KEYSTONE_HBM_BUDGET_BYTES",  # fit-time cache budget, not a serve knob
    "KEYSTONE_HEALTH_TIMEOUT",
    "KEYSTONE_HOST_WORKERS",
    "KEYSTONE_INIT_RETRIES",
    "KEYSTONE_IO_RETRIES",
    "KEYSTONE_METRICS",
    "KEYSTONE_OBS_DIR",
    "KEYSTONE_OBS_KEEP_SEGMENTS",
    "KEYSTONE_OBS_MAX_BYTES",
    "KEYSTONE_OC_PREFETCH",
    "KEYSTONE_OOC_FRACTION",
    "KEYSTONE_PLATFORM",
    "KEYSTONE_SOLVER_PRECISION",  # correctness-critical: never planned
    "KEYSTONE_SPILL_BATCH",
    "KEYSTONE_STAGE_DEADLINE",
    "KEYSTONE_STAGE_RETRIES",
    "KEYSTONE_STATE_DIR",
    "KEYSTONE_STREAM_TIMEOUT",
    "KEYSTONE_VALIDATE",
    "KEYSTONE_VERIFY_BLOCKS",
}


# ------------------------------------------------------------- installed plan

_LOCK = threading.Lock()
_PLAN = None  # the installed PhysicalPlan (None = no plan: legacy path)
_PLAN_SOURCE: Optional[str] = None
#: build-time forcing stack: the cost model samples a candidate by
#: forcing it ABOVE env and plan (it must measure the candidate it asked
#: for, not whatever the operator would have resolved)
_FORCED: list = []


def install_plan(plan, source: str = "install") -> None:
    """Install ``plan`` as THE process plan (every dispatch site's
    third precedence tier).  Idempotent per plan fingerprint; emits an
    ops-ledger event so a swapped/healed replica's plan provenance is
    auditable."""
    global _PLAN, _PLAN_SOURCE
    with _LOCK:
        _PLAN = plan
        _PLAN_SOURCE = source
    try:
        from keystone_tpu.obs import ledger

        ledger.event(
            "plan.install",
            source=source,
            version=None if plan is None else plan.fingerprint(),
            stages=0 if plan is None else len(plan.stages),
        )
    except Exception:
        pass


def clear_plan() -> None:
    """Remove the installed plan (tests; the byte-identical legacy
    path)."""
    global _PLAN, _PLAN_SOURCE
    with _LOCK:
        _PLAN = None
        _PLAN_SOURCE = None


def current_plan():
    return _PLAN


def plan_status() -> Optional[dict]:
    """Compact ``/statusz`` section: None when no plan is installed."""
    plan = _PLAN
    if plan is None:
        return None
    return {
        "fingerprint": plan.fingerprint(),
        "source": _PLAN_SOURCE,
        "backend": plan.backend,
        "stages": len(plan.stages),
        "choices": {s.gate: s.winner for s in plan.stages},
        "knobs": dict(plan.knobs),
    }


@contextmanager
def forced(gate: str, candidate: str):
    """Force ``gate`` to ``candidate`` for the block — the cost model's
    sampling lever, resolving ABOVE every other tier."""
    if gate not in GATES:
        raise KeyError(f"unknown gate {gate!r}; registered: {sorted(GATES)}")
    if candidate not in GATES[gate]["candidates"]:
        raise ValueError(
            f"{candidate!r} is not a candidate of gate {gate!r}: "
            f"{GATES[gate]['candidates']}"
        )
    entry = (gate, candidate)
    with _LOCK:
        _FORCED.append(entry)
    try:
        yield
    finally:
        with _LOCK:
            _FORCED.remove(entry)


def forced_gate(name: str) -> Optional[str]:
    """Innermost forced candidate for ``name``, or None.  Lock-free on
    the hot path (dispatch sites call this per resolution): ``tuple()``
    snapshots the list atomically under the GIL."""
    for gate, cand in reversed(tuple(_FORCED)):
        if gate == name:
            return cand
    return None


def planned_gate(name: str) -> Optional[str]:
    """The candidate the installed plan picked for ``name`` — the
    *forced > plan* slice of the precedence ladder (the dispatch sites
    keep their explicit-arg and env tiers in their own code so the
    no-plan path stays byte-identical).  None when nothing applies."""
    cand = forced_gate(name)
    if cand is not None:
        return cand
    plan = _PLAN
    if plan is None:
        return None
    cand = plan.choice_for(name)
    if cand is not None and cand not in GATES[name]["candidates"]:
        return None  # a corrupt/foreign plan never forces a bad dispatch
    return cand


def planned_knob(name: str):
    """The installed plan's value for knob ``name``, clamped to the
    registry bounds; None when no plan carries it."""
    plan = _PLAN
    if plan is None:
        return None
    if name not in KNOBS:
        raise KeyError(f"unknown knob {name!r}; registered: {sorted(KNOBS)}")
    value = plan.knobs.get(name)
    if value is None:
        return None
    ok, coerced, _why = validate_knob(name, value)
    return coerced if ok else None


def validate_knob(name: str, value):
    """``(ok, coerced, why)`` — the ONE bounds check the plan builder,
    the analysis ``plan`` pass, and the PlanTuner all use."""
    spec = KNOBS.get(name)
    if spec is None:
        return False, None, f"unknown knob {name!r}"
    lo, hi = spec["min"], spec["max"]
    kind = spec["kind"]
    try:
        if kind == "int_tuple":
            vals = tuple(int(v) for v in value)
            if not vals:
                return False, None, "empty bucket set"
            if any(v < lo or v > hi for v in vals):
                return False, None, f"bucket outside [{lo}, {hi}]: {vals}"
            return True, tuple(sorted(set(vals))), ""
        v = int(value) if kind == "int" else float(value)
    except (TypeError, ValueError):
        return False, None, f"{name}={value!r} is not {kind}"
    if v < lo or v > hi:
        return False, None, f"{name}={v} outside [{lo}, {hi}]"
    return True, v, ""


def supported_candidates(gate: str, backend: Optional[str] = None):
    """The candidates of ``gate`` the current (or named) backend can
    actually run — what the cost model samples and what the analysis
    pass accepts in a shipped plan."""
    spec = GATES[gate]
    tpu_only = set(spec.get("tpu_only", ()))
    if not tpu_only:
        return tuple(spec["candidates"])
    if backend is None:
        backend = current_backend()
    if backend in ("tpu", "axon"):
        return tuple(spec["candidates"])
    return tuple(c for c in spec["candidates"] if c not in tpu_only)


def current_backend() -> str:
    """The default JAX backend platform ('tpu' / 'cpu' / ...); 'cpu'
    when JAX is unavailable (plan inspection must work anywhere)."""
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def gate_env_names():
    """Every env var registered as a gate or knob override (the lint
    rule's allow set, alongside OPERATIONAL_ENV)."""
    names = {g["env"] for g in GATES.values() if g.get("env")}
    names |= {k["env"] for k in KNOBS.values() if k.get("env")}
    return names
