"""The sampling-based cost model: micro-profile candidates, fit curves,
pick winners.

This is the KeystoneML optimizer loop (PAPER.md §4) in miniature: at
``freeze()`` time, each stage with more than one physical candidate is
executed on a few **sampled batch sizes** (the ProfilingAutoCacheRule
sampling discipline — truncated inputs, wall-timed runs, best-of-reps),
a linear cost curve ``seconds ≈ a + b·n`` is fitted per candidate, and
the candidate cheapest at the serving batch size wins.  Winners plus
the derived serving knobs land in one :class:`~keystone_tpu.planner.
plan.PhysicalPlan`.

Determinism: sample indices come from ``np.random.default_rng(seed)``
and candidate enumeration order is the registry's — with an injected
``runner`` (tests) the whole plan is a pure function of its inputs.
The default runner wall-times real executions; each timed run passes
the ``plan.sample`` fault site (ctx ``gate=/candidate=/n=``), so a
fault-injected delay inflates exactly one candidate's samples — the
winner-flip test's lever, and the chaos story for the cost model
itself.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from keystone_tpu import faults
from keystone_tpu.planner import registry
from keystone_tpu.planner.plan import (
    CandidateCost,
    PhysicalPlan,
    StageChoice,
    stage_signature,
)

logger = logging.getLogger(__name__)

#: tie margin: a non-default candidate must beat the default by more
#: than this fraction to displace it (sampling noise must not flip a
#: pinned default on a coin toss)
TIE_MARGIN = 0.02


def fit_curve(samples: Sequence[Tuple[int, float]]) -> Tuple[float, float]:
    """Least-squares ``seconds ≈ a + b·n`` over ``[(n, seconds), ...]``;
    degenerate sample sets collapse to a flat curve through the mean."""
    if not samples:
        return (0.0, 0.0)
    ns = np.asarray([float(n) for n, _ in samples])
    ts = np.asarray([float(t) for _, t in samples])
    if len(samples) == 1 or float(np.ptp(ns)) == 0.0:
        return (float(ts.mean()), 0.0)
    b = float(np.cov(ns, ts, bias=True)[0, 1] / np.var(ns))
    a = float(ts.mean() - b * ns.mean())
    return (max(0.0, a), max(0.0, b))


def price(coeffs: Tuple[float, float], n: int) -> float:
    return float(coeffs[0] + coeffs[1] * float(n))


def _block(out) -> None:
    """Force async device work to finish inside the timed region."""
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:
        pass


def wall_runner(fn: Callable[[], object], *, gate: str, candidate: str,
                n: int, reps: int = 2) -> float:
    """Best-of-``reps`` wall seconds for one candidate run at batch
    ``n``.  The first (untimed) call absorbs trace/compile; each timed
    rep passes the ``plan.sample`` fault site so chaos plans can stall
    one candidate's measurements specifically."""
    _block(fn())
    best: Optional[float] = None
    for _ in range(max(1, int(reps))):
        t0 = time.perf_counter()
        faults.fault_point(
            "plan.sample", gate=gate, candidate=candidate, n=int(n)
        )
        _block(fn())
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return float(best)


def _sample_batch(arr: np.ndarray, n: int, rng) -> np.ndarray:
    """``n`` rows drawn (with replacement) from ``arr`` — deterministic
    under the plan seed, any requested size from any sample."""
    rows = max(1, int(arr.shape[0]))
    idx = rng.integers(0, rows, size=int(n))
    return np.asarray(arr)[idx]


def _pick_winner(
    gate: str, candidates: Dict[str, CandidateCost]
) -> Tuple[str, str]:
    """(winner, why) — cheapest at full batch, with the static default
    keeping ties (TIE_MARGIN)."""
    order = [c for c in registry.GATES[gate]["candidates"] if c in candidates]
    runnable = [c for c in order if candidates[c].supported]
    if not runnable:
        return order[0], "no runnable candidate; static default retained"
    if len(runnable) == 1:
        return runnable[0], "single supported candidate on this backend"
    default = runnable[0]
    best = min(runnable, key=lambda c: candidates[c].full_seconds)
    d_cost = candidates[default].full_seconds
    b_cost = candidates[best].full_seconds
    if best != default and d_cost > 0 and (d_cost - b_cost) / d_cost <= TIE_MARGIN:
        return default, (
            f"{best} within {TIE_MARGIN:.0%} of default; default retained"
        )
    if best == default:
        return default, (
            f"default cheapest at n={'full'} "
            f"({b_cost * 1e3:.3f}ms)"
        )
    return best, (
        f"beats {default} at full batch "
        f"({b_cost * 1e3:.3f}ms vs {d_cost * 1e3:.3f}ms)"
    )


def _sample_gate(
    gate: str,
    label: str,
    signature: str,
    cand_fns: Dict[str, Optional[Callable[[np.ndarray], object]]],
    input_arr: np.ndarray,
    batch_sizes: Sequence[int],
    full_batch: int,
    rng,
    runner: Callable[..., float],
) -> StageChoice:
    """Time every candidate of one gate at every sampled batch size and
    choose.  A candidate mapped to None is recorded unsupported."""
    costs: Dict[str, CandidateCost] = {}
    for cand, fn in cand_fns.items():
        cc = CandidateCost(name=cand)
        if fn is None:
            cc.supported = False
            cc.note = "not runnable on this backend"
            cc.full_seconds = float("inf")
            costs[cand] = cc
            continue
        try:
            for n in batch_sizes:
                x = _sample_batch(input_arr, n, rng)
                with registry.forced(gate, cand):
                    secs = runner(
                        lambda x=x, fn=fn: fn(x),
                        gate=gate,
                        candidate=cand,
                        n=n,
                    )
                cc.samples.append([int(n), float(secs)])
            cc.coeffs = fit_curve(cc.samples)
            cc.full_seconds = price(cc.coeffs, full_batch)
        except Exception as e:  # sampling is best-effort, like profiling
            logger.debug("plan sampling failed for %s/%s: %s", gate, cand, e)
            cc.supported = False
            cc.note = f"sampling failed: {type(e).__name__}"
            cc.full_seconds = float("inf")
        costs[cand] = cc
    winner, why = _pick_winner(gate, costs)
    # JSON has no Infinity: unsupported candidates price as 0 with the
    # supported=False flag carrying the meaning
    for cc in costs.values():
        if not np.isfinite(cc.full_seconds):
            cc.full_seconds = 0.0
    return StageChoice(
        gate=gate,
        signature=signature,
        label=label,
        winner=winner,
        why=why,
        candidates=[costs[c] for c in registry.GATES[gate]["candidates"]
                    if c in costs],
    )


def _matmul_candidates(backend: str) -> Tuple[str, ...]:
    """Precision modes worth sampling: off-TPU every mode resolves to
    the inert f32 policy, so there is exactly one physical candidate —
    sampling 'f32' against 'auto' there would let timer noise ship a
    pinned mode that changes numerics on a later TPU deploy."""
    if backend in ("tpu", "axon"):
        return ("auto", "f32", "bf16_apply")
    return ("auto",)


def build_plan(
    pipeline,
    example=None,
    batch_sizes: Sequence[int] = (8, 32, 128),
    full_batch: int = 32,
    max_batch: int = 32,
    seed: int = 0,
    runner: Optional[Callable[..., float]] = None,
    candidates: Optional[Dict[str, Sequence[str]]] = None,
    source: str = "freeze",
) -> PhysicalPlan:
    """Build a :class:`PhysicalPlan` for a fitted ``pipeline``.

    ``example`` — a batch (or one datum) of representative input; the
    sampled batches are drawn from its rows.  Without it, stage
    sampling is skipped and every gate keeps its static default (the
    plan still pins serving knobs and backend).  ``runner`` — injected
    timing function (tests); default :func:`wall_runner`.
    ``candidates`` — per-gate candidate override (bench A/B and the
    winner-flip tests); default :func:`registry.supported_candidates`.
    """
    from keystone_tpu.workflow import graph as G

    backend = registry.current_backend()
    rng = np.random.default_rng(int(seed))
    run = runner or wall_runner
    batch_sizes = tuple(sorted({int(b) for b in batch_sizes}))
    stages: list = []
    forward_coeffs: Optional[Tuple[float, float]] = None

    ex_arr = None
    if example is not None:
        ex_arr = np.asarray(example)
        if ex_arr.ndim == 0:
            ex_arr = ex_arr[None]
        if ex_arr.shape[0] == 1 or ex_arr.ndim == 1:
            ex_arr = ex_arr.reshape(1, *ex_arr.shape[1:] or (1,))

    def cands_for(gate: str) -> Tuple[str, ...]:
        if candidates and gate in candidates:
            return tuple(candidates[gate])
        return registry.supported_candidates(gate, backend=backend)

    graph = pipeline.graph
    executor = None
    if ex_arr is not None:
        try:
            from keystone_tpu.workflow.dataset import Dataset
            from keystone_tpu.workflow.executor import GraphExecutor

            bound, _ = graph.replace_source_with_node(
                pipeline.source,
                G.DatasetOperator(
                    Dataset(ex_arr, n=int(ex_arr.shape[0]), shard=False)
                ),
            )
            executor = (bound, GraphExecutor(bound))
        except Exception as e:
            logger.debug("plan input binding failed: %s", e)
            executor = None

    def _input_rows(node) -> Optional[np.ndarray]:
        """The sampled input rows feeding ``node`` (its single dep's
        output), as a host array."""
        if executor is None:
            return None
        bound, ex = executor
        deps = bound.dependencies.get(node, ())
        if len(deps) != 1:
            return None
        try:
            from keystone_tpu.workflow.executor import DatasetExpr

            expr = ex.execute(deps[0])
            if not isinstance(expr, DatasetExpr) or expr.dataset.is_host:
                return None
            return np.asarray(expr.dataset.array)
        except Exception as e:
            logger.debug("plan input execution failed at %s: %s", node, e)
            return None

    # ---------------------------------------------------- per-stage gates
    if executor is not None:
        bound = executor[0]
        for node in bound.topological_nodes():
            op = bound.operators.get(node)
            t = getattr(op, "transformer", None)
            if t is None:
                continue
            tname = type(t).__name__
            if tname in ("FisherVector", "FusedPcaFisherVector"):
                choice = _plan_fused_fv(
                    bound, node, t, _input_rows, cands_for("fused_fv"),
                    batch_sizes, full_batch, rng, run,
                )
                if choice is not None:
                    stages.append(choice)
            elif tname in (
                "KernelBlockLinearMapper",
                "OutOfCoreKernelBlockLinearMapper",
            ):
                arr = _input_rows(node)
                if arr is None:
                    continue
                fns = {
                    c: (lambda x, t=t: t.apply_batch(x))
                    for c in cands_for("gram_pallas")
                }
                stages.append(
                    _sample_gate(
                        "gram_pallas", op.label(), stage_signature(t), fns,
                        arr, batch_sizes, full_batch, rng, run,
                    )
                )

    # -------------------------------------------- whole-pipeline matmul
    mm_cands = (
        tuple(candidates["matmul"])
        if candidates and "matmul" in candidates
        else _matmul_candidates(backend)
    )
    if executor is not None:
        from keystone_tpu.utils import precision

        bound, ex0 = executor
        sink_dep = bound.sink_dependencies.get(pipeline.sink)

        def forward(x: np.ndarray):
            from keystone_tpu.workflow.dataset import Dataset
            from keystone_tpu.workflow.executor import GraphExecutor

            g2, _ = graph.replace_source_with_node(
                pipeline.source,
                G.DatasetOperator(Dataset(x, n=int(x.shape[0]), shard=False)),
            )
            ex2 = GraphExecutor(g2)
            return ex2.execute(g2.sink_dependencies[pipeline.sink])

        if sink_dep is not None:
            costs: Dict[str, CandidateCost] = {}
            try:
                for cand in mm_cands:
                    cc = CandidateCost(name=cand)
                    for n in batch_sizes:
                        x = _sample_batch(ex_arr, n, rng)
                        with precision.matmul(cand):
                            secs = run(
                                lambda x=x: forward(x),
                                gate="matmul",
                                candidate=cand,
                                n=n,
                            )
                        cc.samples.append([int(n), float(secs)])
                    cc.coeffs = fit_curve(cc.samples)
                    cc.full_seconds = price(cc.coeffs, full_batch)
                    costs[cand] = cc
            except Exception as e:
                logger.debug("plan forward sampling failed: %s", e)
                costs = {}
            if costs:
                winner, why = _pick_winner("matmul", costs)
                psig = ""
                try:
                    from keystone_tpu.utils.hashing import pipeline_fingerprint

                    psig = pipeline_fingerprint(pipeline)
                except Exception:
                    pass
                stages.append(
                    StageChoice(
                        gate="matmul",
                        signature=f"pipeline:{psig[:12]}" if psig else
                        "pipeline",
                        label="<forward>",
                        winner=winner,
                        why=why,
                        candidates=[
                            costs[c]
                            for c in registry.GATES["matmul"]["candidates"]
                            if c in costs
                        ],
                    )
                )
                forward_coeffs = costs[winner].coeffs

    knobs = select_knobs(forward_coeffs, max_batch=max_batch)
    psig = ""
    try:
        from keystone_tpu.utils.hashing import pipeline_fingerprint

        psig = pipeline_fingerprint(pipeline)
    except Exception:
        pass
    return PhysicalPlan(
        backend=backend,
        seed=int(seed),
        batch_sizes=batch_sizes,
        full_batch=int(full_batch),
        stages=stages,
        knobs=knobs,
        source=source,
        pipeline_signature=psig,
    )


def _plan_fused_fv(
    graph, node, fv, input_rows, cands, batch_sizes, full_batch, rng, run
) -> Optional[StageChoice]:
    """The fused-FV gate compares REAL alternatives: the per-stage
    PCA→FV chain ('xla') against the one fused forward node the
    optimizer rule would install ('pallas') — both fed the PCA's input,
    exactly the substitution ``PallasFvFusionRule`` makes."""
    tname = type(fv).__name__
    if tname == "FusedPcaFisherVector":
        # already fused (a re-plan over an optimized graph): nothing to
        # compare — record the standing choice
        return StageChoice(
            gate="fused_fv",
            signature=stage_signature(fv),
            label="FusedPcaFisherVector",
            winner="pallas",
            why="graph already carries the fused node",
        )
    deps = graph.dependencies.get(node, ())
    pca = None
    pca_node = None
    if len(deps) == 1:
        op = graph.operators.get(deps[0])
        t = getattr(op, "transformer", None)
        if type(t).__name__ == "PCATransformer":
            pca, pca_node = t, deps[0]
    if pca is None:
        return None  # the rule only fuses a PCA→FV pair
    arr = input_rows(pca_node)
    if arr is None:
        return None
    fns: Dict[str, Optional[Callable]] = {}
    for c in cands:
        if c == "xla":
            fns[c] = lambda x, pca=pca, fv=fv: fv.apply_batch(
                pca.apply_batch(x)
            )
        elif c == "pallas":
            try:
                from keystone_tpu.ops.fisher import FusedPcaFisherVector

                fused = FusedPcaFisherVector(
                    pca, fv.gmm, sift_normalize=False,
                    use_pallas=fv.use_pallas,
                )
                fns[c] = lambda x, fused=fused: fused.apply_batch(x)
            except Exception as e:
                logger.debug("fused candidate unavailable: %s", e)
                fns[c] = None
        else:
            fns[c] = None
    return _sample_gate(
        "fused_fv",
        f"{type(pca).__name__}->{tname}",
        stage_signature(fv),
        fns,
        arr,
        batch_sizes,
        full_batch,
        rng,
        run,
    )


def select_knobs(
    forward_coeffs: Optional[Tuple[float, float]], max_batch: int = 32
) -> dict:
    """Serving knobs from the fitted forward curve.

    - **buckets**: the power-of-two ladder (the static default — the
      PlanTuner refines the set live from observed flush occupancy);
    - **max_wait_ms**: wait at most ~2 fixed-overheads ``a`` for riders
      (waiting longer than the amortizable launch cost buys nothing),
      clamped to [1, 20] ms around the static 5 ms default;
    - **dispatch_window**: the pool's static default of 2 (the curve
      carries no queueing information; the tuner owns this knob live);
    - **hedge_ms**: fire a hedge past ~5× the fitted full-batch time —
      late enough that healthy flushes never hedge;
    - **pool_budget_bytes**: the resolved device budget, PINNED so a
      deploy host with different headroom serves what was planned.
    """
    from keystone_tpu.serve.service import default_buckets
    from keystone_tpu.workflow.profiling import pool_budget_bytes

    knobs = {
        "buckets": [int(b) for b in default_buckets(int(max_batch))],
        "dispatch_window": 2,
        "pool_budget_bytes": int(pool_budget_bytes()),
    }
    if forward_coeffs is None:
        knobs["max_wait_ms"] = 5.0
        return knobs
    a, b = forward_coeffs
    knobs["max_wait_ms"] = round(min(20.0, max(1.0, 2000.0 * a)), 3)
    knobs["hedge_ms"] = round(
        min(60000.0, max(50.0, 5000.0 * (a + b * max_batch))), 3
    )
    return knobs
