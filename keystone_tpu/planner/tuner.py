"""PlanTuner: refine the plan from live telemetry, retune safe knobs.

The cost model freezes its decisions from a few sampled batches; serving
reality is the better teacher.  The tuner closes the loop the ISSUE (and
ROADMAP item 2) names: a control thread in the Autoscaler's mold
(injectable clock + signal source, pure-ish ``tick`` the tests drive
directly) that reads the serving telemetry the obs layer already
collects — windowed batch occupancy (``serve.batch_rows``), per-window
latency, SLO burn, shared-pool hit rate — and live-retunes the **safe**
serving knobs:

- **padding buckets** through the autoscaler-path machinery (the same
  Signals/policy/tick discipline, applied via
  :meth:`PipelineService.retune_buckets` — an atomic bucket-ladder swap
  that only changes padding, never results, so no future is ever lost);
- **dispatch window** via the existing ``pool.set_window`` lever, using
  the very :meth:`AutoscalePolicy.window_for` rule the autoscaler runs —
  and therefore only when the service has no live autoscaler (two
  controllers on one knob is an oscillator).

Every retune is a ``plan.retune`` ops span + ledger event, and every
retune **bakes** under the PR-19 rollback discipline: the pre-retune
value is captured, the SLO burn rate is watched for ``bake_s`` seconds,
and a retune that burns the error-budget window (``burn > bake_max_burn``
with at least ``min_samples`` windowed requests) is reverted — outcome
``reverted`` — exactly like a bad model swap.  A retune that survives
its bake is committed into the installed plan's ``knobs`` (the refined
cost model ships with the next publish).

Gate *winners* are never retuned live: flipping a physical
implementation under traffic changes compiled programs mid-flight; that
remains a freeze-time decision.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from keystone_tpu.obs import ledger, metrics
from keystone_tpu.planner import registry
from keystone_tpu.serve.autoscale import AutoscalePolicy, Signals

logger = logging.getLogger(__name__)


class PlanTuner:
    """Live knob retuner for one :class:`PipelineService`.

    ``clock`` / ``signal_source`` / ``rows_source`` / ``burn_source``
    are injectable (tests drive :meth:`tick` with a fake clock and
    synthetic telemetry); ``apply=False`` records decisions without
    touching the service.
    """

    def __init__(
        self,
        service,
        plan=None,
        policy: Optional[AutoscalePolicy] = None,
        interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        signal_source: Optional[Callable[[], Signals]] = None,
        rows_source: Optional[Callable[[], Optional[dict]]] = None,
        burn_source: Optional[Callable[[], Optional[dict]]] = None,
        apply: bool = True,
        bake_s: float = 5.0,
        bake_max_burn: float = 2.0,
        min_samples: int = 10,
        cooldown_s: float = 10.0,
        min_bucket: int = 1,
        occupancy_frac: float = 0.5,
    ):
        self.service = service
        self._plan = plan
        self.policy = policy or AutoscalePolicy()
        self.interval_s = max(0.05, float(interval_s))
        self._clock = clock
        self._signals = signal_source or self._sample
        self._rows = rows_source or self._sample_rows
        self._burn = burn_source or self._sample_burn
        self._apply = bool(apply)
        self.bake_s = float(bake_s)
        self.bake_max_burn = float(bake_max_burn)
        self.min_samples = int(min_samples)
        self.cooldown_s = float(cooldown_s)
        self.min_bucket = max(1, int(min_bucket))
        #: flushes averaging below ``occupancy_frac × min(buckets)`` rows
        #: trigger a smaller bucket (padding waste)
        self.occupancy_frac = float(occupancy_frac)
        self._pending: Optional[dict] = None  # the retune currently baking
        self._last_retune = -1e9
        self._rows_base: Optional[dict] = None
        self.retunes = 0
        self.reverts = 0
        self.commits = 0
        self.last_action: Optional[dict] = None
        self.observations: dict = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop,
            daemon=True,
            name=f"{getattr(service, 'name', 'serve')}-plantuner",
        )

    # ------------------------------------------------------------- telemetry
    @property
    def plan(self):
        """The plan being refined: explicit > the process-installed one."""
        return self._plan if self._plan is not None else registry.current_plan()

    def _sample(self) -> Signals:
        svc = self.service
        applier = getattr(svc, "_mt_applier", None)
        pool_rate = None
        if applier is not None:
            try:
                pool_rate = applier.pool().hit_rate()
            except Exception:
                pool_rate = None
        return Signals(
            workers=svc._pool.size,
            queue_depth=svc.queue_depth,
            queue_bound=svc.queue_bound,
            occupancy=svc.occupancy(),
            burn_rate=svc.slo_burn_rate(),
            pool_hit_rate=pool_rate,
        )

    def _sample_rows(self) -> Optional[dict]:
        """Cumulative ``serve.batch_rows`` histogram (count/sum) — the
        tick diffs consecutive reads into observed flush occupancy."""
        try:
            return metrics.REGISTRY.histogram_value("serve.batch_rows")
        except Exception:
            return None

    def _sample_burn(self) -> Optional[dict]:
        try:
            return self.service.slo_burn()
        except Exception:
            return None

    def _avg_rows(self) -> Optional[float]:
        """Mean rows per flush since the previous tick (None until two
        reads with traffic in between)."""
        cur = self._rows()
        prev, self._rows_base = self._rows_base, cur
        if not cur or not prev:
            return None
        dn = float(cur.get("count", 0)) - float(prev.get("count", 0))
        ds = float(cur.get("sum", 0.0)) - float(prev.get("sum", 0.0))
        if dn <= 0:
            return None
        return ds / dn

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "PlanTuner":
        self._thread.start()
        return self

    def stop(self, timeout: float = 60.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def _loop(self) -> None:
        ledger.restore_context(getattr(self.service, "_obs_ctx", None))
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # a retune must never kill the controller
                logger.exception("plan tuner tick failed")

    # ---------------------------------------------------------------- tick
    def tick(self) -> Optional[str]:
        """One control decision; returns ``"retune"``, ``"commit"``,
        ``"revert"``, or None."""
        svc = self.service
        if getattr(svc, "_closing", False):
            return None
        now = self._clock()
        s = self._signals()
        avg_rows = self._avg_rows()
        if avg_rows is not None:
            self.observations["avg_batch_rows"] = round(avg_rows, 2)
        if self._pending is not None:
            return self._judge_bake(now)
        if now - self._last_retune < self.cooldown_s:
            return None
        # dispatch window — the autoscaler's own rule; only when no live
        # autoscaler holds this knob
        if getattr(svc, "autoscaler", None) is None:
            new = self.policy.window_for(s, svc._pool.window)
            if new is not None:
                return self._begin(
                    "dispatch_window",
                    old=svc._pool.window,
                    new=int(new),
                    reason=f"queue_frac={s.queue_frac:.2f} "
                    f"occupancy={s.occupancy:.2f}",
                    now=now,
                    setter=svc.set_dispatch_window,
                )
        # padding buckets — thread fleets only: process workers bake
        # their bucket ladder into spawned programs at startup
        if getattr(svc, "workers", 0) == 0 and avg_rows is not None:
            buckets = tuple(svc.buckets)
            smallest = min(buckets)
            if (
                smallest > self.min_bucket
                and avg_rows < self.occupancy_frac * smallest
            ):
                proposal = tuple(
                    sorted({max(self.min_bucket, smallest // 2)} | set(buckets))
                )
                ok, coerced, _ = registry.validate_knob("buckets", proposal)
                if ok:
                    return self._begin(
                        "buckets",
                        old=buckets,
                        new=coerced,
                        reason=f"avg flush {avg_rows:.1f} rows < "
                        f"{self.occupancy_frac:.0%} of bucket {smallest}",
                        now=now,
                        setter=svc.retune_buckets,
                    )
        return None

    # ------------------------------------------------------------- retuning
    def _begin(self, knob, old, new, reason, now, setter) -> str:
        if self._apply:
            setter(new)
        self._pending = {
            "knob": knob,
            "old": old,
            "new": new,
            "reason": reason,
            "started": now,
            "setter": setter,
        }
        self._last_retune = now
        self.retunes += 1
        self._emit("retune", knob, old, new, reason)
        return "retune"

    def _judge_bake(self, now: float) -> Optional[str]:
        p = self._pending
        burn = self._burn() or {}
        rate = burn.get("burn_rate")
        n = int(burn.get("window_requests") or 0)
        if (
            rate is not None
            and n >= self.min_samples
            and float(rate) > self.bake_max_burn
        ):
            if self._apply:
                p["setter"](p["old"])
            self._pending = None
            self.reverts += 1
            self._emit(
                "reverted",
                p["knob"],
                p["new"],
                p["old"],
                f"burn {float(rate):.2f} > {self.bake_max_burn} "
                f"over {n} requests",
            )
            return "revert"
        if now - p["started"] >= self.bake_s:
            self._pending = None
            self.commits += 1
            plan = self.plan
            if plan is not None:
                value = (
                    list(p["new"])
                    if isinstance(p["new"], (tuple, list))
                    else p["new"]
                )
                plan.knobs[p["knob"]] = value  # the refined model
            self._emit("kept", p["knob"], p["old"], p["new"], p["reason"])
            return "commit"
        return None

    def _emit(self, outcome, knob, old, new, reason) -> None:
        self.last_action = {
            "outcome": outcome,
            "knob": knob,
            "old": old,
            "new": new,
            "reason": reason,
        }
        metrics.inc("plan.retunes", outcome=outcome)
        ledger.event(
            "plan.retune",
            outcome=outcome,
            knob=knob,
            reason=reason,
        )
        rec = getattr(self.service, "recorder", None)
        if rec is not None:
            rec.ops(
                "plan.retune",
                outcome=outcome,
                knob=knob,
                reason=f"{old} -> {new}: {reason}",
            )
        logger.info(
            "plan.retune %s %s: %s -> %s (%s)", outcome, knob, old, new, reason
        )

    # --------------------------------------------------------------- status
    def status(self) -> dict:
        plan = self.plan
        p = self._pending
        return {
            "interval_seconds": self.interval_s,
            "apply": self._apply,
            "retunes": self.retunes,
            "commits": self.commits,
            "reverts": self.reverts,
            "baking": None
            if p is None
            else {
                "knob": p["knob"],
                "old": p["old"],
                "new": p["new"],
                "reason": p["reason"],
            },
            "last_action": self.last_action,
            "observations": dict(self.observations),
            "plan": None if plan is None else plan.fingerprint(),
        }
