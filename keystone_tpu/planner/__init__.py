"""Cost-based physical planning (KeystoneML ICDE 2017 §4, rebuilt).

``registry``  — every physical gate and serving knob, one precedence:
                explicit arg > env override > installed plan > default.
``plan``      — the plain-data :class:`PhysicalPlan` that ships with
                the model (manifest, registry blob, pickled applier).
``cost``      — the freeze-time sampling cost model (:func:`build_plan`).
``tuner``     — the live :class:`PlanTuner` (telemetry-driven knob
                retunes under the rollback-bake discipline).
"""

from keystone_tpu.planner.cost import build_plan
from keystone_tpu.planner.plan import (
    CandidateCost,
    PhysicalPlan,
    StageChoice,
    stage_signature,
)
from keystone_tpu.planner.registry import (
    GATES,
    KNOBS,
    clear_plan,
    current_plan,
    install_plan,
    plan_status,
    planned_gate,
    planned_knob,
)
from keystone_tpu.planner.tuner import PlanTuner

__all__ = [
    "GATES",
    "KNOBS",
    "CandidateCost",
    "PhysicalPlan",
    "PlanTuner",
    "StageChoice",
    "build_plan",
    "clear_plan",
    "current_plan",
    "install_plan",
    "plan_status",
    "planned_gate",
    "planned_knob",
    "stage_signature",
]
