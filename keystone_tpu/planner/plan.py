"""The ``PhysicalPlan`` — plain data, shipped with the model.

The plan is the durable output of the cost model: per-stage physical
candidates with their sampled costs and fitted curves, the chosen
winner (with *why*), and the serving knobs.  It is deliberately plain
data — dicts, lists, strings, numbers — so it JSON-round-trips into
the freeze-artifact manifest (blob-before-pointer discipline via
``ModelRegistry.publish``), survives applier pickling (replica clones,
process-worker spawns), and renders for humans (``keystone plan``).

Stage identity is a **stage signature**: a short content hash of the
transformer's type and params (:func:`stage_signature`).  The analysis
``plan`` pass recomputes signatures over a live graph and flags a plan
whose signatures no longer match (``stale-plan``) — the schema's
defense against a plan shipped with the wrong model.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import List, Optional, Tuple

PLAN_FORMAT = 1


def stage_signature(transformer) -> str:
    """Stable short identity of one pipeline stage: type name plus the
    process-stable repr of its params (``utils.hashing`` discipline —
    weights are deliberately excluded so a re-fit with identical
    architecture keeps its plan)."""
    from keystone_tpu.utils.hashing import _stable_repr

    try:
        params = transformer.params()
    except Exception:
        params = None
    h = hashlib.blake2b(digest_size=6)
    h.update(type(transformer).__name__.encode())
    h.update(_stable_repr(params).encode())
    return f"{type(transformer).__name__}:{h.hexdigest()}"


@dataclasses.dataclass
class CandidateCost:
    """One sampled physical candidate: ``seconds ~= a + b*n`` fitted
    over ``samples`` = [[batch_rows, seconds], ...]."""

    name: str
    samples: List[List[float]] = dataclasses.field(default_factory=list)
    coeffs: Tuple[float, float] = (0.0, 0.0)  # (a, b)
    full_seconds: float = 0.0  # priced at the plan's full_batch
    supported: bool = True
    note: str = ""

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "samples": [[int(n), float(s)] for n, s in self.samples],
            "coeffs": [float(self.coeffs[0]), float(self.coeffs[1])],
            "full_seconds": float(self.full_seconds),
            "supported": bool(self.supported),
            "note": self.note,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CandidateCost":
        return cls(
            name=str(d["name"]),
            samples=[[int(n), float(s)] for n, s in d.get("samples", [])],
            coeffs=tuple(d.get("coeffs", (0.0, 0.0))),
            full_seconds=float(d.get("full_seconds", 0.0)),
            supported=bool(d.get("supported", True)),
            note=str(d.get("note", "")),
        )


@dataclasses.dataclass
class StageChoice:
    """One gate's decision at one stage: the candidates sampled, the
    winner, and the reason."""

    gate: str
    signature: str
    label: str
    winner: str
    why: str
    candidates: List[CandidateCost] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "gate": self.gate,
            "signature": self.signature,
            "label": self.label,
            "winner": self.winner,
            "why": self.why,
            "candidates": [c.to_dict() for c in self.candidates],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "StageChoice":
        return cls(
            gate=str(d["gate"]),
            signature=str(d["signature"]),
            label=str(d.get("label", "")),
            winner=str(d["winner"]),
            why=str(d.get("why", "")),
            candidates=[
                CandidateCost.from_dict(c) for c in d.get("candidates", [])
            ],
        )


@dataclasses.dataclass
class PhysicalPlan:
    """The whole physical plan: stage choices + serving knobs.

    ``knobs`` holds only registry-validated names
    (:data:`keystone_tpu.planner.registry.KNOBS`); values outside their
    bounds are rejected at resolve time, never silently applied."""

    backend: str
    seed: int = 0
    batch_sizes: Tuple[int, ...] = ()
    full_batch: int = 32
    stages: List[StageChoice] = dataclasses.field(default_factory=list)
    knobs: dict = dataclasses.field(default_factory=dict)
    source: str = "freeze"
    pipeline_signature: str = ""
    format: int = PLAN_FORMAT

    # ------------------------------------------------------------- identity
    def to_dict(self) -> dict:
        return {
            "format": int(self.format),
            "backend": self.backend,
            "seed": int(self.seed),
            "batch_sizes": [int(b) for b in self.batch_sizes],
            "full_batch": int(self.full_batch),
            "stages": [s.to_dict() for s in self.stages],
            "knobs": dict(self.knobs),
            "source": self.source,
            "pipeline_signature": self.pipeline_signature,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PhysicalPlan":
        if int(d.get("format", -1)) != PLAN_FORMAT:
            raise ValueError(
                f"unknown plan format {d.get('format')!r} "
                f"(this build reads {PLAN_FORMAT})"
            )
        return cls(
            backend=str(d.get("backend", "cpu")),
            seed=int(d.get("seed", 0)),
            batch_sizes=tuple(int(b) for b in d.get("batch_sizes", ())),
            full_batch=int(d.get("full_batch", 32)),
            stages=[StageChoice.from_dict(s) for s in d.get("stages", [])],
            knobs=dict(d.get("knobs", {})),
            source=str(d.get("source", "freeze")),
            pipeline_signature=str(d.get("pipeline_signature", "")),
            format=PLAN_FORMAT,
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "PhysicalPlan":
        return cls.from_dict(json.loads(text))

    def fingerprint(self) -> str:
        """Short content hash of the canonical JSON — the identity the
        roundtrip tests pin across manifest → registry → worker spawn."""
        return hashlib.blake2b(
            self.to_json().encode(), digest_size=8
        ).hexdigest()

    # ------------------------------------------------------------- queries
    def choice_for(self, gate: str) -> Optional[str]:
        """The winner for ``gate`` (first matching stage; the builder
        emits one consistent choice per gate)."""
        for s in self.stages:
            if s.gate == gate:
                return s.winner
        return None

    def stage_signatures(self) -> List[str]:
        return [s.signature for s in self.stages]

    # ------------------------------------------------------------ validation
    def validate(self, backend: Optional[str] = None) -> List[Tuple[str, str]]:
        """Graph-independent checks: ``(code, message)`` per problem.
        The analysis ``plan`` pass adds the graph-signature check on
        top (it has the graph; this object does not)."""
        from keystone_tpu.planner import registry

        problems: List[Tuple[str, str]] = []
        for s in self.stages:
            spec = registry.GATES.get(s.gate)
            if spec is None:
                problems.append(
                    ("bad-plan-candidate", f"unknown gate {s.gate!r}")
                )
                continue
            if s.winner not in spec["candidates"]:
                problems.append(
                    (
                        "bad-plan-candidate",
                        f"gate {s.gate!r} winner {s.winner!r} is not a "
                        f"candidate: {spec['candidates']}",
                    )
                )
                continue
            ok = registry.supported_candidates(s.gate, backend=backend)
            if s.winner not in ok:
                problems.append(
                    (
                        "bad-plan-candidate",
                        f"gate {s.gate!r} winner {s.winner!r} is not "
                        f"runnable on backend "
                        f"{backend or registry.current_backend()!r}",
                    )
                )
        for name, value in self.knobs.items():
            ok, _v, why = registry.validate_knob(name, value)
            if not ok:
                problems.append(("bad-plan-candidate", f"knob {why}"))
        return problems

    # -------------------------------------------------------------- explain
    def explain(self) -> str:
        """Human rendering for ``keystone plan --explain``: per stage,
        every candidate with sampled costs and the winner's why."""
        lines = [
            f"PhysicalPlan {self.fingerprint()}  "
            f"(backend={self.backend}, seed={self.seed}, "
            f"source={self.source})",
            f"  sampled batch sizes: {list(self.batch_sizes)}  "
            f"(priced at full_batch={self.full_batch})",
        ]
        for s in self.stages:
            lines.append(f"  stage {s.label or s.signature} [{s.signature}]")
            lines.append(f"    gate {s.gate}: winner={s.winner} ({s.why})")
            for c in s.candidates:
                samples = ", ".join(
                    f"n={int(n)}: {sec * 1e3:.3f}ms" for n, sec in c.samples
                )
                mark = "*" if c.name == s.winner else " "
                sup = "" if c.supported else "  [unsupported]"
                lines.append(
                    f"    {mark} {c.name}: full={c.full_seconds * 1e3:.3f}ms"
                    f"  fit a={c.coeffs[0] * 1e3:.4f}ms "
                    f"b={c.coeffs[1] * 1e6:.4f}us/row{sup}"
                    + (f"  [{samples}]" if samples else "")
                    + (f"  ({c.note})" if c.note else "")
                )
        if self.knobs:
            lines.append("  serving knobs:")
            for k in sorted(self.knobs):
                lines.append(f"    {k} = {self.knobs[k]}")
        return "\n".join(lines)
