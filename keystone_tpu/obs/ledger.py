"""Run ledger: a per-run JSONL span/event stream (the Dapper-style
trace the reference got for free from Spark's event log).

One **run** = one JSONL file ``run_<run_id>.jsonl`` under the ledger
directory.  Every line is one event::

    {"ts": <unix seconds>, "run_id": "...", "seq": <monotonic int>,
     "kind": "run_start"|"span_start"|"span_end"|"event"|"metrics",
     "name": "...", "span": <id>, "parent": <id|null>, "attrs": {...}}

``span_end`` lines additionally carry ``"seconds"`` (wall duration) and
the final attrs (spans may accumulate attrs while open — the executor
records attempt counts this way).  The schema is flat on purpose:
``tools/obs_report.py`` and ad-hoc ``jq`` both read it without a parser
library.

Activation — default OFF and inert:

- ``KEYSTONE_OBS_DIR=<dir>`` activates a process-wide ledger lazily (the
  first ``span``/``event`` call creates it, ``atexit`` closes it) — the
  zero-code route, mirroring ``KEYSTONE_FAULTS``.
- ``start_run(dir)`` / ``stop_run()`` scope a ledger explicitly
  (bench.py and tests use this; an explicit run wins over the env one).

With neither, every hook in the codebase reduces to one ``None`` check
(plus one ``os.environ`` lookup) — the disabled-mode zero-event
guarantee tests pin.

Spans also emit ``jax.profiler.TraceAnnotation`` so ledger stages line
up by name with device traces captured via ``utils/tracing.py``, and
sample the device HBM watermark (``memory_stats()``) plus host max-RSS
at boundaries into the metrics registry (gauge ``hbm.bytes_in_use`` /
``host.max_rss_bytes``).

Solver telemetry rides :func:`solver_epoch` — host loops call it
directly; jitted solver scans reach it through ``jax.debug.callback``
(see ``models/lbfgs.py`` et al., gated by a static ``obs`` flag so the
compiled program is byte-identical when observability is off).
"""

from __future__ import annotations

import atexit
import contextlib
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from keystone_tpu.obs import metrics

ENV_DIR = "KEYSTONE_OBS_DIR"
#: size cap (bytes) per ledger segment before rotation; unset = no cap.
#: A long-lived ``serve --watch`` process with KEYSTONE_OBS_DIR set
#: appends forever — without a cap it eventually fills the disk.
ENV_MAX_BYTES = "KEYSTONE_OBS_MAX_BYTES"
#: rotated segments kept per run (oldest pruned); default 8
ENV_KEEP_SEGMENTS = "KEYSTONE_OBS_KEEP_SEGMENTS"

DEFAULT_KEEP_SEGMENTS = 8

#: Registered span/event attribute-key vocabulary.  ``tools/lint.py``'s
#: ``attr`` rule parses this set from the AST (the fault-site rule's
#: discipline — no package import) and requires every literal keyword
#: at a ``ledger.span(...)``/``ledger.event(...)``/flight-recorder
#: emit site to be a snake_case member: a typo'd key otherwise vanishes
#: silently into the JSONL/ring stream and every downstream reader
#: (obs_report, trace_report, jq recipes) quietly reads nothing.  Add
#: a key here when introducing a genuinely new attribute; a one-off
#: escape is a trailing ``# lint: allow-attr``.
ATTR_VOCABULARY = {
    "action",
    "apply_seconds",
    "attempt",
    "attempts",
    "batch",
    "bucket",
    "budget_bytes",
    "budget_seconds",
    "cache_hits",
    "canary_fraction",
    "checkpoint_save_seconds",
    "chunk_seconds",
    "degraded",
    "depth",
    "epoch",
    "epoch_seconds",
    "error",
    "failed_attempt_seconds",
    "from_state",
    "from_replica",
    "from_version",
    "grad_norm",
    "host",
    "instances",
    "it",
    "key",
    "knob",
    "late",
    "leader",
    "n",
    "no_memoize_demotions",
    "node",
    "node_id",
    "objective",
    "occupancy",
    "outcome",
    "path",
    "pause_seconds",
    "pid",
    "pinned_bytes",
    "poisons",
    "predicted_seconds",
    "prime_seconds",
    "queue_depth",
    "queue_wait_seconds",
    "reason",
    "replica",
    "replicas",
    "request_id",
    "request_ids",
    "restarts",
    "retries",
    "refused",
    "rows",
    "rule",
    "seconds",
    "shared_bytes",
    "shared_nodes",
    "shared_stages",
    "sick",
    "site",
    "solver",
    "source",
    "stages",
    "stats",
    "substitute",
    "tag",
    "tenant",
    "tenants",
    "to_state",
    "to_replica",
    "to_version",
    "verdict",
    "version",
    "waited_seconds",
    "wire",
    "worker",
    "worker_spans",
    "workers",
}

#: per-process run discriminator: time.time() alone has 1-second
#: resolution, and two runs started within the same second would
#: silently append into the same JSONL file
_RUN_COUNTER = itertools.count()


def _env_int(name: str) -> Optional[int]:
    """Non-negative int from the environment, or None (unset, empty,
    or non-numeric — warned-free: the ledger must never fail to open
    over a malformed knob)."""
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        v = int(raw)
    except ValueError:
        return None
    return v if v >= 0 else None


def _json_safe(v):
    """Best-effort JSON coercion: numpy scalars/arrays and exotic
    objects must never kill the instrumented path."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_json_safe(x) for x in v]
    item = getattr(v, "item", None)  # numpy scalar / 0-d array
    if callable(item):
        try:
            return _json_safe(item())
        except Exception:
            pass
    tolist = getattr(v, "tolist", None)
    if callable(tolist):
        try:
            return _json_safe(tolist())
        except Exception:
            pass
    return str(v)


def _sample_memory() -> Dict[str, float]:
    """Device HBM in-use bytes (when the backend exposes memory_stats)
    plus host peak RSS.  Best-effort: CPU test meshes have no HBM stats
    and must not error."""
    out: Dict[str, float] = {}
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        used = stats.get("bytes_in_use")
        if used is not None:
            out["hbm_bytes_in_use"] = float(used)
            metrics.gauge_max("hbm.bytes_in_use", float(used))
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                metrics.gauge_max("hbm.peak_bytes_in_use", float(peak))
    except Exception:
        pass
    try:
        import resource

        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        out["host_max_rss_bytes"] = float(rss_kb) * 1024.0
        metrics.gauge_max("host.max_rss_bytes", float(rss_kb) * 1024.0)
    except Exception:
        pass
    return out


class _Span:
    """An open span: ``set(**attrs)`` merges attrs reported at close."""

    __slots__ = ("span_id", "name", "attrs", "t0")

    def __init__(self, span_id: int, name: str, attrs: Dict[str, Any]):
        self.span_id = span_id
        self.name = name
        self.attrs = attrs
        self.t0 = time.perf_counter()

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)


class RunLedger:
    """Append-only JSONL event stream for one run.

    **Rotation** — a long-lived process (``serve --watch`` under
    ``KEYSTONE_OBS_DIR``) appends to one run forever, so the active file
    carries a size cap: past ``max_bytes`` it is renamed to a numbered
    segment (``run_<id>.jsonl.000001``, monotonically increasing) and a
    fresh active file continues the run; only the newest
    ``keep_segments`` segments are kept, oldest pruned.  ``self.path``
    always names the ACTIVE file — readers of a live run see the newest
    tail, and each rotation bumps the ``obs.ledger_rotations`` counter.
    Defaults come from ``KEYSTONE_OBS_MAX_BYTES`` (unset = unbounded,
    the historical behavior) and ``KEYSTONE_OBS_KEEP_SEGMENTS``."""

    def __init__(
        self,
        directory: str,
        run_id: Optional[str] = None,
        max_bytes: Optional[int] = None,
        keep_segments: Optional[int] = None,
    ):
        os.makedirs(directory, exist_ok=True)
        if run_id is None:
            run_id = (
                f"{int(time.time()):x}-{os.getpid()}-{next(_RUN_COUNTER)}"
            )
        self.run_id = run_id
        self.directory = directory
        self.path = os.path.join(directory, f"run_{run_id}.jsonl")
        if max_bytes is None:
            max_bytes = _env_int(ENV_MAX_BYTES)
        self.max_bytes = max_bytes if max_bytes and max_bytes > 0 else None
        if keep_segments is None:
            keep_segments = _env_int(ENV_KEEP_SEGMENTS) or DEFAULT_KEEP_SEGMENTS
        self.keep_segments = max(1, int(keep_segments))
        # resume rotation state from disk: reopening an EXISTING run id
        # (a restarted serve --watch process) must count the bytes
        # already in the active file and continue segment numbering
        # past the highest kept suffix — starting both at zero would
        # let the active file grow to existing+max_bytes and the first
        # rotation os.replace() over (destroy) a retained segment
        try:
            self._bytes = os.path.getsize(self.path)
        except OSError:
            self._bytes = 0
        self._segment = 0
        prefix = f"run_{run_id}.jsonl."
        try:
            for name in os.listdir(directory):
                if name.startswith(prefix) and name[len(prefix):].isdigit():
                    self._segment = max(self._segment, int(name[len(prefix):]))
        except OSError:
            pass
        self._lock = threading.RLock()
        self._seq = 0
        self._f = open(self.path, "a", encoding="utf-8")
        self._tls = threading.local()  # per-thread open-span stack
        self._closed = False
        self._emit("run_start", "run", attrs={"pid": os.getpid()})

    # ------------------------------------------------------------ emit
    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _emit(
        self,
        kind: str,
        name: str,
        span: Optional[int] = None,
        parent: Optional[int] = None,
        attrs: Optional[Dict[str, Any]] = None,
        **extra,
    ) -> None:
        rec = {
            "ts": time.time(),
            "run_id": self.run_id,
            "kind": kind,
            "name": name,
        }
        if span is not None:
            rec["span"] = span
        if parent is not None:
            rec["parent"] = parent
        if attrs:
            rec["attrs"] = _json_safe(attrs)
        rec.update(extra)
        with self._lock:
            if self._closed:
                return
            self._seq += 1
            rec["seq"] = self._seq
            line = json.dumps(rec) + "\n"
            self._f.write(line)
            self._f.flush()
            self._bytes += len(line)
            if self.max_bytes is not None and self._bytes >= self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Must hold self._lock.  Seal the active file as the next
        numbered segment, reopen a fresh active file, prune segments
        past ``keep_segments`` (oldest first)."""
        self._f.close()
        self._segment += 1
        try:
            os.replace(self.path, f"{self.path}.{self._segment:06d}")
        except OSError:
            # the active file vanished under us (operator cleanup): a
            # rotation failure must not kill the instrumented path
            pass
        self._f = open(self.path, "a", encoding="utf-8")
        self._bytes = 0
        prefix = os.path.basename(self.path) + "."
        segments = []
        try:
            for name in os.listdir(self.directory):
                if name.startswith(prefix) and name[len(prefix):].isdigit():
                    segments.append((int(name[len(prefix):]), name))
        except OSError:
            segments = []
        for _, name in sorted(segments)[: -self.keep_segments]:
            try:
                os.remove(os.path.join(self.directory, name))
            except OSError:
                pass
        metrics.inc("obs.ledger_rotations")

    def event(self, name: str, **attrs) -> None:
        st = self._stack()
        self._emit(
            "event",
            name,
            parent=st[-1].span_id if st else None,
            attrs=attrs,
        )

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Timed nested region.  Emits span_start/span_end, annotates the
        jax profiler timeline by the same name, and samples memory
        watermarks at both boundaries."""
        with self._lock:
            self._seq += 1
            span_id = self._seq
        st = self._stack()
        parent = st[-1].span_id if st else None
        sp = _Span(span_id, name, dict(attrs))
        self._emit("span_start", name, span=span_id, parent=parent, attrs=attrs)
        _sample_memory()
        st.append(sp)
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
        except Exception:
            ann = contextlib.nullcontext()
        try:
            with ann:
                yield sp
        finally:
            st.pop()
            mem = _sample_memory()
            end_attrs = dict(sp.attrs)
            end_attrs.update(mem)
            self._emit(
                "span_end",
                name,
                span=span_id,
                parent=parent,
                attrs=end_attrs,
                seconds=time.perf_counter() - sp.t0,
            )

    def metrics_snapshot(self) -> None:
        """Embed the current registry snapshot as one ``metrics`` line
        (the report's source for I/O totals and watermarks)."""
        self._emit("metrics", "metrics.snapshot", attrs=metrics.snapshot())

    def close(self, snapshot: bool = True) -> None:
        if self._closed:
            return
        if snapshot:
            self.metrics_snapshot()
        self._emit("run_end", "run")
        with self._lock:
            self._closed = True
            self._f.close()


# ----------------------------------------------------------- activation

_LOCK = threading.Lock()
_ACTIVE: Optional[RunLedger] = None  # start_run / attach
_ENV_LEDGER: Optional[RunLedger] = None  # lazily created from KEYSTONE_OBS_DIR


def active() -> Optional[RunLedger]:
    """The current ledger, or None (the inert default).  An explicit
    ``start_run``/``attach`` ledger wins; otherwise ``KEYSTONE_OBS_DIR``
    lazily creates one process-wide run."""
    if _ACTIVE is not None:
        return _ACTIVE
    directory = os.environ.get(ENV_DIR)
    if not directory:
        return None
    global _ENV_LEDGER
    with _LOCK:
        if _ENV_LEDGER is None or (
            _ENV_LEDGER._closed or _ENV_LEDGER.directory != directory
        ):
            _ENV_LEDGER = RunLedger(directory)
            atexit.register(_ENV_LEDGER.close)
    return _ENV_LEDGER


def start_run(directory: str, run_id: Optional[str] = None) -> RunLedger:
    """Explicitly open (and activate) a run ledger; pair with
    :func:`stop_run`."""
    global _ACTIVE
    led = RunLedger(directory, run_id=run_id)
    with _LOCK:
        _ACTIVE = led
    return led


def attach(ledger: Optional[RunLedger]) -> None:
    """Install an existing ledger as the active one (None detaches)."""
    global _ACTIVE
    with _LOCK:
        _ACTIVE = ledger


def stop_run(snapshot: bool = True) -> None:
    """Close and detach the explicitly-activated ledger."""
    global _ACTIVE
    with _LOCK:
        led, _ACTIVE = _ACTIVE, None
    if led is not None:
        led.close(snapshot=snapshot)


# ------------------------------------------------------------- frontends


def event(name: str, **attrs) -> None:
    """Record one event on the active ledger; no-op when inert."""
    led = active()
    if led is not None:
        led.event(name, **attrs)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Timed span on the active ledger; yields the span handle (or None
    when inert) so callers can ``sp.set(...)`` extra attrs."""
    led = active()
    if led is None:
        yield None
        return
    with led.span(name, **attrs) as sp:
        yield sp


def capture_context():
    """Snapshot the calling thread's open-span stack (opaque token).
    The span stack is thread-local, so work handed to a worker thread —
    ``utils/guard.run_with_deadline`` watchdogs are the in-repo case —
    would otherwise emit spans/events with no parent.  Capture on the
    calling thread, :func:`restore_context` inside the worker, and the
    worker's spans nest where the caller's would have."""
    led = active()
    if led is None:
        return None
    return (led, list(led._stack()))


def restore_context(token) -> None:
    """Install a :func:`capture_context` snapshot on the CURRENT thread
    (a copy — the originating thread's stack is never shared or
    mutated).  No-op for a None token."""
    if token is None:
        return
    led, stack = token
    led._tls.stack = list(stack)


def device_wait(x, account: str = "device.busy_seconds", force: bool = False):
    """Block until ``x`` (any pytree of device values) is ready and
    charge the wait to the device-busy account — ONLY when a ledger is
    active.  Inert otherwise: no sync, no timing, the dispatch stream is
    untouched — so programs and async pipelining are byte-for-byte the
    pre-obs ones when observability is off.  Returns ``x``.

    ``force=True`` syncs (and meters) unconditionally — for call sites
    where the wait is REQUIRED regardless of observability (checkpoint
    gathers, dispatch-queue flow control) and the metering rides along.

    The account is a host-side measure: seconds the host spent BLOCKED
    on device results at natural drain points (solver finishes, epoch
    boundaries).  Together with ``blockstore.stage_wait_seconds`` (time
    blocked on host→device staging) it decomposes a fit's wall clock
    into device-busy vs transfer vs host overhead —
    ``tools/obs_report.py`` folds both into the ``dataflow`` summary the
    bench artifact embeds."""
    if not force and active() is None:
        return x
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(x)
    metrics.observe(account, time.perf_counter() - t0)
    return x


def solver_obs() -> bool:
    """Should solvers trace per-epoch telemetry?  Resolved at trace time
    and threaded as a STATIC jit argument, so the compiled program is
    exactly the pre-obs one when this is False."""
    return active() is not None


def solver_epoch(solver: str, **series) -> None:
    """One solver convergence point (epoch/objective/grad-norm/...).
    Host loops call this directly; jitted scans reach it via
    :func:`solver_callback`."""
    led = active()
    if led is not None:
        led.event("solver.epoch", solver=solver, **series)


def fold_stage_spans(ledger_path: str) -> Dict[str, dict]:
    """Aggregate a ledger's ``executor.stage`` span_end lines into
    ``{key: {seconds, count, retries, failed_attempt_seconds}}``.

    The ONE reader of this part of the schema — ``tools/obs_report.py``
    and ``workflow/viz.ledger_overlay`` both fold through here, so a
    schema change cannot silently drift them apart.  Keys are
    ``"{node_id}:{label}"`` when the span recorded a node id (matching
    the ``utils/tracing.stage_timings`` convention — distinct nodes
    sharing a label stay distinct), else the bare label."""
    out: Dict[str, dict] = {}
    with open(ledger_path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError:
                continue  # a torn final line must not hide the run
            if e.get("kind") != "span_end" or e.get("name") != "executor.stage":
                continue
            attrs = e.get("attrs") or {}
            label = str(attrs.get("node", "?"))
            nid = attrs.get("node_id")
            key = f"{nid}:{label}" if nid is not None else label
            st = out.setdefault(
                key,
                {
                    "label": label,
                    "seconds": 0.0,
                    "count": 0,
                    "retries": 0,
                    "failed_attempt_seconds": 0.0,
                },
            )
            st["seconds"] += float(e.get("seconds") or 0.0)
            st["count"] += 1
            st["retries"] += int(attrs.get("retries") or 0)
            st["failed_attempt_seconds"] += float(
                attrs.get("failed_attempt_seconds") or 0.0
            )
    return out


def solver_callback(solver: str, *names):
    """A ``jax.debug.callback``-shaped emitter: positional traced values
    are matched to ``names``.  Values arrive as numpy arrays; scalar
    coercion happens in the JSON layer."""

    def cb(*vals):
        led = active()
        if led is None:
            return
        led.event(
            "solver.epoch",
            solver=solver,
            **{n: v for n, v in zip(names, vals)},
        )

    return cb
