"""Unified structured observability: metrics registry + run ledger.

The reference leaned on Spark's event-log UI and a sampling profiler
(SURVEY.md §5); the TPU rebuild replaces both with two process-wide
primitives every subsystem reports through:

- :mod:`keystone_tpu.obs.metrics` — thread-safe counters, gauges, and
  histograms (``REGISTRY``), exported as JSON or Prometheus text.
  Always on (a bump is one lock + dict update); ``KEYSTONE_METRICS=0``
  disables recording entirely.
- :mod:`keystone_tpu.obs.ledger` — a per-run JSONL span/event stream
  (Dapper-style), activated by ``KEYSTONE_OBS_DIR`` or
  ``ledger.start_run``; default OFF and inert.  Spans also annotate the
  jax profiler timeline and sample HBM/RSS watermarks.  Long-lived runs
  rotate past ``KEYSTONE_OBS_MAX_BYTES`` into keep-N numbered segments.
- :mod:`keystone_tpu.obs.recorder` — the serving path's flight
  recorder: a bounded in-memory ring of recent request traces with
  tail-based retention (shed/error/slow traces pinned), ON by default
  in ``serve()`` and independent of the ledger.  Read it live via
  ``GET /tracez`` / ``GET /requestz/<id>`` (``serve/http.py``) or
  render a dump with ``python tools/trace_report.py``.

Render a ledger with ``python tools/obs_report.py <run.jsonl>``.
"""

from keystone_tpu.obs import ledger, metrics  # noqa: F401
from keystone_tpu.obs.ledger import (  # noqa: F401
    RunLedger,
    event,
    span,
    start_run,
    stop_run,
)
from keystone_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    MetricsRegistry,
    WindowedHistogram,
)
from keystone_tpu.obs.recorder import (  # noqa: F401
    FlightRecorder,
    new_request_id,
)
