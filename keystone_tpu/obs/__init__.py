"""Unified structured observability: metrics registry + run ledger.

The reference leaned on Spark's event-log UI and a sampling profiler
(SURVEY.md §5); the TPU rebuild replaces both with two process-wide
primitives every subsystem reports through:

- :mod:`keystone_tpu.obs.metrics` — thread-safe counters, gauges, and
  histograms (``REGISTRY``), exported as JSON or Prometheus text.
  Always on (a bump is one lock + dict update); ``KEYSTONE_METRICS=0``
  disables recording entirely.
- :mod:`keystone_tpu.obs.ledger` — a per-run JSONL span/event stream
  (Dapper-style), activated by ``KEYSTONE_OBS_DIR`` or
  ``ledger.start_run``; default OFF and inert.  Spans also annotate the
  jax profiler timeline and sample HBM/RSS watermarks.

Render a ledger with ``python tools/obs_report.py <run.jsonl>``.
"""

from keystone_tpu.obs import ledger, metrics  # noqa: F401
from keystone_tpu.obs.ledger import (  # noqa: F401
    RunLedger,
    event,
    span,
    start_run,
    stop_run,
)
from keystone_tpu.obs.metrics import REGISTRY, MetricsRegistry  # noqa: F401
