"""Flight recorder: an always-on, bounded, in-memory trace store for
the serving path.

The run ledger (``obs/ledger.py``) is a per-run JSONL stream — perfect
for offline fits, wrong for production serving: it is default-OFF (a
shed request leaves zero causal trace unless an operator pre-set
``KEYSTONE_OBS_DIR``), unbounded (a long-lived server would stream to
disk forever), and file-shaped (answering "why was request X slow?"
means grepping JSONL).  The flight recorder is the serving-side
complement, modeled on aircraft FDRs and the tracez/statusz "z-pages"
tradition: a bounded ring of the most recent request traces, ON by
default in :func:`keystone_tpu.serve.serve`, independent of (and
additive to) the ledger, readable live over HTTP (``GET /tracez``,
``GET /requestz/<id>`` — ``serve/http.py``).

Model:

- one **trace** per request id — an ordered list of events
  (``{"t": <seconds since trace start>, "name": ..., "attrs": {...}}``)
  from ingress to a terminal outcome (``completed`` / ``shed`` /
  ``rejected`` / ``degraded`` / ``error`` / ``cancelled``);
- one **batch record** per flush, carrying its rider request ids as
  span links (the batch is shared by its riders — recording it once and
  joining on read keeps per-request cost flat in batch size);
- **ops spans** for non-request control-plane moments (blue/green
  swaps, watcher actions), so a swap is visible BETWEEN the request
  traces it interleaves with.

Retention is **tail-based**: every finished trace enters the ``recent``
ring (FIFO, bounded), and *interesting* traces — terminal outcome in
``shed``/``rejected``/``error``/``degraded``, or latency at or above
the slow threshold — are ALSO pinned in a separate bounded ring, so the
traces an operator actually debugs survive long after the happy-path
flood evicted their contemporaries.  The slow threshold is either the
explicit ``slow_ms`` or a rolling p99 of recent completed latencies
(recomputed every few dozen finishes, so the sort is amortized).

Overhead budget: every hook is one lock acquisition plus O(1) dict/list
work — no JSON, no I/O, no syscalls on the hot path (JSON-safety is
applied on READ).  Per-trace event count is capped (``max_events``);
live traces that never finish are bounded by eviction into ``recent``
with outcome ``abandoned``.  ``tools/serve_bench.py`` legs with the
recorder on vs off pin the p99/QPS delta under 5% (the bench artifact
records it).

This module is stdlib-only at import (the ``obs`` package contract).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from keystone_tpu.obs.ledger import _json_safe

#: terminal outcomes that pin a trace into the long-retention ring
#: ("poison": a request isolated by batch bisection — exactly the trace
#: an operator wants long after the happy-path flood evicted its peers)
PINNED_OUTCOMES = frozenset({"shed", "rejected", "error", "degraded", "poison"})

#: recompute the rolling-p99 slow threshold every this many finishes
#: (amortizes the sort; a per-finish sort would blow the overhead budget)
_SLOW_REFRESH = 32

#: minimum latency samples before the auto slow threshold activates
_SLOW_MIN_SAMPLES = 20

#: import-time process prefix: random nonce + pid tail.  The pid is
#: captured ONCE — os.getpid() is a syscall (tens of µs under hardened
#: kernels) and must not be paid per request; a fork would stale the
#: tail, but the random nonce alone already separates processes.
_PROC = f"{uuid.uuid4().hex[:6]}{os.getpid() & 0xFFFF:04x}"
_REQ_COUNTER = itertools.count(1)


def new_request_id() -> str:
    """A process-unique request id: 10-hex process prefix (random nonce
    + pid tail, both captured at import) + monotonic counter.  One
    counter bump and one f-string per id — no uuid, no syscall."""
    return f"{_PROC}-{next(_REQ_COUNTER):06x}"


class FlightRecorder:
    """Bounded in-memory store of recent request traces, batch records,
    and ops spans.  Thread-safe; every write is one lock + O(1) work.

    ``capacity``/``pinned_capacity``/``batch_capacity``/``ops_capacity``
    bound the recent, pinned, batch, and ops rings; ``slow_ms`` fixes
    the slow-trace threshold (default: rolling p99 of completed
    latencies); ``max_events`` caps events per trace (overflow counted,
    not stored)."""

    def __init__(
        self,
        capacity: int = 256,
        pinned_capacity: int = 128,
        batch_capacity: int = 512,
        ops_capacity: int = 128,
        slow_ms: Optional[float] = None,
        max_events: int = 64,
    ):
        self.capacity = max(1, int(capacity))
        self.pinned_capacity = max(1, int(pinned_capacity))
        self.batch_capacity = max(1, int(batch_capacity))
        self.max_events = max(4, int(max_events))
        self._slow_s = None if not slow_ms else float(slow_ms) / 1000.0
        self._auto_slow_s: Optional[float] = None
        self._lock = threading.Lock()
        self._live: "OrderedDict[str, dict]" = OrderedDict()
        self._recent: "OrderedDict[str, dict]" = OrderedDict()
        self._pinned: "OrderedDict[str, dict]" = OrderedDict()
        self._batches: "OrderedDict[str, dict]" = OrderedDict()
        self._ops: deque = deque(maxlen=max(1, int(ops_capacity)))
        self._latencies: deque = deque(maxlen=512)
        self._finishes = 0
        self._dropped_events = 0

    # ----------------------------------------------------------- record
    @staticmethod
    def _new_trace(request_id: str) -> dict:
        """The one trace-dict constructor: _trace_locked and the
        finish-an-unknown-id path must mint the SAME shape, or readers
        (_summary/_full) crash on the one that drifted."""
        return {
            "request_id": request_id,
            "ts": time.time(),
            "t0": time.perf_counter(),
            "events": [],
            "batches": [],
            "outcome": None,
            "seconds": None,
            "slow": False,
        }

    def _trace_locked(self, request_id: str) -> dict:
        tr = self._live.get(request_id)
        if tr is None:
            tr = self._live[request_id] = self._new_trace(request_id)
            # a live trace that never finishes (caller vanished between
            # annotate and submit) must not accumulate forever
            while len(self._live) > 4 * self.capacity:
                _, stale = self._live.popitem(last=False)
                self._finalize_locked(stale, "abandoned")
        return tr

    def annotate(self, request_id: Optional[str], name: str, **attrs) -> None:
        """Append one event to ``request_id``'s trace (created lazily on
        first touch).  ``request_id=None`` is the inert no-op — callers
        pass their possibly-absent id straight through."""
        if request_id is None:
            return
        with self._lock:
            tr = self._live.get(request_id)
            if tr is None:
                if request_id in self._pinned or request_id in self._recent:
                    return  # already finalized: a late event is dropped
                tr = self._trace_locked(request_id)
            if len(tr["events"]) >= self.max_events:
                self._dropped_events += 1
                return
            tr["events"].append(
                {
                    "t": time.perf_counter() - tr["t0"],
                    "name": name,
                    "attrs": attrs,
                }
            )
            b = attrs.get("batch")
            if b is not None and b not in tr["batches"]:
                tr["batches"].append(b)

    def finish(
        self,
        request_id: Optional[str],
        outcome: str,
        only_live: bool = False,
        **attrs,
    ) -> None:
        """Terminal event + finalize: the trace moves from the live set
        into the recent ring, and additionally into the pinned ring when
        the outcome is interesting or the trace is slow.  Idempotent for
        already-finalized ids; ``only_live=True`` additionally refuses
        to CREATE a trace (the generic failure path uses it so it can't
        resurrect an evicted id as a one-event stub)."""
        if request_id is None:
            return
        with self._lock:
            tr = self._live.pop(request_id, None)
            if tr is None:
                if only_live or request_id in self._pinned or (
                    request_id in self._recent
                ):
                    return
                tr = self._new_trace(request_id)
            tr["events"].append(
                {
                    "t": time.perf_counter() - tr["t0"],
                    "name": f"serve.{outcome}",
                    "attrs": attrs,
                }
            )
            b = attrs.get("batch")
            if b is not None and b not in tr["batches"]:
                tr["batches"].append(b)
            self._finalize_locked(tr, outcome)

    def _finalize_locked(self, tr: dict, outcome: str) -> None:
        tr["outcome"] = outcome
        tr["seconds"] = time.perf_counter() - tr["t0"]
        threshold = self._slow_s or self._auto_slow_s
        tr["slow"] = threshold is not None and tr["seconds"] >= threshold
        rid = tr["request_id"]
        self._recent[rid] = tr
        self._recent.move_to_end(rid)
        while len(self._recent) > self.capacity:
            self._recent.popitem(last=False)
        if outcome in PINNED_OUTCOMES or tr["slow"]:
            self._pinned[rid] = tr
            self._pinned.move_to_end(rid)
            while len(self._pinned) > self.pinned_capacity:
                self._pinned.popitem(last=False)
        if outcome in ("completed", "degraded"):
            self._latencies.append(tr["seconds"])
        self._finishes += 1
        if (
            self._slow_s is None
            and self._finishes % _SLOW_REFRESH == 0
            and len(self._latencies) >= _SLOW_MIN_SAMPLES
        ):
            lat = sorted(self._latencies)
            self._auto_slow_s = lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    def batch(self, batch_id: str, riders: List[str], **attrs) -> None:
        """Record one flush's batch span, linking its rider request ids
        (the multi-parent join: riders reference the batch, the batch
        lists its riders)."""
        with self._lock:
            self._batches[batch_id] = {
                "batch": batch_id,
                "ts": time.time(),
                "request_ids": list(riders),
                **attrs,
            }
            while len(self._batches) > self.batch_capacity:
                self._batches.popitem(last=False)

    def batch_update(self, batch_id: str, **attrs) -> None:
        """Merge post-apply facts (seconds, bucket, degraded) into an
        existing batch record; no-op for an evicted id."""
        with self._lock:
            rec = self._batches.get(batch_id)
            if rec is not None:
                rec.update(attrs)

    def ops(self, name: str, **attrs) -> None:
        """One control-plane span (swap, watcher action): bounded ring,
        surfaced by ``/tracez`` alongside request traces."""
        with self._lock:
            self._ops.append({"ts": time.time(), "name": name, **attrs})

    # ------------------------------------------------------------- read
    @staticmethod
    def _summary(tr: dict) -> dict:
        last = tr["events"][-1]["name"] if tr["events"] else None
        return _json_safe(
            {
                "request_id": tr["request_id"],
                "ts": tr["ts"],
                "outcome": tr["outcome"],
                "seconds": tr["seconds"],
                "slow": tr["slow"],
                "n_events": len(tr["events"]),
                "last": last,
                "batches": list(tr["batches"]),
            }
        )

    def _matches(self, tr: dict, flt: Optional[str]) -> bool:
        if not flt:
            return True
        if flt == "slow":
            return bool(tr["slow"])
        return tr["outcome"] == flt

    def _full(self, tr: dict) -> dict:
        out = {k: v for k, v in tr.items() if k != "t0"}
        return _json_safe(out)

    def tracez(
        self, filter: Optional[str] = None, limit: int = 50, full: bool = False
    ) -> List[dict]:
        """Recent traces, newest first: pinned + recent + live (open
        traces report ``outcome: null``).  ``filter``: ``"slow"`` or a
        terminal outcome (``"shed"``/``"error"``/...)."""
        with self._lock:
            seen = set()
            rows = []
            for store in (self._live, self._recent, self._pinned):
                for rid, tr in store.items():
                    if rid in seen:
                        continue
                    seen.add(rid)
                    rows.append(tr)
        rows.sort(key=lambda t: t["ts"], reverse=True)
        render = self._full if full else self._summary
        out = []
        for tr in rows:  # filter+limit BEFORE the JSON-safe render:
            if not self._matches(tr, filter):  # rendering ~1400 traces
                continue  # to keep 50 would tax every dashboard poll
            out.append(render(tr))
            if len(out) >= max(1, int(limit)):
                break
        return out

    def request(self, request_id: str) -> Optional[dict]:
        """One request's full causal chain: its trace joined with every
        linked batch record.  None for an unknown (or evicted) id."""
        with self._lock:
            tr = (
                self._live.get(request_id)
                or self._pinned.get(request_id)
                or self._recent.get(request_id)
            )
            if tr is None:
                return None
            batches = [
                dict(self._batches[b])
                for b in tr["batches"]
                if b in self._batches
            ]
            out = {k: v for k, v in tr.items() if k != "t0"}
            out["open"] = request_id in self._live
        out["batch_records"] = batches
        return _json_safe(out)

    def ops_spans(self, limit: int = 50) -> List[dict]:
        with self._lock:
            rows = list(self._ops)
        return _json_safe(rows[-max(1, int(limit)):][::-1])

    def dump(self) -> dict:
        """Everything, JSON-safe — the ``/tracez?full=1`` payload and
        ``tools/trace_report.py``'s recorder-mode input."""
        with self._lock:
            seen = set()
            traces = []
            for store in (self._pinned, self._recent, self._live):
                for rid, tr in store.items():
                    if rid not in seen:
                        seen.add(rid)
                        traces.append(tr)
            batches = [dict(b) for b in self._batches.values()]
            ops = list(self._ops)
        traces.sort(key=lambda t: t["ts"])
        stats = self.stats()  # outside the lock: stats() takes it too
        return _json_safe(
            {
                "traces": [
                    {k: v for k, v in tr.items() if k != "t0"} for tr in traces
                ],
                "batches": batches,
                "ops": ops,
                "stats": stats,
            }
        )

    def stats(self) -> dict:
        with self._lock:
            threshold = self._slow_s or self._auto_slow_s
            return {
                "live": len(self._live),
                "recent": len(self._recent),
                "pinned": len(self._pinned),
                "batches": len(self._batches),
                "ops": len(self._ops),
                "finished": self._finishes,
                "dropped_events": self._dropped_events,
                "slow_threshold_ms": (
                    None if threshold is None else round(1000.0 * threshold, 3)
                ),
            }
