"""Process-wide metrics registry: counters, gauges, histograms.

The reference surfaced operational numbers through Spark's metrics sinks
and event-log UI; the TPU rebuild has no cluster manager underneath, so
the registry itself is the sink every subsystem reports to: blockstore
bytes and retries, durable-layer corruption/fallback counts, executor
retry time, solver telemetry, fault-injection outcomes, HBM watermarks.
One process == one registry (module-level :data:`REGISTRY`), mirroring
``keystone_tpu.faults``' process-global counters.

Design constraints (the reasons this module is stdlib-only and lockful):

- **hot-path cheap**: a counter bump is one lock + one dict update —
  the same order of cost as the ``fault_point`` hook already paid on
  every instrumented path.  ``KEYSTONE_METRICS=0`` short-circuits every
  recording call to a single env lookup (the disabled-mode guarantee
  tests pin).
- **no jax / no numpy at import**: ``keystone_tpu.faults`` imports this
  module, and faults must stay importable before any backend exists.
- **label-aware**: metrics key on ``(name, sorted(labels))`` so
  per-site/per-rule breakdowns (``faults.injected{site=...}``) live next
  to their totals without string mangling at record time.

Exports ride two formats: :meth:`MetricsRegistry.snapshot` (plain dict,
embedded in run-ledger JSONL and bench artifacts) and
:meth:`MetricsRegistry.to_prometheus_text` (the text exposition format,
for scraping or ad-hoc diffing).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

ENV_DISABLE = "KEYSTONE_METRICS"

#: histogram bucket upper bounds (seconds-oriented; byte-scale values
#: simply land in +Inf, where count/sum/min/max still describe them)
DEFAULT_BUCKETS = (
    0.001,
    0.005,
    0.01,
    0.05,
    0.1,
    0.5,
    1.0,
    5.0,
    10.0,
    60.0,
)

#: millisecond-resolution bounds for serve-path latencies.  The default
#: bounds alias everything under 1 ms into one bucket and everything
#: between 1 and 5 ms into another — useless for a micro-batching
#: service whose whole latency budget is tens of milliseconds.  Register
#: these per name via :meth:`MetricsRegistry.register_buckets` (the
#: serve subsystem does for ``serve.latency_seconds`` /
#: ``serve.batch_seconds``), and windowed percentile estimates inherit
#: the resolution.
LATENCY_MS_BUCKETS = (
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)

#: sub-millisecond bounds for the ingress hot path: frame parse and
#: batch admission each cost tens of microseconds when the zero-copy
#: path holds, so even :data:`LATENCY_MS_BUCKETS` (floor 0.5 ms) would
#: flatten every sample into its first bucket.  ``serve/ingress.py``
#: registers these for ``ingress.parse_seconds`` /
#: ``ingress.admit_seconds``.
INGRESS_TIME_BUCKETS = (
    0.00001,
    0.000025,
    0.00005,
    0.0001,
    0.00025,
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.05,
    0.25,
    1.0,
)


def enabled() -> bool:
    """Recording on?  ``KEYSTONE_METRICS=0`` disables every write path
    (reads — snapshot/export — always work)."""
    return os.environ.get(ENV_DISABLE, "1") != "0"


class MetricKindError(TypeError):
    """One metric name registered as two different instrument kinds
    (counter vs gauge vs histogram).  Before this check the second
    registration silently shadowed the first in :meth:`snapshot` —
    dashboards read whichever family exported last.  Raised at record
    time, naming both kinds."""

    def __init__(self, name: str, existing: str, requested: str):
        self.name = name
        super().__init__(
            f"metric {name!r} is already registered as a {existing}; "
            f"cannot also record it as a {requested} — instrument kinds "
            "are exclusive per name"
        )


_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(name: str, labels: Dict[str, object]) -> _Key:
    if not labels:
        return (name, ())
    return (name, tuple(sorted((k, str(v)) for k, v in labels.items())))


class _Histogram:
    __slots__ = ("count", "sum", "min", "max", "buckets", "bounds")

    def __init__(self, bounds=DEFAULT_BUCKETS):
        self.bounds = tuple(bounds)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets = [0] * (len(self.bounds) + 1)  # last = +Inf

    def observe(self, value: float) -> None:
        self.count += 1
        self.sum += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        for i, b in enumerate(self.bounds):
            if value <= b:
                self.buckets[i] += 1
                return
        self.buckets[-1] += 1

    def merge_into(self, other: "_Histogram") -> None:
        """Accumulate this histogram into ``other`` (same bounds — the
        windowed wrapper's read-side merge)."""
        other.count += self.count
        other.sum += self.sum
        other.min = min(other.min, self.min)
        other.max = max(other.max, self.max)
        for i, n in enumerate(self.buckets):
            other.buckets[i] += n

    def quantile(self, q: float) -> Optional[float]:
        """Estimated ``q``-quantile (0..1) by linear interpolation
        within the containing bucket, clamped to the observed min/max.
        Resolution is the bucket grid's — register fine bounds
        (:data:`LATENCY_MS_BUCKETS`) for names whose percentiles matter."""
        if self.count == 0:
            return None
        target = max(0.0, min(1.0, float(q))) * self.count
        cum = 0.0
        lo = 0.0
        for b, n in zip(self.bounds, self.buckets[:-1]):
            if n and cum + n >= target:
                val = lo + (b - lo) * (target - cum) / n
                return min(max(val, self.min), self.max)
            cum += n
            lo = b
        return self.max

    def fraction_above(self, threshold: float) -> float:
        """Estimated fraction of samples strictly above ``threshold``
        (same interpolation as :meth:`quantile`) — the SLO burn-rate
        numerator."""
        if self.count == 0:
            return 0.0
        t = float(threshold)
        below = 0.0
        lo = 0.0
        for b, n in zip(self.bounds, self.buckets[:-1]):
            if b <= t:
                below += n
            elif lo < t:
                below += n * (t - lo) / (b - lo)
            lo = b
        n_inf = self.buckets[-1]
        if n_inf:
            top = self.max if self.max > lo else lo
            if t >= top:
                below += n_inf
            elif t > lo:
                below += n_inf * (t - lo) / (top - lo)
        return max(0.0, min(1.0, 1.0 - below / self.count))

    def as_dict(self) -> dict:
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
        }


class MetricsRegistry:
    """Thread-safe named counters/gauges/histograms with labels."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[_Key, float] = {}
        self._gauges: Dict[_Key, float] = {}
        self._hists: Dict[_Key, _Histogram] = {}
        #: name -> instrument kind; one name is one kind forever (until
        #: reset) — a second registration under a different kind used to
        #: silently shadow the first in the snapshot
        self._kinds: Dict[str, str] = {}
        #: name -> histogram bucket bounds.  Configuration, not data:
        #: survives :meth:`reset` so module-import-time registrations
        #: (the serve subsystem's ms-resolution latency bounds) hold for
        #: the whole process, including across test resets.
        self._bounds_by_name: Dict[str, Tuple[float, ...]] = {}

    def _check_kind(self, name: str, kind: str) -> None:
        """Must hold self._lock.  Raises :class:`MetricKindError` when
        ``name`` is already a different instrument kind — one dict
        lookup on the hot path."""
        prev = self._kinds.get(name)
        if prev is None:
            self._kinds[name] = kind
        elif prev != kind:
            raise MetricKindError(name, prev, kind)

    # ----------------------------------------------------------- record
    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        """Add ``value`` to a monotonic counter."""
        if not enabled():
            return
        k = _key(name, labels)
        with self._lock:
            self._check_kind(name, "counter")
            self._counters[k] = self._counters.get(k, 0.0) + float(value)

    def set_gauge(self, name: str, value: float, **labels) -> None:
        """Set a point-in-time gauge."""
        if not enabled():
            return
        with self._lock:
            self._check_kind(name, "gauge")
            self._gauges[_key(name, labels)] = float(value)

    def gauge_max(self, name: str, value: float, **labels) -> None:
        """Raise a gauge to ``value`` if higher (watermark semantics —
        HBM/RSS peaks survive later lower samples)."""
        if not enabled():
            return
        k = _key(name, labels)
        with self._lock:
            self._check_kind(name, "gauge")
            prev = self._gauges.get(k)
            if prev is None or value > prev:
                self._gauges[k] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        """Record one sample into a histogram (bucket bounds: the ones
        :meth:`register_buckets` registered for ``name``, else
        :data:`DEFAULT_BUCKETS`)."""
        if not enabled():
            return
        k = _key(name, labels)
        with self._lock:
            self._check_kind(name, "histogram")
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = _Histogram(
                    self._bounds_by_name.get(name, DEFAULT_BUCKETS)
                )
            h.observe(float(value))

    def register_buckets(self, name: str, bounds) -> None:
        """Register per-metric histogram bucket bounds for ``name``.
        Applies to histograms created AFTER registration (register at
        module import, before the first sample); an already-live series
        keeps the bounds it was born with.  Registration claims the name
        as a histogram — recording it as a counter/gauge afterwards
        raises :class:`MetricKindError`, same as any kind conflict."""
        bounds = tuple(sorted(float(b) for b in bounds))
        if not bounds:
            raise ValueError(f"register_buckets({name!r}): empty bounds")
        with self._lock:
            self._check_kind(name, "histogram")
            self._bounds_by_name[name] = bounds

    def bucket_bounds(self, name: str) -> Tuple[float, ...]:
        """The bucket bounds a new ``name`` histogram would use."""
        with self._lock:
            return self._bounds_by_name.get(name, DEFAULT_BUCKETS)

    # ------------------------------------------------------------- read
    @staticmethod
    def _fmt(k: _Key) -> str:
        name, labels = k
        if not labels:
            return name
        inner = ",".join(f"{lk}={lv}" for lk, lv in labels)
        return f"{name}{{{inner}}}"

    def snapshot(self) -> dict:
        """Plain-dict view: ``{"counters": {...}, "gauges": {...},
        "histograms": {...}}`` with ``name{label=value}`` keys."""
        with self._lock:
            return {
                "counters": {self._fmt(k): v for k, v in self._counters.items()},
                "gauges": {self._fmt(k): v for k, v in self._gauges.items()},
                "histograms": {
                    self._fmt(k): h.as_dict() for k, h in self._hists.items()
                },
            }

    def counter_value(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get(_key(name, labels), 0.0)

    def counter_total(self, name: str) -> float:
        """Sum of a counter over every label combination."""
        with self._lock:
            return sum(v for (n, _), v in self._counters.items() if n == name)

    def gauge_value(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get(_key(name, labels))

    def histogram_value(self, name: str, **labels) -> Optional[dict]:
        """One histogram series as its ``as_dict()`` summary, or None —
        the point read for surfaces that need a couple of series
        (``/statusz``'s prime-ladder block) without paying a full
        ``snapshot()`` copy of every histogram per poll."""
        with self._lock:
            h = self._hists.get(_key(name, labels))
            return None if h is None else h.as_dict()

    def histogram_summary(
        self, name: str, quantiles=(0.5, 0.95, 0.99), **labels
    ) -> Optional[dict]:
        """One histogram series as ``as_dict()`` plus interpolated
        quantiles (``p50``/``p95``/... keys), or None.  The read behind
        ``/statusz`` blocks that need percentiles of a cumulative
        series (fleet apply/wire, ingress parse/admit) without a
        windowed wrapper per label combination."""
        with self._lock:
            h = self._hists.get(_key(name, labels))
            if h is None:
                return None
            out = h.as_dict()
            for q in quantiles:
                out[f"p{int(round(float(q) * 100))}"] = h.quantile(float(q))
            return out

    def counter_series(self, name: str) -> List[Tuple[dict, float]]:
        """Every label combination of one counter, as
        ``(labels_dict, value)`` pairs — the per-kind / per-worker
        breakdown read (``ingress.frame_errors{kind=}``,
        ``serve.net.retransmits{worker=}``)."""
        with self._lock:
            return [
                (dict(labels), v)
                for (n, labels), v in sorted(self._counters.items())
                if n == name
            ]

    def histogram_series(
        self, name: str, quantiles=(0.5, 0.95, 0.99)
    ) -> List[Tuple[dict, dict]]:
        """Every label combination of one histogram, as
        ``(labels_dict, summary)`` pairs (summary per
        :meth:`histogram_summary`)."""
        with self._lock:
            out = []
            for (n, labels), h in sorted(self._hists.items()):
                if n != name:
                    continue
                d = h.as_dict()
                for q in quantiles:
                    d[f"p{int(round(float(q) * 100))}"] = h.quantile(float(q))
                out.append((dict(labels), d))
            return out

    def remove_gauge(self, name: str, **labels) -> None:
        """Drop one gauge series (registry owners evicting dead keys —
        e.g. guard's breaker registry — keep export cardinality bounded
        by removing the series along with the owner's entry)."""
        with self._lock:
            self._gauges.pop(_key(name, labels), None)

    # --------------------------------------------- cross-process shipping
    def export_raw(self):
        """Raw copies of every series, keyed by ``(name, labels)``:
        ``(counters, gauges, hists)`` where a histogram entry is
        ``(bounds, buckets, count, sum, min, max)``.  The worker-side
        delta exporter (``serve/telemetry.py``) diffs two of these;
        unlike :meth:`snapshot` nothing is string-formatted, so the
        shipped keys round-trip exactly."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {
                k: (
                    h.bounds,
                    list(h.buckets),
                    h.count,
                    h.sum,
                    (h.min if h.count else None),
                    (h.max if h.count else None),
                )
                for k, h in self._hists.items()
            }
        return counters, gauges, hists

    def merge_histogram(
        self,
        name: str,
        labels: Dict[str, object],
        bounds,
        buckets,
        count,
        total,
        mn=None,
        mx=None,
    ) -> None:
        """Fold a shipped histogram delta into one series.  The series
        is created with the SHIPPED bounds (a worker's registration,
        not this registry's) so bucket counts merge exactly; a
        bounds/shape mismatch against an existing series drops the
        shipment rather than corrupting the buckets."""
        if not enabled():
            return
        bounds = tuple(float(b) for b in bounds)
        buckets = [int(b) for b in buckets]
        if len(buckets) != len(bounds) + 1:
            return
        k = _key(name, labels)
        with self._lock:
            self._check_kind(name, "histogram")
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = _Histogram(bounds)
            if h.bounds != bounds:
                return
            h.count += int(count)
            h.sum += float(total)
            for i, n in enumerate(buckets):
                h.buckets[i] += n
            if mn is not None:
                h.min = min(h.min, float(mn))
            if mx is not None:
                h.max = max(h.max, float(mx))

    def merge_entries(self, entries, **extra_labels) -> int:
        """Fold worker-shipped delta entries (the wire format
        ``serve/telemetry.py`` emits: ``["c"|"g"|"h", name, labels,
        data]``) into this registry, with ``extra_labels`` (the
        ``worker=``/``host=`` fan-out) appended to every series.
        Tolerant by contract — a malformed or kind-conflicting entry is
        skipped, never raised (an old/new peer mix must degrade to
        missing telemetry, not a dead fleet).  Returns entries merged."""
        merged = 0
        if not entries:
            return merged
        for entry in entries:
            try:
                kind, name, labels, data = entry
                name = str(name)
                lbl = {str(k): str(v) for k, v in labels}
                for k, v in extra_labels.items():
                    lbl[str(k)] = str(v)
                if kind == "c":
                    self.inc(name, float(data), **lbl)
                elif kind == "g":
                    self.set_gauge(name, float(data), **lbl)
                elif kind == "h":
                    self.merge_histogram(
                        name,
                        lbl,
                        data["bounds"],
                        data["buckets"],
                        data["count"],
                        data["sum"],
                        mn=data.get("min"),
                        mx=data.get("max"),
                    )
                else:
                    continue
                merged += 1
            except (MetricKindError, TypeError, ValueError, KeyError):
                continue
        return merged

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format.  Metric names sanitize
        ``.``/``-`` to ``_``; histograms export ``_count``/``_sum`` plus
        cumulative ``_bucket{le=...}`` series."""

        def san(name: str) -> str:
            return "".join(c if c.isalnum() or c == "_" else "_" for c in name)

        def lbl(labels: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
            parts = [f'{lk}="{lv}"' for lk, lv in labels]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        lines: List[str] = []
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                lines.append(f"{san(name)}_total{lbl(labels)} {v:g}")
            for (name, labels), v in sorted(self._gauges.items()):
                lines.append(f"{san(name)}{lbl(labels)} {v:g}")
            for (name, labels), h in sorted(self._hists.items()):
                base = san(name)
                lines.append(f"{base}_count{lbl(labels)} {h.count}")
                lines.append(f"{base}_sum{lbl(labels)} {h.sum:g}")
                cum = 0
                for bound, n in zip(h.bounds, h.buckets):
                    cum += n
                    le = 'le="%g"' % bound
                    lines.append(f"{base}_bucket{lbl(labels, le)} {cum}")
                cum += h.buckets[-1]
                inf = 'le="+Inf"'
                lines.append(f"{base}_bucket{lbl(labels, inf)} {cum}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._kinds.clear()
            # bucket registrations are configuration, not data: they
            # survive, and so does the histogram-kind claim they made
            for name in self._bounds_by_name:
                self._kinds[name] = "histogram"


#: the process-wide registry every subsystem reports to
REGISTRY = MetricsRegistry()


class WindowedHistogram:
    """A rolling-window histogram: a ring of per-interval
    :class:`_Histogram` slices merged on read.

    The registry's histograms are cumulative — correct for counters and
    whole-run totals, useless for "p99 over the last minute" (one slow
    hour ago poisons the percentile forever).  This wrapper keeps
    ``intervals`` fixed-width time slices covering ``window_seconds``;
    :meth:`observe` lands the sample in the current slice AND forwards
    it to the process-wide registry under the same ``name`` — so
    ``/metrics`` keeps its cumulative series while ``/statusz`` reads
    the window.  Reads merge the non-expired slices into one histogram
    and answer :meth:`percentile` / :meth:`fraction_above` from it
    (bucket-interpolated: register fine bounds for the name —
    :data:`LATENCY_MS_BUCKETS` — or the estimates are as coarse as
    :data:`DEFAULT_BUCKETS`).

    Lock-cheap: one observe is the registry's lock plus one slot lock;
    an expired slot is recycled in place, so memory is
    ``intervals × len(bounds)`` forever.  ``clock`` is injectable for
    tests (monotonic seconds)."""

    def __init__(
        self,
        name: str,
        window_seconds: float = 60.0,
        intervals: int = 12,
        bounds=None,
        clock=time.monotonic,
        **labels,
    ):
        self.name = name
        self.window_seconds = float(window_seconds)
        self._n = max(1, int(intervals))
        self._interval = self.window_seconds / self._n
        self._labels = labels
        self._bounds = (
            tuple(bounds) if bounds is not None else REGISTRY.bucket_bounds(name)
        )
        self._clock = clock
        self._lock = threading.Lock()
        #: slot -> (interval epoch index, histogram); epoch -1 = empty
        self._ring: List[Tuple[int, _Histogram]] = [
            (-1, _Histogram(self._bounds)) for _ in range(self._n)
        ]

    def observe(self, value: float) -> None:
        REGISTRY.observe(self.name, value, **self._labels)
        if not enabled():
            return
        v = float(value)
        idx = int(self._clock() // self._interval)
        slot = idx % self._n
        with self._lock:
            epoch, h = self._ring[slot]
            if epoch != idx:  # slot holds an expired interval: recycle
                h = _Histogram(self._bounds)
                self._ring[slot] = (idx, h)
            h.observe(v)

    def merged(self) -> _Histogram:
        """One histogram over every non-expired interval (the window)."""
        idx = int(self._clock() // self._interval)
        m = _Histogram(self._bounds)
        with self._lock:
            for epoch, h in self._ring:
                if epoch >= 0 and idx - epoch < self._n:
                    h.merge_into(m)
        return m

    def percentile(self, p: float) -> Optional[float]:
        """Windowed percentile (``p`` in 0..100), or None when empty."""
        return self.merged().quantile(p / 100.0)

    def fraction_above(self, threshold: float) -> float:
        return self.merged().fraction_above(threshold)

    def summary(self) -> dict:
        """Windowed ``{count, sum, min, max, p50, p95, p99,
        window_seconds}`` — the shape ``/statusz`` embeds."""
        m = self.merged()
        return {
            "count": m.count,
            "sum": m.sum,
            "min": m.min if m.count else None,
            "max": m.max if m.count else None,
            "p50": m.quantile(0.50),
            "p95": m.quantile(0.95),
            "p99": m.quantile(0.99),
            "window_seconds": self.window_seconds,
        }


# module-level conveniences (the instrumented call sites use these)
inc = REGISTRY.inc
observe = REGISTRY.observe
set_gauge = REGISTRY.set_gauge
gauge_max = REGISTRY.gauge_max
snapshot = REGISTRY.snapshot
reset = REGISTRY.reset
register_buckets = REGISTRY.register_buckets
