"""Process-wide metrics registry: counters, gauges, histograms.

The reference surfaced operational numbers through Spark's metrics sinks
and event-log UI; the TPU rebuild has no cluster manager underneath, so
the registry itself is the sink every subsystem reports to: blockstore
bytes and retries, durable-layer corruption/fallback counts, executor
retry time, solver telemetry, fault-injection outcomes, HBM watermarks.
One process == one registry (module-level :data:`REGISTRY`), mirroring
``keystone_tpu.faults``' process-global counters.

Design constraints (the reasons this module is stdlib-only and lockful):

- **hot-path cheap**: a counter bump is one lock + one dict update —
  the same order of cost as the ``fault_point`` hook already paid on
  every instrumented path.  ``KEYSTONE_METRICS=0`` short-circuits every
  recording call to a single env lookup (the disabled-mode guarantee
  tests pin).
- **no jax / no numpy at import**: ``keystone_tpu.faults`` imports this
  module, and faults must stay importable before any backend exists.
- **label-aware**: metrics key on ``(name, sorted(labels))`` so
  per-site/per-rule breakdowns (``faults.injected{site=...}``) live next
  to their totals without string mangling at record time.

Exports ride two formats: :meth:`MetricsRegistry.snapshot` (plain dict,
embedded in run-ledger JSONL and bench artifacts) and
:meth:`MetricsRegistry.to_prometheus_text` (the text exposition format,
for scraping or ad-hoc diffing).
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Dict, List, Optional, Tuple

ENV_DISABLE = "KEYSTONE_METRICS"

#: histogram bucket upper bounds (seconds-oriented; byte-scale values
#: simply land in +Inf, where count/sum/min/max still describe them)
DEFAULT_BUCKETS = (
    0.001,
    0.005,
    0.01,
    0.05,
    0.1,
    0.5,
    1.0,
    5.0,
    10.0,
    60.0,
)


def enabled() -> bool:
    """Recording on?  ``KEYSTONE_METRICS=0`` disables every write path
    (reads — snapshot/export — always work)."""
    return os.environ.get(ENV_DISABLE, "1") != "0"


class MetricKindError(TypeError):
    """One metric name registered as two different instrument kinds
    (counter vs gauge vs histogram).  Before this check the second
    registration silently shadowed the first in :meth:`snapshot` —
    dashboards read whichever family exported last.  Raised at record
    time, naming both kinds."""

    def __init__(self, name: str, existing: str, requested: str):
        self.name = name
        super().__init__(
            f"metric {name!r} is already registered as a {existing}; "
            f"cannot also record it as a {requested} — instrument kinds "
            "are exclusive per name"
        )


_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(name: str, labels: Dict[str, object]) -> _Key:
    if not labels:
        return (name, ())
    return (name, tuple(sorted((k, str(v)) for k, v in labels.items())))


class _Histogram:
    __slots__ = ("count", "sum", "min", "max", "buckets")

    def __init__(self, bounds=DEFAULT_BUCKETS):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets = [0] * (len(bounds) + 1)  # last = +Inf

    def observe(self, value: float, bounds=DEFAULT_BUCKETS) -> None:
        self.count += 1
        self.sum += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        for i, b in enumerate(bounds):
            if value <= b:
                self.buckets[i] += 1
                return
        self.buckets[-1] += 1

    def as_dict(self) -> dict:
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
        }


class MetricsRegistry:
    """Thread-safe named counters/gauges/histograms with labels."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[_Key, float] = {}
        self._gauges: Dict[_Key, float] = {}
        self._hists: Dict[_Key, _Histogram] = {}
        #: name -> instrument kind; one name is one kind forever (until
        #: reset) — a second registration under a different kind used to
        #: silently shadow the first in the snapshot
        self._kinds: Dict[str, str] = {}

    def _check_kind(self, name: str, kind: str) -> None:
        """Must hold self._lock.  Raises :class:`MetricKindError` when
        ``name`` is already a different instrument kind — one dict
        lookup on the hot path."""
        prev = self._kinds.get(name)
        if prev is None:
            self._kinds[name] = kind
        elif prev != kind:
            raise MetricKindError(name, prev, kind)

    # ----------------------------------------------------------- record
    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        """Add ``value`` to a monotonic counter."""
        if not enabled():
            return
        k = _key(name, labels)
        with self._lock:
            self._check_kind(name, "counter")
            self._counters[k] = self._counters.get(k, 0.0) + float(value)

    def set_gauge(self, name: str, value: float, **labels) -> None:
        """Set a point-in-time gauge."""
        if not enabled():
            return
        with self._lock:
            self._check_kind(name, "gauge")
            self._gauges[_key(name, labels)] = float(value)

    def gauge_max(self, name: str, value: float, **labels) -> None:
        """Raise a gauge to ``value`` if higher (watermark semantics —
        HBM/RSS peaks survive later lower samples)."""
        if not enabled():
            return
        k = _key(name, labels)
        with self._lock:
            self._check_kind(name, "gauge")
            prev = self._gauges.get(k)
            if prev is None or value > prev:
                self._gauges[k] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        """Record one sample into a histogram."""
        if not enabled():
            return
        k = _key(name, labels)
        with self._lock:
            self._check_kind(name, "histogram")
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = _Histogram()
            h.observe(float(value))

    # ------------------------------------------------------------- read
    @staticmethod
    def _fmt(k: _Key) -> str:
        name, labels = k
        if not labels:
            return name
        inner = ",".join(f"{lk}={lv}" for lk, lv in labels)
        return f"{name}{{{inner}}}"

    def snapshot(self) -> dict:
        """Plain-dict view: ``{"counters": {...}, "gauges": {...},
        "histograms": {...}}`` with ``name{label=value}`` keys."""
        with self._lock:
            return {
                "counters": {self._fmt(k): v for k, v in self._counters.items()},
                "gauges": {self._fmt(k): v for k, v in self._gauges.items()},
                "histograms": {
                    self._fmt(k): h.as_dict() for k, h in self._hists.items()
                },
            }

    def counter_value(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get(_key(name, labels), 0.0)

    def counter_total(self, name: str) -> float:
        """Sum of a counter over every label combination."""
        with self._lock:
            return sum(v for (n, _), v in self._counters.items() if n == name)

    def gauge_value(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get(_key(name, labels))

    def remove_gauge(self, name: str, **labels) -> None:
        """Drop one gauge series (registry owners evicting dead keys —
        e.g. guard's breaker registry — keep export cardinality bounded
        by removing the series along with the owner's entry)."""
        with self._lock:
            self._gauges.pop(_key(name, labels), None)

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format.  Metric names sanitize
        ``.``/``-`` to ``_``; histograms export ``_count``/``_sum`` plus
        cumulative ``_bucket{le=...}`` series."""

        def san(name: str) -> str:
            return "".join(c if c.isalnum() or c == "_" else "_" for c in name)

        def lbl(labels: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
            parts = [f'{lk}="{lv}"' for lk, lv in labels]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        lines: List[str] = []
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                lines.append(f"{san(name)}_total{lbl(labels)} {v:g}")
            for (name, labels), v in sorted(self._gauges.items()):
                lines.append(f"{san(name)}{lbl(labels)} {v:g}")
            for (name, labels), h in sorted(self._hists.items()):
                base = san(name)
                lines.append(f"{base}_count{lbl(labels)} {h.count}")
                lines.append(f"{base}_sum{lbl(labels)} {h.sum:g}")
                cum = 0
                for bound, n in zip(DEFAULT_BUCKETS, h.buckets):
                    cum += n
                    le = 'le="%g"' % bound
                    lines.append(f"{base}_bucket{lbl(labels, le)} {cum}")
                cum += h.buckets[-1]
                inf = 'le="+Inf"'
                lines.append(f"{base}_bucket{lbl(labels, inf)} {cum}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._kinds.clear()


#: the process-wide registry every subsystem reports to
REGISTRY = MetricsRegistry()

# module-level conveniences (the instrumented call sites use these)
inc = REGISTRY.inc
observe = REGISTRY.observe
set_gauge = REGISTRY.set_gauge
gauge_max = REGISTRY.gauge_max
snapshot = REGISTRY.snapshot
reset = REGISTRY.reset
