"""Framework interop converters.

The reference bridges Breeze and Spark-MLlib linalg types
(utils/MLlibUtils.scala); the ecosystem neighbors here are numpy and
torch (CPU), e.g. for loading torchvision-prepped data or comparing
against torch reference implementations.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def to_jax(x) -> jnp.ndarray:
    """torch tensor / numpy array / scipy sparse → jnp array."""
    if hasattr(x, "detach"):  # torch tensor
        return jnp.asarray(x.detach().cpu().numpy())
    if hasattr(x, "toarray"):  # scipy sparse
        return jnp.asarray(x.toarray())
    return jnp.asarray(x)


def to_torch(x):
    """jnp/numpy array → torch CPU tensor."""
    import torch

    # copy: jax arrays surface as non-writable numpy views
    return torch.from_numpy(np.array(x, copy=True))


def to_numpy(x) -> np.ndarray:
    if hasattr(x, "detach"):
        return x.detach().cpu().numpy()
    if hasattr(x, "toarray"):
        return x.toarray()
    return np.asarray(x)
