"""Content fingerprints for arrays.

Weight-carrying transformers (random features, convolution filters, GMM
vocabularies) need a *stable* identity for CSE and saved-state keys —
``id()`` is only unique within a process and unusable as a persistent
key.  A short digest of the array bytes is both.
"""

from __future__ import annotations

import hashlib

import numpy as np


def array_fingerprint(*arrays) -> str:
    h = hashlib.sha256()
    for a in arrays:
        arr = np.asarray(a)
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:16]


def cached_fingerprint(obj, attr: str, *arrays) -> str:
    """Compute once per object, cache on the instance."""
    fp = getattr(obj, attr, None)
    if fp is None:
        fp = array_fingerprint(*arrays)
        setattr(obj, attr, fp)
    return fp
