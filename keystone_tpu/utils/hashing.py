"""Content fingerprints for arrays.

Weight-carrying transformers (random features, convolution filters, GMM
vocabularies) need a *stable* identity for CSE and saved-state keys —
``id()`` is only unique within a process and unusable as a persistent
key.  A short digest of the array bytes is both.
"""

from __future__ import annotations

import hashlib

import numpy as np


def array_fingerprint(*arrays) -> str:
    h = hashlib.sha256()
    for a in arrays:
        arr = np.asarray(a)
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:16]


def cached_fingerprint(obj, attr: str, *arrays) -> str:
    """Compute once per object, cache on the instance.

    The cache records the array objects that were hashed (strong refs —
    they're alive through the owning transformer anyway) and is valid
    only while the same objects are passed, so reassigning a
    transformer's weights (``t.filters = new``) invalidates it instead
    of reporting the stale digest (which would let CSE or saved-state
    rules silently alias nodes with different weights).  Bare ``id()``
    keys would be unsound here: CPython reuses addresses after GC."""
    cached = getattr(obj, attr, None)
    if (
        cached is not None
        and len(cached[0]) == len(arrays)
        and all(a is b for a, b in zip(cached[0], arrays))
    ):
        return cached[1]
    fp = array_fingerprint(*arrays)
    setattr(obj, attr, (tuple(arrays), fp))
    return fp
