"""Content fingerprints for arrays.

Weight-carrying transformers (random features, convolution filters, GMM
vocabularies) need a *stable* identity for CSE and saved-state keys —
``id()`` is only unique within a process and unusable as a persistent
key.  A short digest of the array bytes is both.
"""

from __future__ import annotations

import hashlib

import numpy as np


def array_fingerprint(*arrays) -> str:
    h = hashlib.sha256()
    for a in arrays:
        arr = np.asarray(a)
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:16]


def _stable_repr(p) -> str:
    """A process-stable repr of a ``params()`` value: containers recurse
    per element, and ONLY an element whose default repr carries a
    process-local address collapses to its type name — collapsing the
    whole container would also drop its well-behaved siblings, letting
    two pipelines differing only in those params hash identically (the
    stale-artifact hazard the signature exists to prevent)."""
    if isinstance(p, (tuple, list)):
        inner = ",".join(_stable_repr(x) for x in p)
        return f"{type(p).__name__}({inner})"
    if isinstance(p, dict):
        items = sorted(
            (_stable_repr(k), _stable_repr(v)) for k, v in p.items()
        )
        return "dict(" + ",".join(f"{k}:{v}" for k, v in items) + ")"
    r = repr(p)
    return type(p).__name__ if " at 0x" in r else r


def pipeline_fingerprint(pipeline) -> str:
    """Stable content hash of a fitted pipeline: graph structure (topo
    order of operator/transformer types + CSE params) plus every fitted
    array's shape/dtype/bytes.

    The AOT artifact tier (``FrozenApplier.export_artifacts``) keys
    serialized executables by this — an artifact must never be replayed
    against a pipeline whose weights differ from the one it was lowered
    from, and process-local identities (``id()``, optimizer output,
    pickle bytes of hash-randomized sets) are all unstable across the
    publish/deploy process boundary.  Computed from the PRE-optimizer
    graph (the pickled deploy payload), never the optimized one: rules
    like ProfilingAutoCacheRule place nodes by measured timings, so two
    processes can optimize the same pipeline into different graphs.

    Cached on the instance (``_keystone_fp``), validated by fitted-array
    identity like :func:`cached_fingerprint` — replacing a fitted array
    invalidates the cache instead of reporting the stale digest.  The
    cache attribute survives pickling, so replica clones of a published
    pipeline reuse the publisher's hash without re-reading every weight.
    """
    from keystone_tpu.workflow.executor import block_on_arrays

    g = pipeline.graph
    struct = hashlib.sha256()
    arrays: list = []
    for n in g.topological_nodes():
        op = g.operators[n]
        struct.update(type(op).__name__.encode())
        t = getattr(op, "transformer", None)
        if t is None:
            continue
        struct.update(type(t).__name__.encode())
        try:
            p = t.params()
        except Exception:
            p = None
        struct.update(_stable_repr(p).encode())
        block_on_arrays(t, visit=arrays.append)
    struct_hex = struct.hexdigest()[:16]
    cached = getattr(pipeline, "_keystone_fp", None)
    if (
        cached is not None
        and cached[0] == struct_hex
        and len(cached[1]) == len(arrays)
        and all(a is b for a, b in zip(cached[1], arrays))
    ):
        return cached[2]
    fp = struct_hex + array_fingerprint(*arrays)
    try:
        pipeline._keystone_fp = (struct_hex, tuple(arrays), fp)
    except AttributeError:
        pass
    return fp


def cached_fingerprint(obj, attr: str, *arrays) -> str:
    """Compute once per object, cache on the instance.

    The cache records the array objects that were hashed (strong refs —
    they're alive through the owning transformer anyway) and is valid
    only while the same objects are passed, so reassigning a
    transformer's weights (``t.filters = new``) invalidates it instead
    of reporting the stale digest (which would let CSE or saved-state
    rules silently alias nodes with different weights).  Bare ``id()``
    keys would be unsound here: CPython reuses addresses after GC."""
    cached = getattr(obj, attr, None)
    if (
        cached is not None
        and len(cached[0]) == len(arrays)
        and all(a is b for a, b in zip(cached[0], arrays))
    ):
        return cached[1]
    fp = array_fingerprint(*arrays)
    setattr(obj, attr, (tuple(arrays), fp))
    return fp
