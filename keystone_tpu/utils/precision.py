"""Matmul precision policy — what bf16 actually buys on this hardware.

The reference computes everything in f64 on CPU BLAS (netlib-java,
SURVEY.md §2.8).  On TPU the naive expectation is "bf16 inputs ≈ 4× MXU
throughput", but measurement on v5 lite (chained in-jit matmuls, real
device sync) shows XLA's DEFAULT precision already runs f32 matmuls as
bf16-grade MXU passes:

    f32 inputs, precision=default : 2.0× the throughput of true f32
    f32 inputs, precision=float32 : baseline (full-precision passes)
    bf16 inputs                   : ≈ default-f32 (no additional compute win)

Two consequences shape this module:

1. **bf16 is a BANDWIDTH/capacity lever, not a compute lever.**  Explicit
   bf16 pays off only where an op is HBM-bound on its inputs: the SIFT
   windowing convs (+17% measured) and the Pallas FV kernel's descriptor
   stream (+11%).  Output-bound contractions (FV sufficient-statistic
   einsums: 0.64×) and compute-bound convs (Convolver: 0.94×) get only
   cast overhead and are deliberately NOT under the policy, as is the
   phase-sensitive CosineRandomFeatures (unbounded error through cos).

2. **Solvers must opt OUT of XLA's default.**  Default precision quietly
   degrades Gramians/normal equations to bf16-grade passes on TPU — the
   one place the reference used f64.  :func:`sdot` /
   :func:`solver_precision` pin solver contractions to true-f32 passes
   (2× slower on those matmuls, correctness first; env-overridable).

Modes for the featurize policy:
  - ``auto`` (default): bf16 when the default backend is a TPU, f32
    otherwise (CPU test meshes keep full precision).
  - ``bf16`` / ``f32``: forced, e.g. for parity tests.

Set via env ``KEYSTONE_MATMUL``, :func:`set_matmul`, or the
:func:`matmul` context manager.  Compiled functions key their caches on
the resolved mode (transformer jit wrappers include it in their cache
signature; module-level kernels take it as a static argument), so
flipping the policy retraces rather than silently reusing stale
executables.
"""

from __future__ import annotations

import os
from contextlib import contextmanager

import jax
import jax.numpy as jnp

_MODES = ("auto", "bf16", "f32")
_MODE = os.environ.get("KEYSTONE_MATMUL", "auto")
if _MODE not in _MODES:
    raise ValueError(f"KEYSTONE_MATMUL must be one of {_MODES}, got {_MODE!r}")

_TPU_PLATFORMS = ("tpu", "axon")
_DEFAULT_IS_TPU: bool | None = None


def _on_tpu() -> bool:
    """Whether computation currently targets a TPU.

    Resolution order mirrors ops/fisher_pallas.py § pallas_supported: the
    active framework mesh first (so a CPU mesh on a TPU host — e.g. the
    multichip dryrun — keeps full precision and validates what it claims
    to), then the default backend (cached: it cannot change)."""
    global _DEFAULT_IS_TPU
    try:
        from keystone_tpu.parallel.mesh import active_mesh

        m = active_mesh()
        if m is not None and m.devices.size:
            return m.devices.flat[0].platform in _TPU_PLATFORMS
    except Exception:
        pass
    if _DEFAULT_IS_TPU is None:
        try:
            dev = jax.devices()[0]
            kind = getattr(dev, "device_kind", "") or ""
            _DEFAULT_IS_TPU = dev.platform in _TPU_PLATFORMS or "TPU" in kind
        except Exception:
            _DEFAULT_IS_TPU = False
    return _DEFAULT_IS_TPU


def set_matmul(mode: str) -> None:
    global _MODE
    if mode not in _MODES:
        raise ValueError(f"matmul mode must be one of {_MODES}, got {mode!r}")
    _MODE = mode


def matmul_mode() -> str:
    """The resolved mode: 'bf16' or 'f32' (never 'auto')."""
    if _MODE == "auto":
        return "bf16" if _on_tpu() else "f32"
    return _MODE


@contextmanager
def matmul(mode: str):
    prev = _MODE
    set_matmul(mode)
    try:
        yield
    finally:
        set_matmul(prev)


_SOLVER_PRECISIONS = ("default", "float32", "highest")
_SOLVER_PRECISION = os.environ.get("KEYSTONE_SOLVER_PRECISION", "float32")
if _SOLVER_PRECISION not in _SOLVER_PRECISIONS:
    raise ValueError(
        f"KEYSTONE_SOLVER_PRECISION must be one of {_SOLVER_PRECISIONS}, "
        f"got {_SOLVER_PRECISION!r}"
    )


def solver_precision():
    """lax.Precision for solver contractions (Gramians, normal equations,
    LBFGS gradients, covariances).

    Measured on TPU v5 lite: XLA's DEFAULT matmul precision runs f32
    inputs as bf16-grade MXU passes (~2× the throughput of true f32) —
    acceptable for forward features, but normal equations square the
    condition number and the reference solves them in f64, so solvers
    default to 'float32' (full-precision passes).  Override with
    ``KEYSTONE_SOLVER_PRECISION=default`` to trade accuracy for the 2×.
    """
    from jax import lax

    return {
        "default": lax.Precision.DEFAULT,
        "float32": lax.Precision.HIGHEST,
        "highest": lax.Precision.HIGHEST,
    }[_SOLVER_PRECISION]


def sdot(a, b):
    """Solver-grade matmul: true-f32 MXU passes, f32 accumulation.  Use
    for every contraction whose result enters a linear solve (Gramians,
    AᵀB right-hand sides, covariances, EM sufficient statistics,
    LBFGS gradients)."""
    import jax.numpy as jnp

    return jnp.matmul(
        a, b, precision=solver_precision(), preferred_element_type=jnp.float32
    )


def fdtype(mode: str | None = None):
    """The featurize-matmul input dtype for ``mode`` (default: current)."""
    m = matmul_mode() if mode is None else mode
    return jnp.bfloat16 if m == "bf16" else jnp.float32


def fcast(*xs, mode: str | None = None):
    """Cast featurize-matmul inputs to the policy dtype.  Pair every use
    with ``preferred_element_type=jnp.float32`` so accumulation (and the
    result) stays f32."""
    dt = fdtype(mode)
    out = tuple(jnp.asarray(x).astype(dt) for x in xs)
    return out if len(out) > 1 else out[0]
