"""Matmul precision policy — what bf16 actually buys on this hardware.

The reference computes everything in f64 on CPU BLAS (netlib-java,
SURVEY.md §2.8).  On TPU the naive expectation is "bf16 inputs ≈ 4× MXU
throughput", but measurement on v5 lite (chained in-jit matmuls, real
device sync) shows XLA's DEFAULT precision already runs f32 matmuls as
bf16-grade MXU passes:

    f32 inputs, precision=default : 2.0× the throughput of true f32
    f32 inputs, precision=float32 : baseline (full-precision passes)
    bf16 inputs                   : ≈ default-f32 (no additional compute win)

Two consequences shape this module:

1. **bf16 is a BANDWIDTH/capacity lever, not a compute lever.**  Explicit
   bf16 pays off only where an op is HBM-bound on its inputs: the SIFT
   windowing convs (+17% measured) and the Pallas FV kernel's descriptor
   stream (+11%).  Output-bound contractions (FV sufficient-statistic
   einsums: 0.64×) and compute-bound convs (Convolver: 0.94×) get only
   cast overhead and are deliberately NOT under the policy, as is the
   phase-sensitive CosineRandomFeatures (unbounded error through cos).

2. **Solvers must opt OUT of XLA's default.**  Default precision quietly
   degrades Gramians/normal equations to bf16-grade passes on TPU — the
   one place the reference used f64.  :func:`sdot` /
   :func:`solver_precision` pin solver contractions to true-f32 passes
   (2× slower on those matmuls, correctness first; env-overridable).

Modes for the featurize policy:
  - ``auto`` (default): bf16 when the default backend is a TPU, f32
    otherwise (CPU test meshes keep full precision).
  - ``bf16`` / ``f32``: forced, e.g. for parity tests.
  - ``bf16_apply``: everything ``auto``/``bf16`` does, PLUS the
    opt-in APPLY policy — every hot forward contraction (FV
    posterior/sufficient-statistic einsums, Convolver, blur einsums,
    LCS box filters, block-linear scoring, sparse scoring) casts its
    inputs to bf16 on device through :func:`apply_dot` /
    :func:`apply_einsum`, always with f32 accumulation.  The measured
    per-op story above (bf16 loses on output-bound contractions) is
    about HBM traffic of the op in isolation; inside a fused forward
    program the casts also halve every *inter*-contraction stream, so
    the whole-pipeline win is a separate measurement — bench.py's
    precision sweep is the arbiter.  ``bf16_apply`` resolves to the
    INERT f32 policy off-TPU (CPU test meshes stay bit-identical; see
    :func:`matmul_mode`) unless ``force_bf16_apply`` /
    ``KEYSTONE_BF16_APPLY_FORCE=1`` overrides the gate for parity
    testing.  Solver math (``sdot`` / ``solver_precision`` users:
    Gramians, BCD epochs, L-BFGS, EM) is NOT under this policy in any
    mode.

Set via env ``KEYSTONE_MATMUL``, :func:`set_matmul`, or the
:func:`matmul` context manager.  Compiled functions key their caches on
the resolved mode (transformer jit wrappers include it in their cache
signature; module-level kernels take it as a static argument), so
flipping the policy retraces rather than silently reusing stale
executables.
"""

from __future__ import annotations

import os
from contextlib import contextmanager

import jax
import jax.numpy as jnp

_MODES = ("auto", "bf16", "f32", "bf16_apply")
_MODE = os.environ.get("KEYSTONE_MATMUL", "auto")
if _MODE not in _MODES:
    raise ValueError(f"KEYSTONE_MATMUL must be one of {_MODES}, got {_MODE!r}")
#: True once the mode was pinned by a stronger tier than the plan — the
#: KEYSTONE_MATMUL env override at import, or set_matmul()/matmul()
#: (explicit calls).  While False and 'auto', an installed PhysicalPlan
#: may refine the mode (the planner precedence: explicit > env > plan >
#: static default).
_MODE_EXPLICIT = "KEYSTONE_MATMUL" in os.environ

#: test/dev override: lets ``bf16_apply`` resolve ACTIVE on non-TPU
#: backends so the bf16 numerics are exercisable on CPU meshes (the
#: parity suite); never set in production.
_APPLY_FORCE = os.environ.get("KEYSTONE_BF16_APPLY_FORCE", "0") == "1"

_TPU_PLATFORMS = ("tpu", "axon")
_DEFAULT_IS_TPU: bool | None = None


def _on_tpu() -> bool:
    """Whether computation currently targets a TPU.

    Resolution order mirrors ops/fisher_pallas.py § pallas_supported: the
    active framework mesh first (so a CPU mesh on a TPU host — e.g. the
    multichip dryrun — keeps full precision and validates what it claims
    to), then the default backend (cached: it cannot change)."""
    global _DEFAULT_IS_TPU
    try:
        from keystone_tpu.parallel.mesh import active_mesh

        m = active_mesh()
        if m is not None and m.devices.size:
            return m.devices.flat[0].platform in _TPU_PLATFORMS
    except Exception:
        pass
    if _DEFAULT_IS_TPU is None:
        try:
            dev = jax.devices()[0]
            kind = getattr(dev, "device_kind", "") or ""
            _DEFAULT_IS_TPU = dev.platform in _TPU_PLATFORMS or "TPU" in kind
        except Exception:
            _DEFAULT_IS_TPU = False
    return _DEFAULT_IS_TPU


def set_matmul(mode: str) -> None:
    global _MODE, _MODE_EXPLICIT
    if mode not in _MODES:
        raise ValueError(f"matmul mode must be one of {_MODES}, got {mode!r}")
    _MODE = mode
    _MODE_EXPLICIT = True


def _planned_matmul() -> str | None:
    """The installed PhysicalPlan's matmul winner, or None.  Guarded
    lazy import: with no planner in play this costs one cheap call and
    the legacy resolution is untouched."""
    try:
        from keystone_tpu.planner import registry as _plans

        return _plans.planned_gate("matmul")
    except Exception:
        return None


def matmul_mode() -> str:
    """The resolved mode: 'bf16', 'f32', or 'bf16_apply' (never 'auto').

    With nothing pinned (no ``KEYSTONE_MATMUL`` env, no ``set_matmul``),
    an installed ``PhysicalPlan``'s sampled winner applies first — the
    plan tier of the precedence ladder.  ``bf16_apply`` gates on REAL
    TPU hardware: off-chip it resolves to 'f32' — the inert policy — so
    CPU test meshes (and the multichip dryrun's CPU mesh on a TPU host)
    produce bit-identical outputs with the policy set or not.
    ``force_bf16_apply`` / ``KEYSTONE_BF16_APPLY_FORCE=1`` lifts the
    gate for parity testing."""
    mode = _MODE
    if not _MODE_EXPLICIT:
        planned = _planned_matmul()
        if planned in _MODES:
            mode = planned
    if mode == "auto":
        return "bf16" if _on_tpu() else "f32"
    if mode == "bf16_apply":
        return "bf16_apply" if (_on_tpu() or _APPLY_FORCE) else "f32"
    return mode


@contextmanager
def matmul(mode: str):
    global _MODE, _MODE_EXPLICIT
    prev, prev_explicit = _MODE, _MODE_EXPLICIT
    set_matmul(mode)
    try:
        yield
    finally:
        _MODE = prev
        # restore the explicitness too: a scoped matmul() inside an
        # otherwise-unpinned process must not permanently mask the plan
        _MODE_EXPLICIT = prev_explicit


@contextmanager
def force_bf16_apply():
    """Lift the on-TPU gate so ``bf16_apply`` resolves active on any
    backend — the parity suite's way of exercising the bf16 numerics on
    CPU meshes.  Production code never needs this."""
    global _APPLY_FORCE
    prev = _APPLY_FORCE
    _APPLY_FORCE = True
    try:
        yield
    finally:
        _APPLY_FORCE = prev


_SOLVER_PRECISIONS = ("default", "float32", "highest")
_SOLVER_PRECISION = os.environ.get("KEYSTONE_SOLVER_PRECISION", "float32")
if _SOLVER_PRECISION not in _SOLVER_PRECISIONS:
    raise ValueError(
        f"KEYSTONE_SOLVER_PRECISION must be one of {_SOLVER_PRECISIONS}, "
        f"got {_SOLVER_PRECISION!r}"
    )


def solver_precision():
    """lax.Precision for solver contractions (Gramians, normal equations,
    LBFGS gradients, covariances).

    Measured on TPU v5 lite: XLA's DEFAULT matmul precision runs f32
    inputs as bf16-grade MXU passes (~2× the throughput of true f32) —
    acceptable for forward features, but normal equations square the
    condition number and the reference solves them in f64, so solvers
    default to 'float32' (full-precision passes).  Override with
    ``KEYSTONE_SOLVER_PRECISION=default`` to trade accuracy for the 2×.
    """
    from jax import lax

    return {
        "default": lax.Precision.DEFAULT,
        "float32": lax.Precision.HIGHEST,
        "highest": lax.Precision.HIGHEST,
    }[_SOLVER_PRECISION]


def sdot(a, b):
    """Solver-grade matmul: true-f32 MXU passes, f32 accumulation.  Use
    for every contraction whose result enters a linear solve (Gramians,
    AᵀB right-hand sides, covariances, EM sufficient statistics,
    LBFGS gradients)."""
    import jax.numpy as jnp

    return jnp.matmul(
        a, b, precision=solver_precision(), preferred_element_type=jnp.float32
    )


def fdtype(mode: str | None = None):
    """The featurize-matmul input dtype for ``mode`` (default: current).
    ``bf16_apply`` is a superset of the featurize policy, so it maps to
    bf16 here too."""
    m = matmul_mode() if mode is None else mode
    return jnp.bfloat16 if m in ("bf16", "bf16_apply") else jnp.float32


def fcast(*xs, mode: str | None = None):
    """Cast featurize-matmul inputs to the policy dtype.  Pair every use
    with ``preferred_element_type=jnp.float32`` so accumulation (and the
    result) stays f32."""
    dt = fdtype(mode)
    out = tuple(jnp.asarray(x).astype(dt) for x in xs)
    return out if len(out) > 1 else out[0]


# ------------------------------------------------------------------------
# Apply-side policy: the opt-in bf16 fast path for the forward /
# featurization contractions that the featurize policy deliberately
# leaves alone.  Active ONLY when the resolved mode is "bf16_apply"
# (on-TPU-gated above); in every other mode the helpers are identity
# wrappers around jnp.dot / jnp.einsum with f32 accumulation, emitting
# the exact graph the call sites emitted before the policy existed.


def apply_mode(mode: str | None = None) -> str:
    """Collapse the resolved policy to what the APPLY path cares about:
    'bf16_apply' when the apply policy is active, else 'f32'.  Ops whose
    only policy-sensitive contractions go through apply_dot/apply_einsum
    use this as their static jit key so a featurize-only 'bf16' flip
    does not force a pointless retrace of an identical program."""
    m = matmul_mode() if mode is None else mode
    return m if m == "bf16_apply" else "f32"


def adtype(mode: str | None = None):
    """Apply-policy contraction input dtype: bf16 iff active."""
    m = matmul_mode() if mode is None else mode
    return jnp.bfloat16 if m == "bf16_apply" else jnp.float32


def acast(*xs, mode: str | None = None):
    """Cast apply-policy contraction inputs (identity when inert).  Pair
    with ``preferred_element_type=jnp.float32`` like :func:`fcast`."""
    dt = adtype(mode)
    out = tuple(jnp.asarray(x).astype(dt) for x in xs)
    return out if len(out) > 1 else out[0]


def apply_dot(a, b, mode: str | None = None):
    """Apply-policy matmul: bf16 inputs (when active) with f32
    accumulation and f32 output.  Inert modes produce the exact
    ``jnp.dot(a, b, preferred_element_type=f32)`` the converted call
    sites used before — CPU meshes stay bit-identical by construction."""
    a, b = acast(a, b, mode=mode)
    return jnp.dot(a, b, preferred_element_type=jnp.float32)


def apply_einsum(spec: str, *operands, mode: str | None = None):
    """Apply-policy einsum: bf16 operands (when active), f32
    accumulation/output.  See :func:`apply_dot`."""
    ops = acast(*operands, mode=mode)
    if len(operands) == 1:
        ops = (ops,)
    return jnp.einsum(spec, *ops, preferred_element_type=jnp.float32)
