"""Hardened durable-state I/O: the survival side of the fault contract.

Every persistence path in keystone_tpu converges here (pipeline-prefix
saves in workflow/state.py, solver epoch checkpoints in models/lbfgs.py
and models/block_ls.py, block files in workflow/blockstore.py), so the
guarantees are uniform:

- **atomic publication**: tmp + fsync + ``os.replace`` — a crash mid-save
  never destroys the previous good file, and readers never observe a
  half-written one;
- **BLAKE2b sidecar checksums** (``<file>.b2``) verified on load — bit
  rot, torn writes, and injected corruption all surface as a typed
  :class:`CorruptStateError` instead of silently-wrong weights;
- **bounded retry with exponential backoff + jitter** for transient
  I/O (the role Spark task retry played for flaky executor storage);
- **rolling keep-N retention with last-good fallback**: ``save_npz``
  rotates the previous checkpoint to ``<file>.1`` (…``.N-1``) before
  publishing, and ``load_npz`` scans newest→oldest, skipping corrupt or
  unreadable candidates — a corrupt newest checkpoint degrades to the
  previous epoch, never to a crashed fit.

The injected counterpart lives in ``keystone_tpu.faults``: ``save_npz``
exposes the ``ckpt.save`` site (write + publish phases) and ``load_npz``
the ``ckpt.load`` site, so chaos plans can corrupt exactly what these
helpers must then survive.
"""

from __future__ import annotations

import hashlib
import logging
import os
import random
import time
from typing import Callable, Dict, Iterable, Optional, Tuple

import numpy as np

from keystone_tpu.faults import FaultInjected, fault_point
from keystone_tpu.obs import metrics

logger = logging.getLogger(__name__)

CHECKSUM_SUFFIX = ".b2"

#: exception types retried as transient by :func:`with_retries`
#: (FaultInjected subclasses OSError, so injected flakiness is absorbed
#: exactly like real flaky storage).
TRANSIENT = (OSError,)


class CorruptStateError(RuntimeError):
    """Durable state failed its integrity check (checksum mismatch,
    truncation, or an unreadable payload).  Deliberately NOT an
    ``OSError``: retrying a deterministic corruption is futile, so the
    retry layer must not absorb it — fallback/requarantine paths own
    it instead."""


# ------------------------------------------------------------- checksums


def compute_checksum(path: str, chunk_bytes: int = 1 << 20) -> str:
    """Streaming BLAKE2b-128 of a file's content."""
    h = hashlib.blake2b(digest_size=16)
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def checksum_path(path: str) -> str:
    return path + CHECKSUM_SUFFIX


def write_checksum(path: str, digest: Optional[str] = None) -> str:
    """Write ``<path>.b2`` (atomically) for the current content of
    ``path`` — or for a caller-supplied ``digest`` (publishers that
    hashed their own bytes before the rename, so the sidecar can never
    describe somebody else's payload); returns the digest."""
    import threading

    if digest is None:
        digest = compute_checksum(path)
    side = checksum_path(path)
    tmp = f"{side}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        f.write(digest + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, side)
    return digest


def verify_checksum(path: str, required: bool = False) -> bool:
    """Verify ``path`` against its sidecar.  Returns True on a verified
    match, False when no sidecar exists (legacy files pass unverified
    unless ``required``); raises :class:`CorruptStateError` on mismatch.
    """
    side = checksum_path(path)
    if not os.path.exists(side):
        if required:
            raise CorruptStateError(f"missing checksum sidecar for {path}")
        return False
    with open(side) as f:
        expected = f.read().strip()
    actual = compute_checksum(path)
    if actual != expected:
        metrics.inc("durable.corruption")
        raise CorruptStateError(
            f"checksum mismatch for {path}: content={actual[:12]}… "
            f"sidecar={expected[:12]}…"
        )
    return True


# --------------------------------------------------------- retry/backoff


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        logger.warning("bad %s=%r; using %d", name, os.environ.get(name), default)
        return default


def backoff_delays(
    retries: int,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    seed: Optional[int] = None,
) -> Iterable[float]:
    """Exponential backoff delays with multiplicative jitter.  A ``seed``
    makes the jitter deterministic (chaos-test replay); default jitter
    decorrelates a fleet of restarting workers."""
    rng = random.Random(seed)
    for attempt in range(retries):
        delay = min(max_delay, base_delay * (2.0**attempt))
        yield delay * (1.0 + jitter * rng.random())


def with_retries(
    fn: Callable,
    retries: Optional[int] = None,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    retry_on: Tuple = TRANSIENT,
    description: str = "",
    sleep: Callable[[float], None] = time.sleep,
    retry_if: Optional[Callable[[BaseException], bool]] = None,
):
    """Call ``fn()`` with up to ``retries`` bounded retries on transient
    errors.  ``retries=None`` resolves ``KEYSTONE_IO_RETRIES`` (default
    2) so every I/O path honors the knob without plumbing.  Exceptions
    outside ``retry_on`` — notably :class:`CorruptStateError` —
    propagate immediately.  ``retry_if``: an extra predicate a caught
    exception must ALSO satisfy to be retried — for callers whose
    transient/deterministic split is finer than exception types (e.g.
    ``multihost.initialize``, where only connection-shaped
    ``RuntimeError``s are worth the backoff budget)."""
    if retries is None:
        retries = max(0, _env_int("KEYSTONE_IO_RETRIES", 2))
    delays = iter(backoff_delays(retries, base_delay, max_delay))
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            if isinstance(e, CorruptStateError):
                raise
            if retry_if is not None and not retry_if(e):
                raise
            attempt += 1
            if attempt > retries:
                raise
            metrics.inc("durable.retries")
            delay = next(delays)
            logger.warning(
                "transient I/O failure%s (%s); retry %d/%d in %.2fs",
                f" in {description}" if description else "",
                e,
                attempt,
                retries,
                delay,
            )
            sleep(delay)


# -------------------------------------------------- atomic npz + rolling


def _fsync_dir(dirpath: str) -> None:
    """Best-effort directory fsync so the rename itself is durable."""
    try:
        fd = os.open(dirpath or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


#: serializes the payload-rename + sidecar-publish PAIR within this
#: process: a watchdog-abandoned checkpoint attempt racing its own
#: retry (utils/guard.run_with_deadline) must not interleave the two
#: renames — payload B with sidecar A would make the newest checkpoint
#: read as corrupt.  Cross-process writers to one path remain
#: last-writer-wins (unchanged; solver checkpoints are process-0-only).
import threading as _threading

_PUBLISH_LOCK = _threading.Lock()


def atomic_write(path: str, write_fn: Callable[[str], None]) -> None:
    """Publish a file atomically: ``write_fn(tmp)`` writes the payload,
    then fsync + rename + dir fsync + checksum sidecar.  The tmp name is
    per-pid AND per-thread so concurrent writers — other processes on a
    shared directory, or a watchdog-abandoned stage attempt racing its
    own retry (utils/guard.run_with_deadline) — never truncate each
    other mid-write.  The digest is computed from OUR tmp bytes before
    the rename and the rename+sidecar pair is published under a
    process-wide lock, so the sidecar always describes the payload that
    landed with it; publication stays last-writer-wins, which is
    idempotent for the stage-retry case because stages are pure
    functions of memoized inputs."""
    import threading

    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    write_fn(tmp)
    with open(tmp, "rb") as f:
        os.fsync(f.fileno())
    digest = compute_checksum(tmp)
    with _PUBLISH_LOCK:
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(os.path.abspath(path)))
        write_checksum(path, digest=digest)


def _rotated(path: str, i: int) -> str:
    return f"{path}.{i}"


def rotate(path: str, keep: int) -> None:
    """Shift ``path`` → ``path.1`` → … → ``path.keep-1`` (with sidecars),
    dropping the oldest.  Best-effort under concurrent writers: a
    rename that loses a race is skipped, never fatal — every individual
    publish stays atomic."""
    if keep <= 1:
        return
    for i in range(keep - 1, 0, -1):
        src = path if i == 1 else _rotated(path, i - 1)
        if not os.path.exists(src):
            continue
        try:
            os.replace(src, _rotated(path, i))
            if os.path.exists(checksum_path(src)):
                os.replace(checksum_path(src), checksum_path(_rotated(path, i)))
        except OSError:
            pass


def prune_rotated(path: str, keep: int) -> None:
    """Delete rotated copies beyond ``keep`` (retention shrink)."""
    i = max(1, keep)
    while True:
        cand = _rotated(path, i)
        if not os.path.exists(cand):
            break
        for p in (cand, checksum_path(cand)):
            try:
                os.remove(p)
            except OSError:
                pass
        i += 1


def save_npz(
    path: str,
    arrays: Dict[str, np.ndarray],
    keep: int = 2,
    retries: Optional[int] = None,
    fault_site: str = "ckpt.save",
) -> None:
    """Durably publish a dict of arrays as an ``.npz`` checkpoint.

    The previous file rotates to ``path.1`` (…``path.keep-1``) first, so
    the newest checkpoint getting corrupted still leaves a last-good
    fallback for :func:`load_npz`.  The write itself is atomic, retried
    on transient errors, and checksummed.  Fault sites: the ``write``
    phase fires inside the retry scope (a transient injected failure is
    absorbed); the ``publish`` phase fires after the sidecar lands, so
    ``corrupt``/``truncate`` actions damage exactly what a subsequent
    load must detect."""
    rotate(path, keep)
    prune_rotated(path, keep)

    def _write(tmp: str) -> None:
        fault_point(fault_site, path=tmp, phase="write")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())

    with_retries(
        lambda: atomic_write(path, _write),
        retries=retries,
        description=f"checkpoint save {os.path.basename(path)}",
    )
    fault_point(fault_site, path=path, phase="publish")


def load_npz(
    path: str,
    validate: Optional[Callable[[Dict[str, np.ndarray]], bool]] = None,
    fault_site: str = "ckpt.load",
) -> Optional[Tuple[Dict[str, np.ndarray], str]]:
    """Load the newest *valid* checkpoint among ``path``, ``path.1``, …

    Validity = checksum sidecar matches (when present), the npz parses,
    and ``validate(arrays)`` (when given) accepts it.  Invalid
    candidates are skipped with a warning — the resume scan degrades to
    the last good epoch instead of crashing the fit.  Returns
    ``(arrays, path_used)`` or None when no candidate survives.
    Transient read errors retry with backoff before the candidate is
    declared dead."""
    candidates = [path]
    i = 1
    while os.path.exists(_rotated(path, i)):
        candidates.append(_rotated(path, i))
        i += 1

    for cand in candidates:
        if not os.path.exists(cand):
            continue

        def _read(cand=cand):
            fault_point(fault_site, path=cand)
            verify_checksum(cand)
            with np.load(cand, allow_pickle=False) as z:
                return {k: np.asarray(z[k]) for k in z.files}

        try:
            arrays = with_retries(
                _read, description=f"checkpoint load {os.path.basename(cand)}"
            )
        except CorruptStateError as e:
            metrics.inc("durable.skipped_corrupt")
            logger.warning("skipping corrupt checkpoint %s: %s", cand, e)
            continue
        except Exception as e:
            metrics.inc("durable.skipped_unreadable")
            logger.warning("skipping unreadable checkpoint %s: %s", cand, e)
            continue
        if validate is not None:
            try:
                ok = bool(validate(arrays))
            except Exception as e:
                logger.warning("checkpoint %s failed validation: %s", cand, e)
                continue
            if not ok:
                logger.info("checkpoint %s rejected by validator", cand)
                continue
        if cand != path:
            metrics.inc("durable.fallback")
            logger.warning(
                "resumed from fallback checkpoint %s (newer candidates "
                "invalid)",
                cand,
            )
        return arrays, cand
    return None


def quarantine(path: str) -> Optional[str]:
    """Move a known-bad state file (and its sidecar) aside as
    ``<path>.corrupt`` so resume scans stop tripping over it; returns
    the new path (None when the rename failed)."""
    dest = path + ".corrupt"
    try:
        os.replace(path, dest)
    except OSError:
        return None
    side = checksum_path(path)
    if os.path.exists(side):
        try:
            os.replace(side, checksum_path(dest))
        except OSError:
            pass
    metrics.inc("durable.quarantined")
    logger.warning("quarantined corrupt state file %s -> %s", path, dest)
    return dest
