"""Matrix helpers (utils/MatrixUtils.scala).

The reference's ``rowsToMatrix`` is the per-partition batching primitive
every solver uses (stack an iterator of row vectors into one DenseMatrix so
work happens as BLAS gemm).  On TPU the data model is *already* batched —
a Dataset is a sharded (n, d) array — so these helpers exist mainly at
host/ingest boundaries and for API parity.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import jax.numpy as jnp
import numpy as np


def rows_to_matrix(rows: Iterable) -> jnp.ndarray:
    """Stack row vectors into an (n, d) matrix."""
    rows = list(rows)
    if not rows:
        return jnp.zeros((0, 0), dtype=jnp.float32)
    return jnp.stack([jnp.asarray(r) for r in rows], axis=0)


def matrix_to_rows(mat) -> list:
    """Inverse of :func:`rows_to_matrix` (utils/MatrixUtils.scala § matrixToRowArray)."""
    return [mat[i] for i in range(mat.shape[0])]


matrix_to_row_array = matrix_to_rows  # reference-named alias


def shuffle_rows(mat, seed: int = 0) -> jnp.ndarray:
    """Row permutation with a fixed seed (MatrixUtils.shuffleArray analogue)."""
    mat = jnp.asarray(mat)
    perm = np.random.default_rng(seed).permutation(mat.shape[0])
    return mat[jnp.asarray(perm)]


def block_ranges(dim: int, block_size: int) -> Sequence[tuple]:
    """[(start, end), ...] covering ``dim`` in blocks of ``block_size``.

    The feature-block decomposition used by the block solvers
    (nodes/util/VectorSplitter.scala).
    """
    return [(s, min(s + block_size, dim)) for s in range(0, dim, block_size)]
