"""Persistent XLA compilation cache.

The dominant cost of a cold pipeline run in this environment is XLA
compilation (the north-star ImageNet fit: ~60 s cold vs ~2 s warm on one
chip).  The reference amortizes its equivalent (JVM/JIT warmup, Spark
executor reuse) by keeping the cluster alive between jobs; the TPU-era
equivalent is JAX's persistent compilation cache, which persists compiled
executables across *processes* so the second `bin/run-pipeline.sh` of the
same pipeline skips compilation entirely (measured: 2.9 s → 0.24 s for a
representative program; the full ImageNet pipeline drops from ~60 s to
seconds).

Enabled by default for CLI/bench entry points; library users call
:func:`enable_compilation_cache` themselves.  Controlled by
``KEYSTONE_COMPILE_CACHE``: a directory path overrides the default
(``~/.cache/keystone_tpu/xla``); ``0``/``off``/``none`` disables.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_DISABLE_VALUES = ("0", "off", "none", "false")


def enable_compilation_cache(
    cache_dir: Optional[str] = None, min_compile_secs: float = 0.0
) -> Optional[str]:
    """Point jax at a persistent on-disk compilation cache.

    Returns the cache directory, or None when disabled via
    ``KEYSTONE_COMPILE_CACHE``.  Idempotent; safe to call before or after
    backend initialization (config is read at compile time).
    """
    env = os.environ.get("KEYSTONE_COMPILE_CACHE", "").strip()
    if env.lower() in _DISABLE_VALUES:
        return None
    d = cache_dir or env or os.path.join(
        os.path.expanduser("~"), ".cache", "keystone_tpu", "xla"
    )
    prev_dir = None
    dir_updated = False
    try:
        os.makedirs(d, exist_ok=True)
        import jax

        prev_dir = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", d)
        dir_updated = True
        # persist EVERYTHING (threshold 0): even sub-second eager-op
        # compiles pay a device-RPC round-trip per program in tunneled
        # environments, and dozens of them add tens of seconds
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_compile_secs)
        )
    except Exception as e:  # unwritable dir, ancient jax — run uncached
        if dir_updated:
            # roll back only what THIS call changed; a pre-existing cache
            # config (env var, prior enable) must survive our failure
            try:
                import jax

                jax.config.update("jax_compilation_cache_dir", prev_dir)
            except Exception:
                pass
        logger.warning("compilation cache unavailable (%s); continuing without", e)
        return None
    return d


def ensure_compilation_cache() -> Optional[str]:
    """Library-path auto-enable (the serve/``PipelineService`` entry
    points call this): honor an already-configured cache dir — a user
    who pointed ``jax.config.jax_compilation_cache_dir`` somewhere must
    not be clobbered — else apply :func:`enable_compilation_cache` with
    its ``KEYSTONE_COMPILE_CACHE`` env semantics (path overrides,
    ``0``/``off`` disables).  Returns the active cache dir or None."""
    env = os.environ.get("KEYSTONE_COMPILE_CACHE", "").strip()
    if env.lower() in _DISABLE_VALUES:
        return None
    try:
        import jax

        existing = jax.config.jax_compilation_cache_dir
    except Exception:
        existing = None
    if existing:
        return existing
    return enable_compilation_cache()


def cache_active() -> bool:
    """Is a persistent XLA compilation cache configured right now?
    (The serve prime path labels its timings
    ``serve.prime_seconds{source=cache}`` vs ``compile`` on this.)"""
    try:
        import jax

        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:
        return False
