"""Persistent XLA compilation cache.

The dominant cost of a cold pipeline run in this environment is XLA
compilation (the north-star ImageNet fit: ~60 s cold vs ~2 s warm on one
chip).  The reference amortizes its equivalent (JVM/JIT warmup, Spark
executor reuse) by keeping the cluster alive between jobs; the TPU-era
equivalent is JAX's persistent compilation cache, which persists compiled
executables across *processes* so the second `bin/run-pipeline.sh` of the
same pipeline skips compilation entirely (measured: 2.9 s → 0.24 s for a
representative program; the full ImageNet pipeline drops from ~60 s to
seconds).

Enabled by default for CLI/bench entry points; library users call
:func:`enable_compilation_cache` themselves.  Controlled by
``KEYSTONE_COMPILE_CACHE``: a directory path overrides the default
(``~/.cache/keystone_tpu/xla``); ``0``/``off``/``none`` disables.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_DISABLE_VALUES = ("0", "off", "none", "false")


def enable_compilation_cache(
    cache_dir: Optional[str] = None, min_compile_secs: float = 0.0
) -> Optional[str]:
    """Point jax at a persistent on-disk compilation cache.

    Returns the cache directory, or None when disabled via
    ``KEYSTONE_COMPILE_CACHE``.  Idempotent; safe to call before or after
    backend initialization (config is read at compile time).
    """
    env = os.environ.get("KEYSTONE_COMPILE_CACHE", "").strip()
    if env.lower() in _DISABLE_VALUES:
        return None
    d = cache_dir or env or os.path.join(
        os.path.expanduser("~"), ".cache", "keystone_tpu", "xla"
    )
    prev_dir = None
    dir_updated = False
    try:
        os.makedirs(d, exist_ok=True)
        import jax

        prev_dir = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", d)
        dir_updated = True
        if prev_dir and prev_dir != d:
            # jax lazily binds ONE cache object to the first dir it
            # initializes; without a reset, later dir changes silently
            # keep reading/writing the old directory (observed: a
            # second export in one process captured zero entries — they
            # landed in the first test's dir)
            try:
                from jax._src.compilation_cache import reset_cache

                reset_cache()
            except Exception:
                pass  # older jax: the single-dir behavior stands
        # persist EVERYTHING (threshold 0): even sub-second eager-op
        # compiles pay a device-RPC round-trip per program in tunneled
        # environments, and dozens of them add tens of seconds
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_compile_secs)
        )
    except Exception as e:  # unwritable dir, ancient jax — run uncached
        if dir_updated:
            # roll back only what THIS call changed; a pre-existing cache
            # config (env var, prior enable) must survive our failure
            try:
                import jax

                jax.config.update("jax_compilation_cache_dir", prev_dir)
            except Exception:
                pass
        logger.warning("compilation cache unavailable (%s); continuing without", e)
        return None
    return d


def ensure_compilation_cache() -> Optional[str]:
    """Library-path auto-enable (the serve/``PipelineService`` entry
    points call this): honor an already-configured cache dir — a user
    who pointed ``jax.config.jax_compilation_cache_dir`` somewhere must
    not be clobbered — else apply :func:`enable_compilation_cache` with
    its ``KEYSTONE_COMPILE_CACHE`` env semantics (path overrides,
    ``0``/``off`` disables).  Returns the active cache dir or None."""
    env = os.environ.get("KEYSTONE_COMPILE_CACHE", "").strip()
    if env.lower() in _DISABLE_VALUES:
        return None
    try:
        import jax

        existing = jax.config.jax_compilation_cache_dir
    except Exception:
        existing = None
    if existing:
        return existing
    return enable_compilation_cache()


def snapshot_cache_entries() -> Optional[set]:
    """The active cache dir's current file set (None: no active dir) —
    the 'before' side of :func:`collect_new_entries`."""
    try:
        import jax

        d = jax.config.jax_compilation_cache_dir
    except Exception:
        return None
    if not d or not os.path.isdir(d):
        return None
    return set(os.listdir(d))


def collect_new_entries(before: Optional[set]) -> dict:
    """Files the active cache dir gained since ``before`` was
    snapshotted, as ``{filename: bytes}`` — the export path captures
    the persistent-cache entries its backend compiles mint, so a
    freeze-artifact bundle can SHIP them (the artifact ladder's last
    cold rung: a fresh host's first deploy then skips even the backend
    compile of the deserialized module)."""
    if before is None:
        return {}
    import jax

    d = jax.config.jax_compilation_cache_dir
    if not d or not os.path.isdir(d):
        return {}
    out = {}
    for name in sorted(set(os.listdir(d)) - before):
        path = os.path.join(d, name)
        try:
            if os.path.isfile(path):
                with open(path, "rb") as f:
                    out[name] = f.read()
        except OSError:
            continue  # capture is best-effort; the entry just re-compiles
    return out


def seed_compile_cache(bundle: Optional[dict]) -> int:
    """Install an artifact bundle's shipped compile-cache entries into
    the active persistent cache dir (missing files only — an existing
    entry is never clobbered).  Returns how many files were written.
    Best-effort end to end: no active cache, no shipped entries, or an
    unwritable dir all degrade to plain compilation, never fail a
    deploy.  Counted as ``serve.cache_seeded``."""
    manifest = (bundle or {}).get("manifest") or {}
    blobs = (bundle or {}).get("blobs") or {}
    entries = {
        key: ent
        for key, ent in (manifest.get("entries") or {}).items()
        if ent.get("kind") == "compile_cache"
    }
    if not entries:
        return 0
    d = ensure_compilation_cache()
    if not d:
        return 0
    seeded = 0
    for key, ent in entries.items():
        data = blobs.get(key)
        name = ent.get("name")
        if data is None or not name or os.sep in str(name):
            continue
        path = os.path.join(d, str(name))
        if os.path.exists(path):
            continue
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
            seeded += 1
        except OSError as e:
            logger.warning("compile-cache seed of %s failed: %s", name, e)
    if seeded:
        from keystone_tpu.obs import metrics

        metrics.inc("serve.cache_seeded", seeded)
        logger.info(
            "seeded %d persistent-compile-cache entr%s from the artifact "
            "bundle",
            seeded,
            "y" if seeded == 1 else "ies",
        )
    return seeded


def cache_active() -> bool:
    """Is a persistent XLA compilation cache configured right now?
    (The serve prime path labels its timings
    ``serve.prime_seconds{source=cache}`` vs ``compile`` on this.)"""
    try:
        import jax

        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:
        return False
