"""Deadlines, watchdogs & circuit breakers: the time half of the fault
contract.

The retry machinery (``utils/durable.with_retries``, executor stage
retry, ``loaders/stream.resilient``) only fires when a site *raises* —
a stage, stream source, or coordinator that silently hangs stalls the
whole pipeline forever.  This module supplies the missing failure mode's
remedies, mirroring what Spark gave the reference via task timeouts and
speculative re-execution:

- :class:`Deadline` — an absolute wall-clock budget (``remaining()``,
  ``expired()``, ``child()`` sub-budgets that never outlive the parent);
- :func:`run_with_deadline` — a watchdog: the work runs on a worker
  thread, the caller waits at most the budget, and an overrun raises
  :class:`DeadlineExceeded` — deliberately an ``OSError``, so every
  existing transient-I/O retry path (stage retry, stream retry,
  ``with_retries``) treats a hang exactly like a flaky read.  The
  abandoned worker is signalled through a cooperative cancel flag
  (:func:`current_cancel` / :func:`interruptible_sleep`) so injected
  hangs (``keystone_tpu.faults`` ``hang`` action) unblock promptly
  instead of leaking hour-long sleeps;
- :class:`CircuitBreaker` — per-key closed → open (after N consecutive
  failures) → half-open (one probe after ``reset_timeout``) → closed,
  with every transition mirrored into ``obs.metrics``
  (``breaker.state{key=…}`` gauge, ``breaker.opens`` counter) and the
  run ledger (``breaker.transition`` events).  :func:`breaker` is the
  process-wide per-key registry the executor consults.

Default-off and inert: with no deadline configured
``run_with_deadline(fn, None)`` is one ``None`` check around ``fn()``
(no thread), and with no ``KEYSTONE_BREAKER_THRESHOLD`` the executor
never touches the registry.  Nothing here runs inside a traced program
— solver HLO stays byte-identical whatever the configuration (pinned by
tests/test_guard.py).

Environment knobs (all unset by default):

- ``KEYSTONE_STAGE_DEADLINE`` — seconds per executor stage attempt;
- ``KEYSTONE_BREAKER_THRESHOLD`` — consecutive stage failures before a
  node's breaker opens (unset = breakers off);
- ``KEYSTONE_BREAKER_RESET`` — seconds an open breaker waits before
  allowing a half-open probe (default 30);
- ``KEYSTONE_HANG_SECONDS`` — how long the injected ``hang`` action
  sleeps (default 3600 — far past any sane deadline; cancel-aware).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

from keystone_tpu.obs import ledger, metrics

logger = logging.getLogger(__name__)

ENV_STAGE_DEADLINE = "KEYSTONE_STAGE_DEADLINE"
ENV_BREAKER_THRESHOLD = "KEYSTONE_BREAKER_THRESHOLD"
ENV_BREAKER_RESET = "KEYSTONE_BREAKER_RESET"
ENV_HANG_SECONDS = "KEYSTONE_HANG_SECONDS"


class DeadlineExceeded(OSError):
    """A guarded operation overran its budget.  Subclasses ``OSError``
    on purpose (the :class:`~keystone_tpu.faults.FaultInjected`
    precedent): every retry path that absorbs transient I/O absorbs
    overruns identically, so a hang under a deadline becomes a retried —
    or gracefully degraded — operation instead of a stalled pipeline."""

    def __init__(self, site: str, budget_seconds: float):
        super().__init__(
            f"deadline exceeded at {site!r} after {budget_seconds:.3f}s"
        )
        self.site = site
        self.budget_seconds = budget_seconds
        #: the abandoned watchdog worker (None for a born-expired
        #: deadline).  Callers that want to RESUME the timed-out
        #: resource — the stream layer continuing a batch-resumable
        #: iterator — can briefly ``worker.join()`` to learn whether the
        #: resource has been vacated (cancel-aware work exits promptly)
        #: or is still occupied (use a fresh resource instead).
        self.worker: Optional[threading.Thread] = None


class CircuitOpenError(RuntimeError):
    """An operation was refused because its circuit breaker is open.
    Deliberately NOT an ``OSError``: immediately retrying a tripped
    breaker is futile by definition — recovery is time-based (the
    half-open probe) or structural (a fallback node)."""


def env_float(name: str) -> Optional[float]:
    """Positive float from the environment, or None — unset, empty,
    zero, negative, and non-numeric (warned) all mean "disabled".  The
    one parse every time-ish env knob shares (guard's own, and e.g.
    KEYSTONE_HEALTH_TIMEOUT in parallel/multihost.py), so "0 disables"
    holds uniformly."""
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        logger.warning("%s=%r is not a number; ignoring", name, raw)
        return None
    return v if v > 0 else None


class Deadline:
    """An absolute wall-clock budget (monotonic-clock based).

    ``Deadline.after(5.0)`` expires five seconds from now; ``child()``
    derives a sub-budget that can only tighten — a stage budget
    apportioned from a pipeline budget never outlives the pipeline."""

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = float(at)

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + float(seconds))

    def remaining(self) -> float:
        """Seconds left (negative when expired)."""
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def child(self, seconds: Optional[float] = None) -> "Deadline":
        """A sub-budget: at most ``seconds`` from now, never past this
        deadline.  ``seconds=None`` = inherit the parent's expiry."""
        if seconds is None:
            return Deadline(self.at)
        return Deadline(min(self.at, time.monotonic() + float(seconds)))

    def __repr__(self):
        return f"Deadline(remaining={self.remaining():.3f}s)"


class Heartbeat:
    """A renewable :class:`Deadline`: ``beat()`` pushes the expiry
    ``timeout`` seconds into the future, ``expired()`` reports whether
    the holder has gone silent past it.  The liveness half of the
    supervision contract — a worker thread beats once per loop
    iteration, and a supervisor that finds the heartbeat expired while
    work is outstanding knows the worker is wedged (stuck inside one
    operation), as opposed to dead (thread exited), which plain thread
    liveness already shows.  Thread-safe: one writer (the worker), any
    number of readers (the supervisor)."""

    __slots__ = ("timeout", "_deadline")

    def __init__(self, timeout: float):
        self.timeout = float(timeout)
        self._deadline = Deadline.after(self.timeout)

    def beat(self) -> None:
        # a fresh Deadline object per beat: assignment is atomic, so
        # readers never observe a half-updated expiry (no lock needed)
        self._deadline = Deadline.after(self.timeout)

    def expired(self) -> bool:
        return self._deadline.expired()

    def remaining(self) -> float:
        return self._deadline.remaining()

    def __repr__(self):
        return f"Heartbeat(timeout={self.timeout}, remaining={self.remaining():.3f}s)"


def as_deadline(value) -> Optional[Deadline]:
    """Coerce a user-facing budget (None, seconds, or a Deadline) into
    an Optional[Deadline] — the one conversion every ``deadline=`` API
    parameter shares."""
    if value is None or isinstance(value, Deadline):
        return value
    return Deadline.after(float(value))


# ------------------------------------------------- cooperative cancellation

_TLS = threading.local()


def current_cancel() -> Optional[threading.Event]:
    """The cancel flag of the enclosing :func:`run_with_deadline` scope
    (None outside one).  Long-running cooperative code — notably the
    injected ``hang``/``delay`` fault actions — polls this so abandoned
    watchdog workers unblock promptly after their caller gave up."""
    return getattr(_TLS, "cancel", None)


def interruptible_sleep(seconds: float) -> None:
    """``time.sleep`` that wakes early when the enclosing watchdog
    cancels.  Outside a deadline scope it is a plain sleep — which is
    exactly what a ``hang`` injection without a configured deadline
    should be: a real hang."""
    cancel = current_cancel()
    if cancel is None:
        time.sleep(seconds)
        return
    cancel.wait(timeout=seconds)


def run_with_deadline(
    fn: Callable,
    deadline: Optional[Deadline],
    site: str = "guard",
    **attrs,
):
    """Run ``fn()`` under a watchdog.

    ``deadline=None`` (the default everywhere) is the inert path: one
    ``None`` check, then ``fn()`` on the calling thread — no thread, no
    queue, no overhead.  With a deadline, ``fn`` runs on a daemon worker
    thread while the caller waits at most ``deadline.remaining()``; an
    overrun sets the worker's cooperative cancel flag, emits a
    ``deadline_exceeded`` ledger event plus a
    ``guard.deadline_exceeded{site=…}`` counter, and raises
    :class:`DeadlineExceeded` (an ``OSError`` — the caller's retry
    machinery owns what happens next).  The abandoned worker's eventual
    result is discarded.

    ``fn`` must not depend on running on the calling thread (the
    executor's stage bodies and stream fetches — the wired sites — do
    not).  A worker exception re-raises in the caller unchanged.

    Caveat — the watchdog ABANDONS, it cannot kill: a slow-but-alive
    ``fn`` keeps running (and keeps its side effects) concurrently with
    whatever the caller does next, until it finishes or polls the
    cancel flag.  The wired sites are safe by construction: stages are
    pure functions of memoized inputs and the durable layer's tmp names
    are per-thread with atomic last-writer-wins publication
    (``durable.atomic_write``), so a retried attempt racing its
    abandoned twin converges on the same bytes.  Two real limits
    remain: (1) budget deadlines well below a stage's honest runtime
    cause duplicated work, not faster runs; (2) on MULTI-HOST jobs a
    deadline must not be set below collective completion time — an
    abandoned attempt parked inside a collective desynchronizes peers
    (use :func:`~keystone_tpu.parallel.multihost.health_barrier` as the
    multi-host hang remedy instead)."""
    if deadline is None:
        return fn()
    budget = deadline.remaining()
    if budget <= 0.0:
        _deadline_exceeded(site, 0.0, **attrs)
    cancel = threading.Event()
    out: list = []
    err: list = []
    # the ledger's open-span stack is thread-local: carry the caller's
    # into the worker so spans/events emitted by fn (solver epochs,
    # blockstore spans) keep nesting under the caller's open span
    # exactly as they would without a watchdog
    obs_ctx = ledger.capture_context()

    def work():
        _TLS.cancel = cancel
        ledger.restore_context(obs_ctx)
        try:
            out.append(fn())
        except BaseException as e:  # surfaced to the caller below
            err.append(e)
        finally:
            _TLS.cancel = None

    t = threading.Thread(
        target=work, daemon=True, name=f"guard-watchdog:{site}"
    )
    t.start()
    t.join(budget)
    if t.is_alive():
        cancel.set()
        _deadline_exceeded(site, budget, worker=t, **attrs)
    if err:
        raise err[0]
    return out[0] if out else None


def _deadline_exceeded(
    site: str, budget: float, worker: Optional[threading.Thread] = None, **attrs
):
    metrics.inc("guard.deadline_exceeded", site=site)
    ledger.event(
        "deadline_exceeded", site=site, budget_seconds=budget, **attrs
    )
    logger.warning(
        "deadline exceeded at %s (budget %.3fs)%s",
        site,
        budget,
        f" {attrs}" if attrs else "",
    )
    exc = DeadlineExceeded(site, budget)
    exc.worker = worker
    raise exc


# ---------------------------------------------------------- circuit breaker

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"

#: numeric encoding for the ``breaker.state`` gauge (dashboards sort it)
_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}

DEFAULT_THRESHOLD = 3
DEFAULT_RESET_SECONDS = 30.0


class CircuitBreaker:
    """Closed → open after ``threshold`` CONSECUTIVE failures; open →
    half-open (exactly one probe admitted) once ``reset_timeout``
    elapses; the probe's success closes the breaker, its failure
    re-opens it and restarts the clock.

    Thread-safe.  Transitions mirror into the metrics registry
    (``breaker.state{key=…}`` gauge, ``breaker.opens{key=…}`` counter)
    and the run ledger (``breaker.transition`` events) — the chaos
    report and obs stack read breaker history from the same place as
    every other subsystem.  ``clock`` is injectable for tests."""

    def __init__(
        self,
        key: str,
        threshold: int = DEFAULT_THRESHOLD,
        reset_timeout: float = DEFAULT_RESET_SECONDS,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.key = key
        self.threshold = max(1, int(threshold))
        self.reset_timeout = float(reset_timeout)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self._probe_started: Optional[float] = None
        metrics.set_gauge("breaker.state", _STATE_GAUGE[CLOSED], key=key)

    # internal: must hold self._lock; returns the transition to report
    def _to(self, new_state: str) -> tuple:
        old, self._state = self._state, new_state
        if new_state == OPEN:
            self._opened_at = self._clock()
        self._probing = False
        self._probe_started = None
        return (old, new_state)

    def _resolve_locked(self) -> Optional[tuple]:
        """Time-based open→half-open promotion; returns a transition to
        report or None."""
        if (
            self._state == OPEN
            and self._opened_at is not None
            and self._clock() - self._opened_at >= self.reset_timeout
        ):
            return self._to(HALF_OPEN)
        if (
            self._state == HALF_OPEN
            and self._probing
            and self._probe_started is not None
            and self._clock() - self._probe_started >= self.reset_timeout
        ):
            # the admitted probe's outcome was never recorded (its
            # caller died, or its failure was deliberately not charged
            # — e.g. an executor attempt born after the run budget
            # blew): presume the probe lost and admit a fresh one, or
            # the breaker would wedge in half-open refusing everything
            # forever
            self._probing = False
            self._probe_started = None
        return None

    def _report(self, transition: Optional[tuple]) -> None:
        """Emit a transition OUTSIDE the breaker lock (the ledger and
        registry have their own locks; no nesting, no ordering hazard)."""
        if transition is None:
            return
        old, new = transition
        metrics.set_gauge("breaker.state", _STATE_GAUGE[new], key=self.key)
        if new == OPEN:
            metrics.inc("breaker.opens", key=self.key)
        ledger.event(
            "breaker.transition", key=self.key, from_state=old, to_state=new
        )
        logger.warning("breaker %r: %s -> %s", self.key, old, new)

    def state(self) -> str:
        with self._lock:
            tr = self._resolve_locked()
        self._report(tr)
        return self._state

    def allow(self) -> bool:
        """May the caller attempt the operation?  Closed: yes.  Open:
        no — until ``reset_timeout`` elapses, when exactly ONE caller is
        admitted as the half-open probe."""
        with self._lock:
            tr = self._resolve_locked()
            if self._state == CLOSED:
                allowed = True
            elif self._state == HALF_OPEN and not self._probing:
                self._probing = True
                self._probe_started = self._clock()
                allowed = True
            else:
                allowed = False
        self._report(tr)
        return allowed

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            tr = self._to(CLOSED) if self._state != CLOSED else None
        self._report(tr)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            tr = None
            if self._state == HALF_OPEN or (
                self._state == CLOSED and self._failures >= self.threshold
            ):
                tr = self._to(OPEN)
        self._report(tr)

    def seconds_until_probe(self) -> float:
        """Seconds until this breaker would admit traffic again: 0 for
        closed/half-open, else the remaining open window before the
        half-open probe.  Read-only — unlike :meth:`allow` it neither
        consumes the probe slot nor transitions state, so availability
        checks (a 503's derived ``Retry-After``) can poll it freely."""
        with self._lock:
            if self._state != OPEN or self._opened_at is None:
                return 0.0
            return max(
                0.0, self.reset_timeout - (self._clock() - self._opened_at)
            )


# process-wide per-key registry (the executor's per-node breakers;
# mirrors the faults/metrics module-global convention)
_BREAKERS: Dict[str, CircuitBreaker] = {}
_REG_LOCK = threading.Lock()

#: soft cap on registered breakers: object-identity-keyed breakers
#: (signatureless nodes in processes that build a fresh graph per
#: request) would otherwise accumulate forever.  At the cap, CLOSED
#: failure-free breakers are evicted oldest-first — open/half-open
#: state is load-bearing and is never dropped — along with their
#: ``breaker.state`` gauge series, so metrics cardinality stays
#: bounded too.
REGISTRY_CAP = 1024


def _evict_closed_locked() -> None:
    """Must hold _REG_LOCK.  Reading b._state without b's own lock is a
    benign heuristic here: a breaker mid-transition is simply kept."""
    for k in list(_BREAKERS):
        if len(_BREAKERS) <= REGISTRY_CAP // 2:
            break
        b = _BREAKERS[k]
        if b._state == CLOSED and b._failures == 0:
            del _BREAKERS[k]
            metrics.REGISTRY.remove_gauge("breaker.state", key=k)


def breaker(
    key: str,
    threshold: Optional[int] = None,
    reset_timeout: Optional[float] = None,
) -> CircuitBreaker:
    """The process-wide breaker for ``key``, created on first use.
    ``threshold``/``reset_timeout`` configure creation only — an
    existing breaker keeps its settings (per-key state must be stable
    across executors, which is the point of the registry)."""
    with _REG_LOCK:
        b = _BREAKERS.get(key)
        if b is None:
            if len(_BREAKERS) >= REGISTRY_CAP:
                _evict_closed_locked()
            b = _BREAKERS[key] = CircuitBreaker(
                key,
                threshold=threshold
                if threshold is not None
                else DEFAULT_THRESHOLD,
                reset_timeout=reset_timeout
                if reset_timeout is not None
                else breaker_reset_seconds(),
            )
        return b


def reset_breakers() -> None:
    """Drop every registered breaker (tests; a fresh chaos window),
    including their ``breaker.state`` gauge series."""
    with _REG_LOCK:
        for k in _BREAKERS:
            metrics.REGISTRY.remove_gauge("breaker.state", key=k)
        _BREAKERS.clear()


# ------------------------------------------------------------- env resolution


def stage_deadline_seconds() -> Optional[float]:
    """Per-stage attempt budget from ``KEYSTONE_STAGE_DEADLINE``
    (seconds); None = no per-stage deadline.  Resolved at executor
    construction, not import, so post-import env changes take effect."""
    return env_float(ENV_STAGE_DEADLINE)


def stage_breaker_threshold() -> Optional[int]:
    """Per-node breaker threshold from ``KEYSTONE_BREAKER_THRESHOLD``;
    None = breakers disabled (the executor never touches the registry)."""
    v = env_float(ENV_BREAKER_THRESHOLD)
    return None if v is None else max(1, int(v))


def breaker_reset_seconds() -> float:
    return env_float(ENV_BREAKER_RESET) or DEFAULT_RESET_SECONDS


def hang_seconds() -> float:
    """How long the injected ``hang`` fault action sleeps — far past any
    sane deadline by default, and cancel-aware either way."""
    return env_float(ENV_HANG_SECONDS) or 3600.0
